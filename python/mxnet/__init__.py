"""`mxnet` compatibility alias — makes reference scripts run unmodified.

Importing this package replaces the ``mxnet`` entry in ``sys.modules``
with :mod:`mxnet_tpu` and installs a meta-path finder so that every
``mxnet.X`` submodule import resolves to the already-loaded
``mxnet_tpu.X`` module object (never a second copy — a re-executed
module would duplicate registry state).

Usage: put this directory's parent on ``PYTHONPATH`` (it mirrors the
reference's ``python/mxnet`` layout) and run any reference script:

    PYTHONPATH=/root/repo/python:/root/repo python train_mnist.py ...

Reference: python/mxnet/__init__.py (the public namespace this forwards
to, re-exported by mxnet_tpu/__init__.py).
"""
import importlib
import importlib.abc
import importlib.util
import sys

_PKG = 'mxnet_tpu'


class _AliasFinder(importlib.abc.MetaPathFinder, importlib.abc.Loader):
    """Route ``mxnet[.sub]`` imports to the ``mxnet_tpu`` module objects."""

    def find_spec(self, fullname, path=None, target=None):
        if fullname == 'mxnet' or fullname.startswith('mxnet.'):
            # only claim names whose mxnet_tpu counterpart exists, so
            # find_spec-based feature probes stay truthful and missing
            # imports raise under the name the user asked for
            real_name = _PKG + fullname[len('mxnet'):]
            try:
                if importlib.util.find_spec(real_name) is None:
                    return None
            except (ImportError, ValueError):
                return None
            return importlib.util.spec_from_loader(fullname, self)
        return None

    def create_module(self, spec):
        real_name = _PKG + spec.name[len('mxnet'):]
        return importlib.import_module(real_name)

    def exec_module(self, module):
        pass  # the real module is already executed


def _install():
    real = importlib.import_module(_PKG)
    # alias already-imported submodules so `from mxnet.gluon import nn`
    # style imports hit the same objects
    for name, mod in list(sys.modules.items()):
        if name == _PKG or name.startswith(_PKG + '.'):
            sys.modules['mxnet' + name[len(_PKG):]] = mod
    if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
        sys.meta_path.insert(0, _AliasFinder())
    return real


_install()
