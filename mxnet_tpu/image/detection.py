"""Detection augmenters + iterator (reference python/mxnet/image/
detection.py: DetAugmenter classes and ImageDetIter).

Labels are [N, 5]: (cls, xmin, ymin, xmax, ymax) normalized to [0, 1],
-1 rows are padding — the MultiBoxTarget convention
(ops/contrib_ops.py)."""
import random

import numpy as np

from ..io import DataIter, DataBatch, DataDesc
from ..ndarray.ndarray import array as nd_array
from .image import (Augmenter, imresize, ImageIter, resize_short,
                    HorizontalFlipAug)

__all__ = ['DetAugmenter', 'DetHorizontalFlipAug', 'DetRandomCropAug',
           'DetBorderAug', 'CreateDetAugmenter', 'ImageDetIter']


class DetAugmenter:
    """Detection augmenter: __call__(src, label) -> (src, label)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if random.random() < self.p:
            src = src[:, ::-1]
            valid = label[:, 0] >= 0
            x0 = label[:, 1].copy()
            label[:, 1] = np.where(valid, 1.0 - label[:, 3], label[:, 1])
            label[:, 3] = np.where(valid, 1.0 - x0, label[:, 3])
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping boxes with center inside the crop
    (reference detection.py DetRandomCropAug, simplified)."""

    def __init__(self, min_scale=0.5, max_trials=10):
        self.min_scale = min_scale
        self.max_trials = max_trials

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_trials):
            s = random.uniform(self.min_scale, 1.0)
            cw, ch = int(w * s), int(h * s)
            x0 = random.randint(0, w - cw)
            y0 = random.randint(0, h - ch)
            nx0, ny0 = x0 / w, y0 / h
            valid = label[:, 0] >= 0
            cx = (label[:, 1] + label[:, 3]) / 2
            cy = (label[:, 2] + label[:, 4]) / 2
            keep = valid & (cx > nx0) & (cx < nx0 + s) & \
                (cy > ny0) & (cy < ny0 + s)
            if not keep.any():
                continue
            out = label.copy()
            out[~keep] = -1
            for col, off, scale in ((1, nx0, s), (3, nx0, s),
                                    (2, ny0, s), (4, ny0, s)):
                out[keep, col] = np.clip((out[keep, col] - off) / scale, 0, 1)
            return src[y0:y0 + ch, x0:x0 + cw], out
        return src, label


class DetBorderAug(DetAugmenter):
    """Pad to square with value fill, rescaling boxes."""

    def __init__(self, fill=127):
        self.fill = fill

    def __call__(self, src, label):
        h, w = src.shape[:2]
        side = max(h, w)
        out = np.full((side, side, src.shape[2]), self.fill, src.dtype)
        out[:h, :w] = src
        valid = label[:, 0] >= 0
        label[valid, 1] *= w / side
        label[valid, 3] *= w / side
        label[valid, 2] *= h / side
        label[valid, 4] *= h / side
        return out, label


def CreateDetAugmenter(data_shape, rand_crop=0, rand_mirror=False,
                       rand_pad=0, **kwargs):
    """Reference detection.py CreateDetAugmenter (core subset)."""
    augs = []
    if rand_pad:
        augs.append(DetBorderAug())
    if rand_crop:
        augs.append(DetRandomCropAug())
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    return augs


class ImageDetIter(ImageIter):
    """Detection iterator: batches (data, [B, max_objs, 5] labels)
    (reference detection.py ImageDetIter / src/io/
    iter_image_det_recordio.cc)."""

    def __init__(self, batch_size, data_shape, images, labels,
                 aug_list=None, data_name='data', label_name='label',
                 shuffle=False, **kwargs):
        # images: [N, H, W, C] float; labels: [N, max_objs, 5]
        self._images = images
        self._labels = labels
        DataIter.__init__(self, batch_size)
        self.data_shape = tuple(data_shape)
        self.shuffle = shuffle
        self.data_name = data_name
        self.label_name = label_name
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **kwargs)
        self._order = list(range(len(images)))
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size,) + self._labels.shape[1:])]

    def reset(self):
        if self.shuffle:
            random.shuffle(self._order)
        self._cursor = 0

    def next(self):
        if self._cursor + self.batch_size > len(self._order):
            raise StopIteration
        c, h, w = self.data_shape
        data = np.empty((self.batch_size, c, h, w), np.float32)
        label = np.empty((self.batch_size,) + self._labels.shape[1:],
                         np.float32)
        for i in range(self.batch_size):
            j = self._order[self._cursor + i]
            img = np.asarray(self._images[j], np.float32)
            lab = np.array(self._labels[j], np.float32)
            for aug in self.auglist:
                img, lab = aug(img, lab)
            if img.shape[:2] != (h, w):
                img = imresize(img, w, h)
            data[i] = img.transpose(2, 0, 1)[:c]
            label[i] = lab
        self._cursor += self.batch_size
        return DataBatch(data=[nd_array(data)], label=[nd_array(label)],
                         pad=0, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
