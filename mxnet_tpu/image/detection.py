"""Detection augmenters + iterator (reference python/mxnet/image/
detection.py: DetAugmenter classes and ImageDetIter).

Labels are [N, 5]: (cls, xmin, ymin, xmax, ymax) normalized to [0, 1],
-1 rows are padding — the MultiBoxTarget convention
(ops/contrib_ops.py)."""
import numpy as np

from .. import random as _random

from ..io import DataIter, DataBatch, DataDesc
from ..ndarray.ndarray import array as nd_array
from .image import (Augmenter, imresize, ImageIter, resize_short,
                    HorizontalFlipAug)

# framework-private stdlib-style stream: mx.random.seed controls it,
# user-global `random` state is untouched
random = _random.host_pyrng()

__all__ = ['DetAugmenter', 'DetBorrowAug', 'DetRandomSelectAug',
           'DetHorizontalFlipAug', 'DetRandomCropAug', 'DetRandomPadAug',
           'DetBorderAug', 'CreateDetAugmenter', 'ImageDetIter']


class DetAugmenter:
    """Detection augmenter: __call__(src, label) -> (src, label)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a plain (image-only) Augmenter for detection pipelines —
    reference detection.py:63 (color jitter etc. don't move boxes)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly apply one of the given augmenters, or none —
    reference detection.py:88 (skip_prob gates the whole group)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if random.random() < self.skip_prob or not self.aug_list:
            return src, label
        return random.choice(self.aug_list)(src, label)


class DetRandomPadAug(DetAugmenter):
    """Random expansion pad: place the image inside a larger fill canvas
    and rescale boxes — reference detection.py:323."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            ratio = random.uniform(*self.aspect_ratio_range)
            area = random.uniform(*self.area_range)
            if area < 1.0:
                continue
            nh = int(round(np.sqrt(area * h * w / ratio)))
            nw = int(round(nh * ratio))
            if nh < h or nw < w:
                continue
            y0 = random.randint(0, nh - h)
            x0 = random.randint(0, nw - w)
            canvas = np.empty((nh, nw, src.shape[2]), src.dtype)
            canvas[:] = np.asarray(self.pad_val, src.dtype)[:src.shape[2]]
            canvas[y0:y0 + h, x0:x0 + w] = src
            out = label.copy()
            valid = out[:, 0] >= 0
            out[valid, 1] = (out[valid, 1] * w + x0) / nw
            out[valid, 3] = (out[valid, 3] * w + x0) / nw
            out[valid, 2] = (out[valid, 2] * h + y0) / nh
            out[valid, 4] = (out[valid, 4] * h + y0) / nh
            return canvas, out
        return src, label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if random.random() < self.p:
            src = src[:, ::-1]
            valid = label[:, 0] >= 0
            x0 = label[:, 1].copy()
            label[:, 1] = np.where(valid, 1.0 - label[:, 3], label[:, 1])
            label[:, 3] = np.where(valid, 1.0 - x0, label[:, 3])
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping boxes with center inside the crop
    (reference detection.py DetRandomCropAug, simplified)."""

    def __init__(self, min_scale=0.5, max_trials=10):
        self.min_scale = min_scale
        self.max_trials = max_trials

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_trials):
            s = random.uniform(self.min_scale, 1.0)
            cw, ch = int(w * s), int(h * s)
            x0 = random.randint(0, w - cw)
            y0 = random.randint(0, h - ch)
            nx0, ny0 = x0 / w, y0 / h
            valid = label[:, 0] >= 0
            cx = (label[:, 1] + label[:, 3]) / 2
            cy = (label[:, 2] + label[:, 4]) / 2
            keep = valid & (cx > nx0) & (cx < nx0 + s) & \
                (cy > ny0) & (cy < ny0 + s)
            if not keep.any():
                continue
            out = label.copy()
            out[~keep] = -1
            for col, off, scale in ((1, nx0, s), (3, nx0, s),
                                    (2, ny0, s), (4, ny0, s)):
                out[keep, col] = np.clip((out[keep, col] - off) / scale, 0, 1)
            return src[y0:y0 + ch, x0:x0 + cw], out
        return src, label


class DetBorderAug(DetAugmenter):
    """Pad to square with value fill, rescaling boxes."""

    def __init__(self, fill=127):
        self.fill = fill

    def __call__(self, src, label):
        h, w = src.shape[:2]
        side = max(h, w)
        out = np.full((side, side, src.shape[2]), self.fill, src.dtype)
        out[:h, :w] = src
        valid = label[:, 0] >= 0
        label[valid, 1] *= w / side
        label[valid, 3] *= w / side
        label[valid, 2] *= h / side
        label[valid, 4] *= h / side
        return out, label


def CreateDetAugmenter(data_shape, rand_crop=0, rand_mirror=False,
                       rand_pad=0, rand_gray=0, brightness=0, contrast=0,
                       saturation=0, hue=0, pca_noise=0, **kwargs):
    """Reference detection.py:482 CreateDetAugmenter — color transforms
    borrowed from the classification set, geometric ones box-aware,
    rand_crop/rand_pad are application probabilities."""
    from .image import (BrightnessJitterAug, ContrastJitterAug,
                        SaturationJitterAug, HueJitterAug, LightingAug,
                        RandomGrayAug, IMAGENET_PCA_EIGVAL,
                        IMAGENET_PCA_EIGVEC)
    augs = []
    jitters = []
    if brightness:
        jitters.append(BrightnessJitterAug(brightness))
    if contrast:
        jitters.append(ContrastJitterAug(contrast))
    if saturation:
        jitters.append(SaturationJitterAug(saturation))
    if hue:
        jitters.append(HueJitterAug(hue))
    for j in jitters:
        augs.append(DetBorrowAug(j))
    if pca_noise > 0:
        augs.append(DetBorrowAug(LightingAug(
            pca_noise, IMAGENET_PCA_EIGVAL, IMAGENET_PCA_EIGVEC)))
    if rand_gray > 0:
        augs.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if rand_pad > 0:
        augs.append(DetRandomSelectAug([DetRandomPadAug()],
                                       skip_prob=1 - rand_pad))
    if rand_crop > 0:
        augs.append(DetRandomSelectAug([DetRandomCropAug()],
                                       skip_prob=1 - rand_crop))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    return augs


class ImageDetIter(ImageIter):
    """Detection iterator: batches (data, [B, max_objs, 5] labels)
    (reference detection.py ImageDetIter / src/io/
    iter_image_det_recordio.cc)."""

    def __init__(self, batch_size, data_shape, images, labels,
                 aug_list=None, data_name='data', label_name='label',
                 shuffle=False, **kwargs):
        # images: [N, H, W, C] float; labels: [N, max_objs, 5]
        self._images = images
        self._labels = labels
        DataIter.__init__(self, batch_size)
        self.data_shape = tuple(data_shape)
        self.shuffle = shuffle
        self.data_name = data_name
        self.label_name = label_name
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **kwargs)
        self._order = list(range(len(images)))
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size,) + self._labels.shape[1:])]

    def reset(self):
        if self.shuffle:
            random.shuffle(self._order)
        self._cursor = 0

    def next(self):
        if self._cursor + self.batch_size > len(self._order):
            raise StopIteration
        c, h, w = self.data_shape
        data = np.empty((self.batch_size, c, h, w), np.float32)
        label = np.empty((self.batch_size,) + self._labels.shape[1:],
                         np.float32)
        for i in range(self.batch_size):
            j = self._order[self._cursor + i]
            img = np.asarray(self._images[j], np.float32)
            lab = np.array(self._labels[j], np.float32)
            for aug in self.auglist:
                img, lab = aug(img, lab)
            if img.shape[:2] != (h, w):
                img = imresize(img, w, h)
            data[i] = img.transpose(2, 0, 1)[:c]
            label[i] = lab
        self._cursor += self.batch_size
        return DataBatch(data=[nd_array(data)], label=[nd_array(label)],
                         pad=0, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one augmenter to apply, or skip with
    ``skip_prob`` (reference detection.py DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if random.random() < self.skip_prob or not self.aug_list:
            return src, label
        return random.choice(self.aug_list)(src, label)


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """Multiple random-crop augmenters, one chosen per sample
    (reference detection.py:417). List-valued parameters are aligned
    pairwise; scalar parameters broadcast. Each entry maps onto this
    module's simplified DetRandomCropAug (area_range -> crop scale,
    max_attempts -> trials); coverage thresholds are handled by the
    center-in-crop keep rule."""
    def listify(p):
        return p if isinstance(p, list) else [p]

    params = [listify(min_object_covered), listify(aspect_ratio_range),
              listify(area_range), listify(min_eject_coverage),
              listify(max_attempts)]
    num = max(len(p) for p in params)
    for i, p in enumerate(params):
        if len(p) != num:
            assert len(p) == 1, 'parameter lists must align or be scalar'
            params[i] = p * num
    augs = []
    for _, _, area, _, attempts in zip(*params):
        lo = float(area[0]) if isinstance(area, (tuple, list)) else 0.05
        augs.append(DetRandomCropAug(min_scale=max(lo, 0.05) ** 0.5,
                                     max_trials=int(attempts)))
    return DetRandomSelectAug(augs, skip_prob=skip_prob)
