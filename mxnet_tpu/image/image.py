"""Image pipeline — pure-python/numpy ImageIter + Augmenter classes.

Reference: python/mxnet/image/image.py:975 (ImageIter with Augmenter
pipeline, :482-871 augmenter classes) and the OpenCV-backed src/io image
ops. Here decode/resize run on numpy (bilinear; pillow when available
for JPEG), augmentation composes the same Augmenter objects, and batches
come out as NDArrays in NCHW. Heavy lifting (normalize etc.) stays in
numpy to keep the TPU free for the training step; the iterator plugs
into PrefetchingIter (io.py) for engine-backed double buffering.

Images are HWC float32 throughout augmentation (the reference's
convention), transposed to CHW at batching.
"""
import logging
import os

import numpy as np

from ..io import DataIter, DataBatch, DataDesc
from .. import random as _random
from ..ndarray.ndarray import NDArray, array as nd_array
from .. import recordio

# framework-private stdlib-style stream: mx.random.seed controls it,
# user-global `random` state is untouched
random = _random.host_pyrng()

__all__ = ['ImageIter', 'Augmenter', 'CreateAugmenter']


def imdecode(buf, to_rgb=True, flag=1):
    """Decode an image buffer. JPEG/PNG need pillow; raw numpy buffers
    (pack_img '.raw' format) decode natively (reference mx.image.imdecode
    backed by src/io/image_io.cc)."""
    try:
        from PIL import Image
        import io as _io
        img = np.asarray(Image.open(_io.BytesIO(buf)).convert('RGB'))
        return img.astype(np.float32)
    except Exception:
        arr = np.frombuffer(buf, dtype=np.uint8)
        side = int(round((arr.size // 3) ** 0.5))
        if side * side * 3 == arr.size:
            return arr.reshape(side, side, 3).astype(np.float32)
        raise ValueError('cannot decode image buffer (pillow unavailable '
                         'and not a square raw buffer)')


def imresize(src, w, h, interp=1):
    """Bilinear resize HWC numpy image (reference mx.image.imresize)."""
    sh, sw = src.shape[:2]
    if (sh, sw) == (h, w):
        return src
    ys = (np.arange(h) + 0.5) * sh / h - 0.5
    xs = (np.arange(w) + 0.5) * sw / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, sh - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, sw - 1)
    y1 = np.clip(y0 + 1, 0, sh - 1)
    x1 = np.clip(x0 + 1, 0, sw - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    img = src.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def copyMakeBorder(src, top, bot, left, right, type=0, values=0.0):  # noqa: A002
    """Pad an HWC image with a border (reference _cvcopyMakeBorder op,
    src/io/image_io.cc; keyword names match the reference signature).
    ``type`` follows the cv2 codes: 0 constant, 1 replicate edge,
    2 reflect (edge pixel doubled), 3 wrap, 4 reflect_101 (edge pixel
    not doubled)."""
    pad = ((top, bot), (left, right)) + ((0, 0),) * (src.ndim - 2)
    modes = {1: 'edge', 2: 'symmetric', 3: 'wrap', 4: 'reflect'}
    if type == 0:
        return np.pad(src, pad, mode='constant', constant_values=values)
    if type not in modes:
        raise ValueError('unsupported border type %r' % (type,))
    return np.pad(src, pad, mode=modes[type])


def resize_short(src, size, interp=1):
    """Resize so the shorter side equals size (reference image.py:90)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=1):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = random.randint(0, max(0, w - new_w))
    y0 = random.randint(0, max(0, h - new_h))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=1):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src - mean
    if std is not None:
        src = src / std
    return src


def imread(filename, to_rgb=True, flag=1):
    """Read an image file to an HWC uint8 array (reference image.py:44,
    cv2.imread there; PIL here)."""
    from PIL import Image
    img = Image.open(filename)
    img = img.convert('RGB' if flag else 'L')
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if flag and not to_rgb:
        arr = arr[:, :, ::-1]  # BGR, the reference's cv2 default
    return arr


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area + aspect-ratio crop (reference image.py:435); falls
    back to center_crop after 10 failed draws."""
    h, w = src.shape[0], src.shape[1]
    area = h * w
    for _ in range(10):
        target_area = random.uniform(min_area, 1.0) * area
        new_ratio = random.uniform(*ratio)
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if random.random() < 0.5:
            new_h, new_w = new_w, new_h
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def scale_down(src_size, size):
    """Scale size down to fit within src_size keeping the ratio."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = w * sh // h, sh
    if sw < w:
        w, h = sw, h * sw // w
    return w, h


class Augmenter:
    """Image augmenter base (reference image.py:482)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __call__(self, src):
        return src.astype(np.float32)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.contrast, self.contrast)
        gray = (src * self.coef).sum() * 3.0 / src.size
        return src * alpha + gray * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.saturation, self.saturation)
        gray = (src * self.coef).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class HueJitterAug(Augmenter):
    """Reference image.py:706 — rotate hue in YIQ space."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], np.float32)

    def __call__(self, src):
        alpha = random.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], np.float32)
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        return np.asarray(src, np.float32) @ t


# ImageNet RGB PCA decomposition (reference image.py:934, AlexNet lighting)
IMAGENET_PCA_EIGVAL = np.array([55.46, 4.794, 1.148])
IMAGENET_PCA_EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                                [-0.5808, -0.0045, -0.8140],
                                [-0.5836, -0.6948, 0.4203]])


class LightingAug(Augmenter):
    """Reference image.py:763 — AlexNet-style PCA lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = _random.host_rng().normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return np.asarray(src, np.float32) + rgb


class RandomGrayAug(Augmenter):
    """Reference image.py:809 — randomly convert to 3-channel gray."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = np.array([[0.21, 0.21, 0.21],
                             [0.72, 0.72, 0.72],
                             [0.07, 0.07, 0.07]], np.float32)

    def __call__(self, src):
        if random.random() < self.p:
            src = np.asarray(src, np.float32) @ self.mat
        return src


class RandomSizedCropAug(Augmenter):
    """Reference image.py:569 — random area + aspect-ratio crop."""

    def __init__(self, size, min_area, ratio, interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size = size
        self.min_area = min_area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.min_area, self.ratio,
                                self.interp)[0]


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = np.asarray(mean, np.float32) if mean is not None else None
        self.std = np.asarray(std, np.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean if self.mean is not None else 0,
                               self.std)


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        random.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ColorJitterAug(RandomOrderAug):
    """Reference image.py:740 — brightness/contrast/saturation in random
    order."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference image.py:861)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0,
                                                            4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    jitters = []
    if brightness:
        jitters.append(BrightnessJitterAug(brightness))
    if contrast:
        jitters.append(ContrastJitterAug(contrast))
    if saturation:
        jitters.append(SaturationJitterAug(saturation))
    if jitters:
        auglist.append(RandomOrderAug(jitters))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(pca_noise, IMAGENET_PCA_EIGVAL,
                                   IMAGENET_PCA_EIGVEC))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    if mean is not None and np.any(np.asarray(mean) > 0):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Pure-python image iterator over .rec or an image list
    (reference image.py:975 ImageIter).

    >>> it = ImageIter(32, (3, 224, 224), path_imgrec='train.rec',
    ...                rand_crop=True, rand_mirror=True)
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root='',
                 shuffle=False, aug_list=None, imglist=None,
                 data_name='data', label_name='softmax_label', **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or imglist is not None, \
            'one of path_imgrec / path_imglist / imglist is required'
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self._records = []  # (label, raw-buffer or path)

        if path_imgrec:
            rec = recordio.MXRecordIO(path_imgrec, 'r')
            while True:
                item = rec.read()
                if item is None:
                    break
                header, buf = recordio.unpack(item)
                self._records.append((np.float32(header.label), buf))
            rec.close()
        else:
            entries = imglist
            if path_imglist:
                entries = []
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split('\t')
                        entries.append([float(x) for x in parts[1:-1]] +
                                       [parts[-1]])
            for e in entries:
                label, fname = (np.float32(e[0]) if len(e) == 2
                                else np.asarray(e[:-1], np.float32)), e[-1]
                self._records.append((label, os.path.join(path_root, fname)))

        if aug_list is None:
            aug_list = CreateAugmenter(data_shape, **kwargs)
        self.auglist = aug_list
        self.data_name = data_name
        self.label_name = label_name
        self._order = list(range(len(self._records)))
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, (self.batch_size,))]

    def reset(self):
        if self.shuffle:
            random.shuffle(self._order)
        self._cursor = 0

    def _load(self, rec):
        label, src = rec
        if isinstance(src, bytes):
            img = imdecode(src)
        else:
            img = imdecode(open(src, 'rb').read())
        return label, img

    def next(self):
        if self._cursor + self.batch_size > len(self._records):
            raise StopIteration
        data = np.empty((self.batch_size,) + self.data_shape, np.float32)
        label = np.empty((self.batch_size,), np.float32)
        for i in range(self.batch_size):
            lab, img = self._load(
                self._records[self._order[self._cursor + i]])
            for aug in self.auglist:
                img = aug(img)
            c, h, w = self.data_shape
            if img.shape[:2] != (h, w):
                img = imresize(img, w, h)
            data[i] = img.transpose(2, 0, 1)[:c]
            label[i] = np.float32(lab) if np.ndim(lab) == 0 else lab[0]
        self._cursor += self.batch_size
        return DataBatch(data=[nd_array(data)], label=[nd_array(label)],
                         pad=0, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
