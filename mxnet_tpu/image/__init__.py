from .image import (Augmenter, ResizeAug, ForceResizeAug, RandomCropAug,
                    CenterCropAug, HorizontalFlipAug, CastAug,
                    ColorNormalizeAug, BrightnessJitterAug,
                    ContrastJitterAug, SaturationJitterAug, RandomOrderAug,
                    CreateAugmenter, ImageIter, imresize, imdecode,
                    resize_short, fixed_crop, random_crop, center_crop,
                    color_normalize, scale_down)
from . import detection  # noqa: F401

__all__ = ['Augmenter', 'ResizeAug', 'ForceResizeAug', 'RandomCropAug',
           'CenterCropAug', 'HorizontalFlipAug', 'CastAug',
           'ColorNormalizeAug', 'BrightnessJitterAug', 'ContrastJitterAug',
           'SaturationJitterAug', 'RandomOrderAug', 'CreateAugmenter',
           'ImageIter', 'imresize', 'imdecode', 'resize_short', 'fixed_crop',
           'random_crop', 'center_crop', 'color_normalize', 'scale_down',
           'detection']
