from .image import (Augmenter, ResizeAug, ForceResizeAug, RandomCropAug,
                    RandomSizedCropAug, CenterCropAug, HorizontalFlipAug,
                    CastAug, ColorNormalizeAug, BrightnessJitterAug,
                    ContrastJitterAug, SaturationJitterAug, HueJitterAug,
                    ColorJitterAug, LightingAug, RandomGrayAug,
                    RandomOrderAug, CreateAugmenter, ImageIter, imread,
                    imresize, imdecode, resize_short, fixed_crop,
                    random_crop, random_size_crop, center_crop,
                    color_normalize, scale_down, copyMakeBorder)
from . import detection  # noqa: F401
from .detection import (DetAugmenter, DetBorrowAug, DetRandomSelectAug,
                        DetHorizontalFlipAug, DetRandomCropAug,
                        DetRandomPadAug, CreateDetAugmenter, ImageDetIter)

__all__ = ['Augmenter', 'ResizeAug', 'ForceResizeAug', 'RandomCropAug',
           'RandomSizedCropAug', 'CenterCropAug', 'HorizontalFlipAug',
           'CastAug', 'ColorNormalizeAug', 'BrightnessJitterAug',
           'ContrastJitterAug', 'SaturationJitterAug', 'HueJitterAug',
           'ColorJitterAug', 'LightingAug', 'RandomGrayAug',
           'RandomOrderAug', 'CreateAugmenter', 'ImageIter', 'imread',
           'imresize', 'imdecode', 'resize_short', 'fixed_crop',
           'random_crop', 'random_size_crop', 'center_crop',
           'color_normalize', 'scale_down', 'copyMakeBorder',
           'detection', 'DetAugmenter',
           'DetBorrowAug', 'DetRandomSelectAug', 'DetHorizontalFlipAug',
           'DetRandomCropAug', 'DetRandomPadAug', 'CreateDetAugmenter',
           'ImageDetIter']
