"""Gluon vision datasets.

Reference: python/mxnet/gluon/data/vision.py — MNIST, FashionMNIST, CIFAR10.
Reads the standard on-disk formats when present; falls back to the same
hermetic synthetic generator as io.MNISTIter so training tests run with
zero network egress.
"""
import os
import gzip
import struct

import numpy as np

from ... import ndarray as nd
from ...io import synthetic_mnist
from .dataset import Dataset, RecordFileDataset

__all__ = ['MNIST', 'FashionMNIST', 'CIFAR10']


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError()


class MNIST(_DownloadedDataset):
    """Reference vision.py:33."""

    _base = 'train'

    def _get_data(self):
        if self._train:
            data_file = os.path.join(self._root, 'train-images-idx3-ubyte.gz')
            label_file = os.path.join(self._root, 'train-labels-idx1-ubyte.gz')
        else:
            data_file = os.path.join(self._root, 't10k-images-idx3-ubyte.gz')
            label_file = os.path.join(self._root, 't10k-labels-idx1-ubyte.gz')
        if os.path.exists(data_file) and os.path.exists(label_file):
            with gzip.open(label_file, 'rb') as fin:
                struct.unpack('>II', fin.read(8))
                label = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.int32)
            with gzip.open(data_file, 'rb') as fin:
                struct.unpack('>IIII', fin.read(16))
                data = np.frombuffer(fin.read(), dtype=np.uint8)
                data = data.reshape(len(label), 28, 28, 1)
        else:
            imgs, labels = synthetic_mnist(6000 if self._train else 1000,
                                           seed=0 if self._train else 1)
            data = (imgs * 255).astype(np.uint8).reshape(-1, 28, 28, 1)
            label = labels.astype(np.int32)
        self._data = [nd.array(x, dtype=np.uint8) for x in data]
        self._label = label

    def __init__(self, root='~/.mxnet/datasets/mnist', train=True,
                 transform=None):
        super().__init__(root, train, transform)


class FashionMNIST(MNIST):
    def __init__(self, root='~/.mxnet/datasets/fashion-mnist', train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """Reference vision.py:83."""

    def __init__(self, root='~/.mxnet/datasets/cifar10', train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        with open(filename, 'rb') as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(-1, 3072 + 1)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(np.int32)

    def _get_data(self):
        if self._train:
            files = [os.path.join(self._root, 'data_batch_%d.bin' % i)
                     for i in range(1, 6)]
        else:
            files = [os.path.join(self._root, 'test_batch.bin')]
        if all(os.path.exists(f) for f in files):
            data, label = zip(*(self._read_batch(f) for f in files))
            data = np.concatenate(data)
            label = np.concatenate(label)
        else:
            protos = np.random.RandomState(99).rand(10, 32, 32, 3).astype(np.float32)
            rng = np.random.RandomState(0 if self._train else 1)
            n = 5000 if self._train else 1000
            label = rng.randint(0, 10, n).astype(np.int32)
            data = np.clip(protos[label] + 0.25 * rng.randn(n, 32, 32, 3), 0, 1)
            data = (data * 255).astype(np.uint8)
        self._data = [nd.array(x, dtype=np.uint8) for x in data]
        self._label = label


class ImageFolderDataset(Dataset):
    """Images stored as ``root/<class>/<file>.jpg`` (reference
    data/vision.py:233): class names come from the sorted folder names.

    ``transform`` receives ``(data, label)`` and returns the same pair.
    """

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = ['.jpg', '.jpeg', '.png']
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                ext = os.path.splitext(filename)[1].lower()
                if ext not in self._exts:
                    continue
                self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from ... import image
        img = image.imread(self.items[idx][0], flag=self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageRecordDataset(RecordFileDataset):
    """Images packed in a RecordIO file (reference data/vision.py:300):
    each record is an image-record header + encoded image, as written
    by tools/im2rec.py."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ... import image, recordio
        record = super().__getitem__(idx)
        header, img_bytes = recordio.unpack(record)
        img = image.imdecode(img_bytes, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
