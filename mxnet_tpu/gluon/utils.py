"""Gluon utilities.

Reference: python/mxnet/gluon/utils.py — split_data, split_and_load,
clip_global_norm.
"""
import math

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ['split_data', 'split_and_load', 'clip_global_norm',
           'download', 'check_sha1']


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Reference utils.py:28."""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            'Too many slices for data with shape %s. Arguments are '
            'num_slice=%d and batch_axis=%d.' % (str(data.shape), num_slice,
                                                 batch_axis))
    if even_split and size % num_slice != 0:
        raise ValueError(
            'data with shape %s cannot be evenly split into %d slices along '
            'axis %d. Use a batch size that\'s multiple of %d or set '
            'even_split=False to allow uneven partitioning of data.' % (
                str(data.shape), num_slice, batch_axis, num_slice))

    step = size // num_slice
    if batch_axis == 0:
        slices = [data[i * step:(i + 1) * step] if i < num_slice - 1
                  else data[i * step:size]
                  for i in range(num_slice)]
    elif even_split:
        slices = nd.split(data, num_outputs=num_slice, axis=batch_axis)
    else:
        slices = [nd.slice_axis(data, batch_axis, i * step, (i + 1) * step)
                  if i < num_slice - 1 else
                  nd.slice_axis(data, batch_axis, i * step, size)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Reference utils.py:63."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Reference utils.py:83."""
    assert len(arrays) > 0
    total_norm = 0
    for arr in arrays:
        arr = arr.reshape((-1,))
        norm = nd.dot(arr, arr)
        total_norm += norm.asscalar()
    total_norm = math.sqrt(total_norm)
    ratio = max_norm / (total_norm + 1e-8)
    if ratio < 1:
        for arr in arrays:
            arr *= ratio
    return total_norm


def check_sha1(filename, sha1_hash):
    """Whether the file's sha1 matches (reference utils.py:139)."""
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, 'rb') as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """Download a file (reference utils.py:166). Zero-egress
    environments raise a clear error instead of hanging; a file:// url
    or an already-present verified file short-circuits."""
    import os
    import shutil
    import urllib.request

    fname = url.split('/')[-1]
    assert fname, ('cannot derive a file name from %r; provide path= '
                   'with a file name' % url)
    if path is None:
        path = fname
    elif os.path.isdir(path):
        path = os.path.join(path, fname)
    if os.path.exists(path) and not overwrite and \
            (sha1_hash is None or check_sha1(path, sha1_hash)):
        return path
    dirname = os.path.dirname(os.path.abspath(path))
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    # write to a temp name and move into place only on success, so a
    # dropped connection never leaves a truncated file that later calls
    # would return as a valid cached download
    tmp = path + '.part'
    if url.startswith('file://'):
        shutil.copyfile(url[len('file://'):], tmp)
    else:
        try:
            r = urllib.request.urlopen(url, timeout=30)
        except OSError as e:
            raise OSError('download of %s failed (offline environment?): '
                          '%s' % (url, e))
        try:
            with r, open(tmp, 'wb') as f:
                shutil.copyfileobj(r, f)
        except OSError:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
    if sha1_hash and not check_sha1(tmp, sha1_hash):
        os.remove(tmp)
        raise OSError('downloaded file %s sha1 mismatch' % path)
    os.replace(tmp, path)
    return path
