"""Gluon Parameter / ParameterDict.

Reference: python/mxnet/gluon/parameter.py:41 (Parameter: deferred alloc,
per-ctx replicas list_data/list_grad, _finish_deferred_init:187, grad_req,
zero_grad) and :330 (ParameterDict: get/prefix nesting/save/load).
"""
import numpy as np

from .. import autograd
from .. import ndarray as nd
from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import initializer as initializer_mod
from ..initializer import InitDesc, Initializer, Uniform
from ..ndarray import NDArray

__all__ = ['Parameter', 'ParameterDict', 'DeferredInitializationError']


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization."""


class Parameter:
    """Reference parameter.py:41."""

    def __init__(self, name, grad_req='write', shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True):
        self._var = None
        self._data = None
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self._differentiable = differentiable
        self._allow_deferred_init = allow_deferred_init
        self._grad_req = None
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req
        self.init = init

    def __repr__(self):
        s = 'Parameter {name} (shape={shape}, dtype={dtype})'
        return s.format(name=self.name, shape=self.shape, dtype=self.dtype)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ['write', 'add', 'null'], \
            "grad_req must be one of 'write', 'add', or 'null', but got '%s'" % req
        if not self._differentiable:
            req = 'null'
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == 'null' and self._grad is not None:
            self._grad = None
            if self._data:
                for arr in self._data.values():
                    arr._leaf = None
        elif self._data is not None:
            self._init_grad()

    def _check_and_get(self, arr_dict, ctx):
        if arr_dict is not None:
            if ctx is list:
                return list(arr_dict.values())
            if ctx is None:
                if len(arr_dict) == 1:
                    return list(arr_dict.values())[0]
                ctx = current_context()
            if ctx in arr_dict:
                return arr_dict[ctx]
            raise RuntimeError(
                "Parameter %s was not initialized on context %s. "
                "It was only initialized on %s." % (
                    self.name, str(ctx), str(list(arr_dict.keys()))))
        if self._deferred_init:
            raise DeferredInitializationError(
                'Parameter %s has not been initialized yet because '
                'initialization was deferred. Actual initialization happens '
                'during the first forward pass. Please pass one batch of data '
                'through the network before accessing Parameters.' % self.name)
        raise RuntimeError(
            "Parameter %s has not been initialized. Note that you should "
            "initialize parameters and create Trainer with Block.collect_params() "
            "instead of Block.params because the later does not include "
            "Parameters of nested child Blocks" % self.name)

    def _load_init(self, data, ctx):
        if self.shape:
            for self_dim, data_dim in zip(self.shape, data.shape):
                assert self_dim == 0 or self_dim == data_dim, \
                    'Failed loading Parameter %s from saved params: shape ' \
                    'incompatible expected %s vs saved %s' % (
                        self.name, str(self.shape), str(data.shape))
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is None:
            if self._deferred_init:
                assert set(ctx) == set(self._deferred_init[1]), \
                    'Failed to load Parameter %s on %s because it was previous ' \
                    'initialized on %s.' % (self.name, str(ctx),
                                            str(self.list_ctx()))
            self._init_impl(data, ctx)
        else:
            assert set(ctx) == set(self.list_ctx()), \
                'Failed to load Parameter %s on %s because it was previous ' \
                'initialized on %s.' % (self.name, str(ctx),
                                        str(self.list_ctx()))
            self.set_data(data)
        self._deferred_init = ()

    def _finish_deferred_init(self):
        """Reference parameter.py:187."""
        if not self._deferred_init:
            return
        init, ctx, default_init = self._deferred_init
        self._deferred_init = ()
        assert self.shape is not None and np.prod(self.shape) > 0, \
            'Cannot initialize Parameter %s because it has invalid shape: %s.' \
            % (self.name, str(self.shape))
        with autograd.pause():
            data = nd.zeros(self.shape, dtype=self.dtype, ctx=cpu())
            initializer = initializer_mod.create(
                init if init is not None else default_init)
            initializer(InitDesc(self.name, {'__init__': ''}), data)
            self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._ctx_list = list(ctx_list)
        self._data = {ctx: data.copyto(ctx) for ctx in self._ctx_list}
        self._init_grad()

    def _init_grad(self):
        if self.grad_req == 'null':
            self._grad = None
            return
        self._grad = {ctx: nd.zeros(self._data[ctx].shape, ctx=ctx,
                                    dtype=str(self._data[ctx]._data.dtype))
                      for ctx in self._data}
        for ctx in self._data:
            autograd.mark_variables([self._data[ctx]], [self._grad[ctx]],
                                    self.grad_req)

    def initialize(self, init=None, ctx=None, default_init=Uniform(),
                   force_reinit=False):
        """Reference parameter.py:233."""
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        if not self.shape or np.prod(self.shape) <= 0:
            if self._allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError('Cannot initialize Parameter %s because it has '
                             'invalid shape: %s.' % (self.name, str(self.shape)))
        self._deferred_init = (init, ctx, default_init)
        self._finish_deferred_init()

    def reset_ctx(self, ctx):
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data:
            data = self._reduce()
            with autograd.pause():
                self._init_impl(data, ctx)
        elif self._deferred_init:
            init, _, default_init = self._deferred_init
            self._deferred_init = (init, ctx, default_init)
        else:
            raise ValueError('Cannot reset context for Parameter %s because it '
                             'has not been initialized.' % self.name)

    def set_data(self, data):
        assert self._data is not None, \
            'Parameter %s has not been initialized' % self.name
        for ctx in self._data:
            self._data[ctx]._data = data.copyto(ctx)._data

    def data(self, ctx=None):
        return self._check_and_get(self._data, ctx)

    def list_data(self):
        return self._check_and_get(self._data, list)

    def grad(self, ctx=None):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter %s because grad_req='null'"
                % self.name)
        return self._check_and_get(self._grad, ctx)

    def list_grad(self):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter %s because grad_req='null'"
                % self.name)
        return self._check_and_get(self._grad, list)

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise RuntimeError('Parameter %s has not been initialized' % self.name)
        return self._ctx_list

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad.values():
            g[:] = 0
        for d in self._data.values():
            d._fresh_grad = True

    def _reduce(self):
        """Average weights over contexts → cpu copy."""
        block = self.list_data()
        return block[0].copyto(cpu())

    def var(self):
        if self._var is None:
            from .. import symbol
            self._var = symbol.var(self.name, shape=self.shape,
                                   lr_mult=self.lr_mult, wd_mult=self.wd_mult,
                                   init=self.init)
        return self._var

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        with autograd.pause():
            self._data = {ctx: d.astype(dtype) for ctx, d in self._data.items()}
            self._init_grad()


class ParameterDict:
    """Reference parameter.py:330."""

    def __init__(self, prefix='', shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    def __getitem__(self, key):
        return self._params[key]

    def __repr__(self):
        s = '{name}(\n{content}\n)'
        name = self._prefix + ' ' if self._prefix else ''
        return s.format(name=name, content='\n'.join(
            [_indent('  {0}'.format(v), 2) for v in self.values()]))

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._shared._params[name]
        return None

    def get(self, name, **kwargs):
        """Reference parameter.py:400 — create-or-retrieve with attr merge."""
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == 'shape' and v is not None and len(v) == len(existing):
                        inferred_shape = []
                        matched = True
                        for dim1, dim2 in zip(v, existing):
                            if dim1 != dim2 and dim1 * dim2 != 0:
                                matched = False
                                break
                            elif dim1 == dim2:
                                inferred_shape.append(dim1)
                            elif dim1 == 0:
                                inferred_shape.append(dim2)
                            else:
                                inferred_shape.append(dim1)
                        if matched:
                            param.shape = tuple(inferred_shape)
                            continue
                    assert v is None or v == existing, \
                        'Cannot retrieve Parameter %s because desired attribute ' \
                        'does not match with stored for attribute %s: desired %s' \
                        ' vs stored %s.' % (name, k, str(v), str(getattr(param, k)))
                else:
                    setattr(param, k, v)
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    'Cannot update self with other because they have different ' \
                    'Parameters with the same name %s' % k
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if verbose and init is not None:
            init.set_verbosity(verbose=verbose)
        for _, v in self.items():
            v.initialize(None, ctx, init if init is not None else Uniform(),
                         force_reinit=force_reinit)

    def zero_grad(self):
        for i in self.values():
            i.zero_grad()

    def reset_ctx(self, ctx):
        for i in self.values():
            i.reset_ctx(ctx)

    def setattr(self, name, value):
        for i in self.values():
            setattr(i, name, value)

    def save(self, filename, strip_prefix=''):
        arg_dict = {}
        for param in self.values():
            weight = param._reduce()
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    'Prefix %s is to be striped before saving, but Parameter '
                    '%s does not start with %s.' % (
                        strip_prefix, param.name, strip_prefix))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx, allow_missing=False,
             ignore_extra=False, restore_prefix=''):
        if restore_prefix:
            for name in self.keys():
                assert name.startswith(restore_prefix), \
                    'restore_prefix is %s but Parameters name %s does not start ' \
                    'with %s' % (restore_prefix, name, restore_prefix)
        lprefix = len(restore_prefix)
        arg_dict = {restore_prefix + k: v for k, v in nd.load(filename).items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    'Parameter %s is missing in file %s' % (name[lprefix:], filename)
        for name in arg_dict:
            if name not in self._params:
                assert ignore_extra, \
                    'Parameter %s loaded from file %s is not present in ' \
                    'ParameterDict' % (name[lprefix:], filename)
                continue
            self[name]._load_init(arg_dict[name], ctx)


def _indent(s_, num_spaces):
    s = str(s_).split('\n')
    if len(s) == 1:
        return s_
    first = s.pop(0)
    return first + '\n' + '\n'.join(' ' * num_spaces + line for line in s)
