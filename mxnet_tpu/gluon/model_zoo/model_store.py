"""Pre-trained model parameter store (reference gluon/model_zoo/
model_store.py). This environment has no network egress, so
``get_model_file`` resolves ONLY against the local directory (drop
``<name>.params`` files there yourself); the rest of the API —
existence checks, purge, the sha1 table protocol — behaves as the
reference's."""
import os

__all__ = ['get_model_file', 'purge']

# name -> sha1 of the published .params (reference _model_sha1); empty
# here because nothing can be fetched without egress — local files are
# trusted as-is.
_model_sha1 = {}


def short_hash(name):
    if name not in _model_sha1:
        raise ValueError('Pretrained model for %s is not available.' % name)
    return _model_sha1[name][:8]


def get_model_file(name, local_dir=os.path.expanduser('~/.mxnet/models/')):
    """Return the path of a locally present pre-trained parameter file.

    The reference downloads from the model zoo on miss; without network
    egress a miss raises with instructions instead."""
    file_path = os.path.join(local_dir, '%s.params' % name)
    if os.path.exists(file_path):
        return file_path
    raise IOError(
        'Pretrained model file %s is not present and this environment '
        'has no network egress. Place the reference-format .params file '
        'at that path (checkpoints interoperate, docs/migration.md), or '
        'train from scratch with pretrained=False.' % file_path)


def purge(local_dir=os.path.expanduser('~/.mxnet/models/')):
    """Remove all cached model files (reference model_store.py:108)."""
    if not os.path.isdir(local_dir):
        return
    for f in os.listdir(local_dir):
        if f.endswith('.params'):
            os.remove(os.path.join(local_dir, f))
