"""Inception V3 (Szegedy et al. 2015) as a spec-table build.

Parity target: python/mxnet/gluon/model_zoo/vision/inception.py. The
reference spells each grid cell out as nested `_make_branch` calls;
here the whole architecture is a table of compact conv-spec strings
(`"192x7.1s2p3.0"` = 192 channels, 7x1 kernel, stride 2, pad (3,0))
parsed by one builder. Cell prefixes (A1_...E2_) and within-cell child
order match the reference so auto-generated parameter names stay
checkpoint-compatible.
"""
from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ['Inception3', 'inception_v3']


def _parse_conv(tok):
    """'CHxK[.K2][sS][pP[.P2]]' -> Conv2D kwargs (BN+relu added by
    _unit). Examples: '64x1', '96x3p1', '384x3s2', '192x1.7p0.3'."""
    ch, rest = tok.split('x', 1)
    kw = {'channels': int(ch), 'use_bias': False}

    def grab(marker):
        nonlocal rest
        if marker in rest:
            rest, val = rest.split(marker, 1)
            return val
        return None

    pad = grab('p')
    stride = grab('s')

    def pair(v):
        if v is None:
            return None
        return tuple(int(x) for x in v.split('.')) if '.' in v else int(v)

    kw['kernel_size'] = pair(rest)
    if stride is not None:
        kw['strides'] = pair(stride)
    if pad is not None:
        kw['padding'] = pair(pad)
    return kw


def _unit(tok):
    """One conv-BN-relu unit from a spec token."""
    seq = nn.HybridSequential(prefix='')
    seq.add(nn.Conv2D(**_parse_conv(tok)))
    seq.add(nn.BatchNorm(epsilon=0.001))
    seq.add(nn.Activation('relu'))
    return seq


class _Fanout(HybridBlock):
    """Run every child on the same input and concat on channels."""

    def add(self, block):
        self.register_child(block)

    def hybrid_forward(self, F, x):
        return F.Concat(*[c(x) for c in self._children], dim=1)


def _branch(spec):
    """Build one branch from a comma-joined spec: optional leading
    'avg'/'max' pool, conv tokens, and an optional trailing fanout
    'a|b' (the E-cell 1x3 / 3x1 split)."""
    seq = nn.HybridSequential(prefix='')
    for tok in spec.split(','):
        if tok == 'avg':
            seq.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
        elif tok == 'max':
            seq.add(nn.MaxPool2D(pool_size=3, strides=2))
        elif '|' in tok:
            fan = _Fanout(prefix='')
            for sub in tok.split('|'):
                fan.add(_unit(sub))
            seq.add(fan)
        else:
            seq.add(_unit(tok))
    return seq


def _cell(prefix, branch_specs):
    cell = _Fanout(prefix=prefix)
    with cell.name_scope():
        for spec in branch_specs:
            cell.add(_branch(spec))
    return cell


# stem tokens ('M' = 3x3/2 maxpool) and the grid-cell table. Constants
# are the published Inception-v3 architecture.
_STEM = ('32x3s2', '32x3', '64x3p1', 'M', '80x1', '192x3', 'M')


def _a_cell(pool_ch):
    return ('64x1',
            '48x1,64x5p2',
            '64x1,96x3p1,96x3p1',
            'avg,%dx1' % pool_ch)


def _c_cell(c7):
    d = {'c': c7}
    return ('192x1',
            '%(c)dx1,%(c)dx1.7p0.3,192x7.1p3.0' % d,
            '%(c)dx1,%(c)dx7.1p3.0,%(c)dx1.7p0.3,%(c)dx7.1p3.0,'
            '192x1.7p0.3' % d,
            'avg,192x1')


_E_SPLIT = '384x1.3p0.1|384x3.1p1.0'
_CELLS = (
    ('A1_', _a_cell(32)),
    ('A2_', _a_cell(64)),
    ('A3_', _a_cell(64)),
    ('B_', ('384x3s2', '64x1,96x3p1,96x3s2', 'max')),
    ('C1_', _c_cell(128)),
    ('C2_', _c_cell(160)),
    ('C3_', _c_cell(160)),
    ('C4_', _c_cell(192)),
    ('D_', ('192x1,320x3s2',
            '192x1,192x1.7p0.3,192x7.1p3.0,192x3s2', 'max')),
    ('E1_', ('320x1', '384x1,' + _E_SPLIT, '448x1,384x3p1,' + _E_SPLIT,
             'avg,192x1')),
    ('E2_', ('320x1', '384x1,' + _E_SPLIT, '448x1,384x3p1,' + _E_SPLIT,
             'avg,192x1')),
)


def make_aux(classes):
    """Auxiliary classifier head (reference vision/inception.py:145)."""
    out = nn.HybridSequential(prefix='')
    out.add(nn.AvgPool2D(pool_size=5, strides=3))
    out.add(_unit('128x1'))
    out.add(_unit('768x5'))
    out.add(nn.Flatten())
    out.add(nn.Dense(classes))
    return out


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            for tok in _STEM:
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2)
                                  if tok == 'M' else _unit(tok))
            for prefix, branches in _CELLS:
                self.features.add(_cell(prefix, branches))
            self.features.add(nn.AvgPool2D(pool_size=8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, ctx=cpu(), **kwargs):
    net = Inception3(**kwargs)
    if pretrained:
        raise ValueError('no pretrained weights available (zero-egress build)')
    return net
