"""AlexNet (Krizhevsky et al. 2012) as a config-table build.

Parity target: python/mxnet/gluon/model_zoo/vision/alexnet.py (the
reference hand-writes the layer stack; here the architecture lives in
two tables and a loop). Child-block ORDER matches the reference so
auto-generated parameter names — and therefore checkpoints — stay
compatible.
"""
from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ['AlexNet', 'alexnet']

# feature extractor: ('C', channels, kernel, stride, pad) | ('M',) maxpool
_FEATURES = (
    ('C', 64, 11, 4, 2), ('M',),
    ('C', 192, 5, 1, 2), ('M',),
    ('C', 384, 3, 1, 1),
    ('C', 256, 3, 1, 1),
    ('C', 256, 3, 1, 1), ('M',),
)
_HIDDEN = 4096
_DROP = 0.5


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            with self.features.name_scope():
                for spec in _FEATURES:
                    if spec[0] == 'M':
                        self.features.add(nn.MaxPool2D(pool_size=3,
                                                       strides=2))
                    else:
                        _, ch, k, s, p = spec
                        self.features.add(nn.Conv2D(
                            ch, kernel_size=k, strides=s, padding=p,
                            activation='relu'))
                self.features.add(nn.Flatten())
            self.classifier = nn.HybridSequential(prefix='')
            with self.classifier.name_scope():
                for _ in range(2):
                    self.classifier.add(nn.Dense(_HIDDEN,
                                                 activation='relu'))
                    self.classifier.add(nn.Dropout(_DROP))
                self.classifier.add(nn.Dense(classes))

    def hybrid_forward(self, F, x):
        return self.classifier(self.features(x))


def alexnet(pretrained=False, ctx=cpu(), **kwargs):
    net = AlexNet(**kwargs)
    if pretrained:
        raise ValueError('no pretrained weights available (zero-egress build)')
    return net
