"""VGG (reference python/mxnet/gluon/model_zoo/vision/vgg.py)."""
from ....context import cpu
from ....initializer import Xavier
from ...block import HybridBlock
from ... import nn

__all__ = ['VGG', 'vgg11', 'vgg13', 'vgg16', 'vgg19', 'vgg11_bn', 'vgg13_bn',
           'vgg16_bn', 'vgg19_bn', 'get_vgg']

# depth -> convs per stage; every variant shares the same stage widths
_STAGE_WIDTHS = (64, 128, 256, 512, 512)
_DEPTHS = {11: (1, 1, 2, 2, 2), 13: (2, 2, 2, 2, 2),
           16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}
vgg_spec = {d: (list(counts), list(_STAGE_WIDTHS))
            for d, counts in _DEPTHS.items()}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = self._make_features(layers, filters, batch_norm)
            self.features.add(nn.Dense(4096, activation='relu',
                                       weight_initializer='normal',
                                       bias_initializer='zeros'))
            self.features.add(nn.Dropout(rate=0.5))
            self.features.add(nn.Dense(4096, activation='relu',
                                       weight_initializer='normal',
                                       bias_initializer='zeros'))
            self.features.add(nn.Dropout(rate=0.5))
            self.output = nn.Dense(classes, weight_initializer='normal',
                                   bias_initializer='zeros')

    def _make_features(self, layers, filters, batch_norm):
        featurizer = nn.HybridSequential(prefix='')
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(nn.Conv2D(filters[i], kernel_size=3, padding=1,
                                         weight_initializer=Xavier(
                                             rnd_type='gaussian',
                                             factor_type='out',
                                             magnitude=2),
                                         bias_initializer='zeros'))
                if batch_norm:
                    featurizer.add(nn.BatchNorm())
                featurizer.add(nn.Activation('relu'))
            featurizer.add(nn.MaxPool2D(strides=2))
        return featurizer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def get_vgg(num_layers, pretrained=False, ctx=cpu(), **kwargs):
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        raise ValueError('no pretrained weights available (zero-egress build)')
    return net


def _shortcut(depth, bn):
    def f(**kwargs):
        if bn:
            kwargs['batch_norm'] = True
        return get_vgg(depth, **kwargs)
    f.__name__ = 'vgg%d%s' % (depth, '_bn' if bn else '')
    f.__doc__ = 'VGG-%d%s (get_vgg shortcut).' % (depth,
                                                  ' + BatchNorm' if bn else '')
    return f


# vgg11 ... vgg19_bn, generated from the table
for _d in sorted(_DEPTHS):
    for _bn in (False, True):
        _fn = _shortcut(_d, _bn)
        globals()[_fn.__name__] = _fn
del _d, _bn, _fn
