"""SqueezeNet 1.0/1.1 (Iandola et al. 2016) as a config-table build.

Parity target: python/mxnet/gluon/model_zoo/vision/squeezenet.py. Each
version is one table row: the stem conv spec plus a sequence of fire
squeeze widths interleaved with 'M' maxpool markers (expand widths are
always 4x the squeeze width, split evenly between the 1x1 and 3x3
paths — the paper's fixed ratio). Child order matches the reference
for checkpoint-compatible parameter naming.
"""
from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ['SqueezeNet', 'squeezenet1_0', 'squeezenet1_1', 'get_squeezenet']

# version -> ((stem_channels, stem_kernel), layout); layout entries:
# int = fire module squeeze width, 'M' = ceil-mode 3x3/2 maxpool
_LAYOUT = {
    '1.0': ((96, 7), ('M', 16, 16, 32, 'M', 32, 48, 48, 64, 'M', 64)),
    '1.1': ((64, 3), ('M', 16, 16, 'M', 32, 32, 'M', 48, 48, 64, 64)),
}


def _conv_relu(channels, kernel, padding=0):
    seq = nn.HybridSequential(prefix='')
    seq.add(nn.Conv2D(channels, kernel, padding=padding))
    seq.add(nn.Activation('relu'))
    return seq


class _FireExpand(HybridBlock):
    """The fire module's parallel 1x1 / 3x3 expand paths."""

    def __init__(self, e1, e3, **kwargs):
        super().__init__(**kwargs)
        self.p1 = _conv_relu(e1, 1)
        self.p2 = _conv_relu(e3, 3, 1)

    def hybrid_forward(self, F, x):
        return F.Concat(self.p1(x), self.p2(x), dim=1)


def _fire(squeeze):
    seq = nn.HybridSequential(prefix='')
    seq.add(_conv_relu(squeeze, 1))
    seq.add(_FireExpand(squeeze * 4, squeeze * 4))
    return seq


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in _LAYOUT:
            raise ValueError(
                'Unsupported SqueezeNet version %s: 1.0 or 1.1 expected'
                % version)
        (stem_ch, stem_k), layout = _LAYOUT[version]
        with self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            self.features.add(nn.Conv2D(stem_ch, kernel_size=stem_k,
                                        strides=2))
            self.features.add(nn.Activation('relu'))
            for item in layout:
                if item == 'M':
                    self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                                   ceil_mode=True))
                else:
                    self.features.add(_fire(item))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix='')
            self.output.add(nn.Conv2D(classes, kernel_size=1))
            self.output.add(nn.Activation('relu'))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_squeezenet(version, pretrained=False, ctx=cpu(), **kwargs):
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        raise ValueError('no pretrained weights available (zero-egress build)')
    return net


def squeezenet1_0(**kwargs):
    return get_squeezenet('1.0', **kwargs)


def squeezenet1_1(**kwargs):
    return get_squeezenet('1.1', **kwargs)
