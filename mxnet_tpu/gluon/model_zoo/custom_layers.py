"""Custom layers used by model-zoo architectures (reference
gluon/model_zoo/custom_layers.py: HybridConcurrent + Identity)."""
from ..block import HybridBlock

__all__ = ['HybridConcurrent', 'Identity']


class HybridConcurrent(HybridBlock):
    """Runs child blocks on the same input concurrently and concatenates
    their outputs along ``concat_dim`` (reference custom_layers.py:25).

    Example::

        net = HybridConcurrent(concat_dim=1)
        with net.name_scope():
            net.add(nn.Dense(10, activation='relu'))
            net.add(nn.Dense(20))
            net.add(Identity())
    """

    def __init__(self, concat_dim, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.concat_dim = concat_dim

    def add(self, block):
        self.register_child(block)

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children]
        return F.concat(*out, dim=self.concat_dim)

    def __repr__(self):
        modstr = '\n'.join('  (%d): %s' % (k, b)
                           for k, b in enumerate(self._children))
        return '%s(\n%s\n)' % (type(self).__name__, modstr)


class Identity(HybridBlock):
    """Passes the input through unchanged — the residual-branch partner
    of HybridConcurrent (reference custom_layers.py:62)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x
