"""String → Initializer resolution shared by gluon layers — delegates
to the single registry-backed resolver (initializer.create)."""
from ...initializer import create as init_by_name  # noqa: F401
