"""String → Initializer resolution shared by gluon layers."""
from ...initializer import Zero, One


def init_by_name(init):
    if init is None or not isinstance(init, str):
        return init
    return {'zeros': Zero(), 'ones': One()}.get(init, init)
