"""Gluon fused recurrent layers (RNN/LSTM/GRU).

Reference: python/mxnet/gluon/rnn/rnn_layer.py — _RNNLayer dispatching to
the fused RNN op, with begin_state and layout handling.
"""
from ... import ndarray as nd
from ...ops.rnn_ops import rnn_param_size, _gates
from ..block import Block
from .basic_init import init_by_name

__all__ = ['RNN', 'LSTM', 'GRU']


class _RNNLayer(Block):
    """Reference rnn_layer.py:33."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout == 'TNC' or layout == 'NTC', \
            'Invalid layout %s; must be one of ["TNC" or "NTC"]' % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer

        self._gates = _gates(mode)
        ng, ni, nh = self._gates, input_size, hidden_size
        # flat cuDNN-layout parameter vector (matches the fused RNN op)
        size = rnn_param_size(num_layers, hidden_size, input_size,
                              bidirectional, mode) if input_size else 0
        from ...initializer import Uniform
        self.parameters = self.params.get(
            'parameters', shape=(size,) if size else (0,),
            init=i2h_weight_initializer or Uniform(0.1),
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def __repr__(self):
        s = '{name}({mapping}, {_layout}'
        if self._num_layers != 1:
            s += ', num_layers={_num_layers}'
        if self._dropout != 0:
            s += ', dropout={_dropout}'
        if self._dir == 2:
            s += ', bidirectional'
        s += ')'
        mapping = ('{_input_size} -> {_hidden_size}'.format(**self.__dict__)
                   if self._input_size else self._hidden_size)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Reference rnn_layer.py:136."""
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            shape = info.pop('shape', ())
            info.pop('__layout__', None)
            states.append(func(shape=shape, **{k: v for k, v in info.items()
                                               if k in ('ctx', 'dtype')}))
        return states

    def forward(self, inputs, states=None):
        batch_size = inputs.shape[self._layout.find('N')]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=inputs.context)
        if isinstance(states, nd.NDArray):
            states = [states]
        for state, info in zip(states, self.state_info(batch_size)):
            if state.shape != info['shape']:
                raise ValueError(
                    'Invalid recurrent state shape. Expecting %s, got %s.' % (
                        str(info['shape']), str(state.shape)))
        if self._input_size == 0:
            self._input_size = inputs.shape[2] if self._layout == 'TNC' else \
                inputs.shape[2]
            size = rnn_param_size(self._num_layers, self._hidden_size,
                                  self._input_size, self._dir == 2, self._mode)
            self.parameters.shape = (size,)
            self.parameters._finish_deferred_init()
        if self._layout == 'NTC':
            inputs = inputs.swapaxes(0, 1)
        out = nd.RNN(inputs, self.parameters.data(inputs.context), *states,
                     state_size=self._hidden_size,
                     num_layers=self._num_layers,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True, mode=self._mode)
        outputs = out[0]
        out_states = list(out[1:])
        if self._layout == 'NTC':
            outputs = outputs.swapaxes(0, 1)
        if skip_states:
            return outputs
        return outputs, out_states


class RNN(_RNNLayer):
    """Reference rnn_layer.py:240."""

    def __init__(self, hidden_size, num_layers=1, activation='relu',
                 layout='TNC', dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer,
                         init_by_name(i2h_bias_initializer),
                         init_by_name(h2h_bias_initializer),
                         'rnn_' + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]


class LSTM(_RNNLayer):
    """Reference rnn_layer.py:334."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer,
                         init_by_name(i2h_bias_initializer),
                         init_by_name(h2h_bias_initializer), 'lstm', **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'},
                {'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]


class GRU(_RNNLayer):
    """Reference rnn_layer.py:439."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer,
                         init_by_name(i2h_bias_initializer),
                         init_by_name(h2h_bias_initializer), 'gru', **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]
