"""Gluon recurrent cells.

Reference: python/mxnet/gluon/rnn/rnn_cell.py — RecurrentCell base,
RNNCell/LSTMCell/GRUCell, SequentialRNNCell, DropoutCell, Zoneout/Residual
modifiers, BidirectionalCell.
"""
from ... import ndarray as nd
from ..block import Block, HybridBlock

__all__ = ['RecurrentCell', 'HybridRecurrentCell', 'RNNCell', 'LSTMCell',
           'GRUCell', 'SequentialRNNCell', 'DropoutCell', 'ModifierCell',
           'ZoneoutCell', 'ResidualCell', 'BidirectionalCell']


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    from ...ndarray import NDArray
    from ... import symbol
    assert inputs is not None
    axis = layout.find('T')
    batch_axis = layout.find('N')
    batch_size = 0
    in_axis = in_layout.find('T') if in_layout is not None else axis
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            assert length is None or length == inputs.shape[in_axis]
            inputs = list(nd.split(inputs, axis=in_axis,
                                   num_outputs=inputs.shape[in_axis],
                                   squeeze_axis=1))
    elif isinstance(inputs, symbol.Symbol):
        if merge is False:
            assert len(inputs.list_outputs()) == 1
            inputs = list(symbol.split(inputs, axis=in_axis,
                                       num_outputs=length, squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        if isinstance(inputs[0], symbol.Symbol):
            F = symbol
        else:
            F = nd
            batch_size = inputs[0].shape[batch_axis - 1] if batch_axis > 0 \
                else inputs[0].shape[0]
        if merge is True:
            inputs = [F.expand_dims(i, axis=axis) for i in inputs]
            inputs = F.Concat(*inputs, dim=axis) if F is symbol else \
                nd.concatenate(inputs, axis=axis)
    if isinstance(inputs, tuple([type(None)])) is False and \
            not isinstance(inputs, list) and axis != in_axis:
        inputs = (symbol if isinstance(inputs, symbol.Symbol) else nd).swapaxes(
            inputs, dim1=axis, dim2=in_axis)
    return inputs, axis, (symbol if not isinstance(inputs, (NDArray, list)) or
                          (isinstance(inputs, list) and
                           isinstance(inputs[0], symbol.Symbol)) else nd), \
        batch_size


class RecurrentCell(Block):
    """Reference gluon/rnn/rnn_cell.py:33."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            'After applying modifier cells the base cell cannot be called directly. Call the modifier cell instead.'
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            shape = info.pop('shape', ())
            info.pop('__layout__', None)
            state = func(shape=shape,
                         **{k: v for k, v in info.items() if k in
                            ('ctx', 'dtype')})
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        inputs, _, F, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _, _, _ = _format_sequence(length, outputs, layout,
                                            merge_outputs)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError()


class RNNCell(HybridRecurrentCell):
    """Reference gluon/rnn/rnn_cell.py:224."""

    def __init__(self, hidden_size, activation='tanh', i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer='zeros',
                 h2h_bias_initializer='zeros', input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get('i2h_weight',
                                          shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get('h2h_weight',
                                          shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        from .basic_init import init_by_name
        self.i2h_bias = self.params.get('i2h_bias', shape=(hidden_size,),
                                        init=init_by_name(i2h_bias_initializer),
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get('h2h_bias', shape=(hidden_size,),
                                        init=init_by_name(h2h_bias_initializer),
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size), '__layout__': 'NC'}]

    def _alias(self):
        return 'rnn'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """Reference gluon/rnn/rnn_cell.py:302. Gate order i,f,c,o."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer='zeros',
                 h2h_bias_initializer='zeros', input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get('i2h_weight',
                                          shape=(4 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get('h2h_weight',
                                          shape=(4 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        from .basic_init import init_by_name
        self.i2h_bias = self.params.get('i2h_bias', shape=(4 * hidden_size,),
                                        init=init_by_name(i2h_bias_initializer),
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get('h2h_bias', shape=(4 * hidden_size,),
                                        init=init_by_name(h2h_bias_initializer),
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size), '__layout__': 'NC'},
                {'shape': (batch_size, self._hidden_size), '__layout__': 'NC'}]

    def _alias(self):
        return 'lstm'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size * 4)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size * 4)
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4)
        in_gate = F.Activation(slice_gates[0], act_type='sigmoid')
        forget_gate = F.Activation(slice_gates[1], act_type='sigmoid')
        in_transform = F.Activation(slice_gates[2], act_type='tanh')
        out_gate = F.Activation(slice_gates[3], act_type='sigmoid')
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type='tanh')
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """Reference gluon/rnn/rnn_cell.py:426. Gate order r,z,n."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer='zeros',
                 h2h_bias_initializer='zeros', input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get('i2h_weight',
                                          shape=(3 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get('h2h_weight',
                                          shape=(3 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        from .basic_init import init_by_name
        self.i2h_bias = self.params.get('i2h_bias', shape=(3 * hidden_size,),
                                        init=init_by_name(i2h_bias_initializer),
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get('h2h_bias', shape=(3 * hidden_size,),
                                        init=init_by_name(h2h_bias_initializer),
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size), '__layout__': 'NC'}]

    def _alias(self):
        return 'gru'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size * 3)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size * 3)
        i2h_r, i2h_z, i2h = F.SliceChannel(i2h, num_outputs=3)
        h2h_r, h2h_z, h2h = F.SliceChannel(h2h, num_outputs=3)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type='sigmoid')
        update_gate = F.Activation(i2h_z + h2h_z, act_type='sigmoid')
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type='tanh')
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Reference gluon/rnn/rnn_cell.py:540."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        num_cells = len(self._children)
        _, _, _, batch_size = _format_sequence(length, inputs, layout, None)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args, **kwargs):
        raise NotImplementedError()


class DropoutCell(HybridRecurrentCell):
    """Reference gluon/rnn/rnn_cell.py:624."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, (int, float))
        self.rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return 'dropout'

    def hybrid_forward(self, F, inputs, states):
        if self.rate > 0:
            inputs = F.Dropout(inputs, p=self.rate)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Reference gluon/rnn/rnn_cell.py:672."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            'Cell %s is already modified. One cell cannot be modified twice' \
            % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell), \
            'BidirectionalCell doesn\'t support zoneout. ' \
            'Please add ZoneoutCell to the cells underneath instead.'
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def _alias(self):
        return 'zoneout'

    def reset(self):
        super().reset()
        self.prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = self.base_cell, self.zoneout_outputs, \
            self.zoneout_states
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)

        prev_output = self.prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output,
                         prev_output) if p_outputs != 0. else next_output
        states = [F.where(mask(p_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if p_states != 0. else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return 'residual'

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        from ...ndarray import NDArray
        merge_outputs = isinstance(outputs, NDArray) if merge_outputs is None \
            else merge_outputs
        inputs, _, F, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [i + j for i, j in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Reference gluon/rnn/rnn_cell.py:805."""

    def __init__(self, l_cell, r_cell, output_prefix='bi_'):
        super().__init__(prefix='', params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError('Bidirectional cannot be stepped. Please use unroll')

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(length, inputs, layout,
                                                       False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        l_cell, r_cell = self._children
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info(batch_size))],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info(batch_size)):],
            layout=layout, merge_outputs=False)
        if F is nd:
            concat = lambda a, b: nd.Concat(a, b, dim=1)
        else:
            from ... import symbol
            concat = lambda a, b: symbol.Concat(a, b, dim=1)
        outputs = [concat(l_o, r_o) for l_o, r_o in
                   zip(l_outputs, reversed(r_outputs))]
        outputs, _, _, _ = _format_sequence(length, outputs, layout,
                                            merge_outputs)
        states = l_states + r_states
        return outputs, states

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError()
