"""Gluon Trainer — applies an Optimizer to a set of Parameters.

Reference: python/mxnet/gluon/trainer.py:26 (_init_kvstore:95, step:116 —
push grads / pull weights when update_on_kvstore, else pull grads + local
updaters per device).
"""
from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter

__all__ = ['Trainer']


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore='device'):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                'First argument must be a list or dict of Parameters, '
                'got %s.' % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    'First argument must be a list or dict of Parameters, '
                    'got list of %s.' % (type(param)))
            if param.grad_req != 'null':
                self._params.append(param)
        self._scale = float(optimizer_params.get('rescale_grad', 1.0)) \
            if optimizer_params else 1.0
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params or {})
        self._kv_initialized = False
        self._kvstore = kvstore

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                'All Parameters must be initialized on the same set of contexts, ' \
                'but Parameter %s is initialized on %s while previous Parameters ' \
                'are initialized on %s.' % (param.name, str(ctx), str(contexts))
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                'optimizer_params must be None if optimizer is an Optimizer ' \
                'instance'
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        """Reference trainer.py:95."""
        arg_arrays = {param.name: param.data(self._contexts[0])
                      for param in self._params}
        kvstore, update_on_kvstore = _create_kvstore(
            self._kvstore, len(self._contexts), arg_arrays)
        if kvstore:
            if 'dist' in kvstore.type:
                update_on_kvstore = False
            for i, param in enumerate(self._params):
                param_arrays = param.list_data()
                kvstore.init(i, param_arrays[0])
                if update_on_kvstore:
                    kvstore.pull(i, param_arrays, priority=-i)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore = kvstore
            self._update_on_kvstore = update_on_kvstore
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """Reference trainer.py:116."""
        if not self._kv_initialized:
            self._init_kvstore()

        self._optimizer.rescale_grad = self._scale / batch_size

        for i, param in enumerate(self._params):
            if param.grad_req == 'null':
                continue
            if not ignore_stale_grad:
                for data in param.list_data():
                    if data._fresh_grad:
                        raise UserWarning(
                            'Gradient of Parameter `%s` on context %s has not '
                            'been updated by backward since last `step`. This '
                            'could mean a bug in your model that made it only '
                            'use a subset of the Parameters (Blocks) for this '
                            'iteration. If you are intentionally only using a '
                            'subset, call step with ignore_stale_grad=True to '
                            'suppress this warning and skip updating of '
                            'Parameters with stale gradient' % (
                                param.name, str(data.context)))
            if self._kvstore:
                self._kvstore.push(i, param.list_grad(), priority=-i)
                if self._update_on_kvstore:
                    self._kvstore.pull(i, param.list_data(), priority=-i)
                    continue
                self._kvstore.pull(i, param.list_grad(), priority=-i)

            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                if not ignore_stale_grad or not arr._fresh_grad:
                    upd(i, grad, arr)
                    arr._fresh_grad = True
        # reset for next iteration's staleness tracking
        for param in self._params:
            for data in param.list_data():
                data._fresh_grad = True

    def save_states(self, fname):
        """Reference trainer.py:162."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, 'wb') as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Reference trainer.py:178."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, 'rb') as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
