"""Gluon Trainer — applies an Optimizer to a set of Parameters.

Reference: python/mxnet/gluon/trainer.py:26 (_init_kvstore:95, step:116 —
push grads / pull weights when update_on_kvstore, else pull grads + local
updaters per device).
"""
from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter

__all__ = ['Trainer']


def _trainable(params):
    """Validate and flatten the params argument; keep grad-bearing ones."""
    if isinstance(params, (dict, ParameterDict)):
        params = list(params.values())
    if not isinstance(params, (list, tuple)):
        raise ValueError(
            'First argument must be a list or dict of Parameters, '
            'got %s.' % (type(params)))
    for p in params:
        if not isinstance(p, Parameter):
            raise ValueError(
                'First argument must be a list or dict of Parameters, '
                'got list of %s.' % (type(p)))
    return [p for p in params if p.grad_req != 'null']


class Trainer:
    """Steps an optimizer over a Block's parameters, aggregating
    gradients across the parameters' contexts through a KVStore (or
    per-context Updaters when no store is warranted)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore='device'):
        self._params = _trainable(params)
        self._scale = float((optimizer_params or {}).get('rescale_grad', 1.0))
        self._contexts = self._shared_contexts()
        self._init_optimizer(optimizer, optimizer_params or {})
        self._kvstore = kvstore
        self._kv_initialized = False

    def _shared_contexts(self):
        """All parameters must live on one common context list."""
        seen = None
        for p in self._params:
            ctx = p.list_ctx()
            if seen is not None and seen != ctx:
                raise AssertionError(
                    'All Parameters must be initialized on the same set of '
                    'contexts, but Parameter %s is initialized on %s while '
                    'previous Parameters are initialized on %s.'
                    % (p.name, str(ctx), str(seen)))
            seen = ctx
        return seen

    def _init_optimizer(self, optimizer, optimizer_params):
        by_index = dict(enumerate(self._params))
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise AssertionError('optimizer_params must be None if '
                                     'optimizer is an Optimizer instance')
            self._optimizer = optimizer
            optimizer.param_dict = by_index
        else:
            self._optimizer = opt.create(optimizer, param_dict=by_index,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        """Decide the gradient-aggregation path once, lazily.
        Reference trainer.py:95."""
        sample = {p.name: p.data(self._contexts[0]) for p in self._params}
        kvstore, update_on_kvstore = _create_kvstore(
            self._kvstore, len(self._contexts), sample)
        if not kvstore:
            self._kvstore, self._update_on_kvstore = None, False
        else:
            if 'dist' in kvstore.type:
                update_on_kvstore = False
            for i, p in enumerate(self._params):
                replicas = p.list_data()
                kvstore.init(i, replicas[0])
                if update_on_kvstore:
                    kvstore.pull(i, replicas, priority=-i)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore = kvstore
            self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _assert_fresh(self, param):
        for data in param.list_data():
            if data._fresh_grad:
                raise UserWarning(
                    'Gradient of Parameter `%s` on context %s has not '
                    'been updated by backward since last `step`. This '
                    'could mean a bug in your model that made it only '
                    'use a subset of the Parameters (Blocks) for this '
                    'iteration. If you are intentionally only using a '
                    'subset, call step with ignore_stale_grad=True to '
                    'suppress this warning and skip updating of '
                    'Parameters with stale gradient' % (
                        param.name, str(data.context)))

    def step(self, batch_size, ignore_stale_grad=False):
        """Aggregate gradients and apply one optimizer update.
        Reference trainer.py:116."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size

        for i, param in enumerate(self._params):
            if param.grad_req == 'null':
                continue
            if not ignore_stale_grad:
                self._assert_fresh(param)

            store = self._kvstore
            if store:
                store.push(i, param.list_grad(), priority=-i)
                if self._update_on_kvstore:
                    # server-side update: fetch fresh weights, done
                    store.pull(i, param.list_data(), priority=-i)
                    continue
                store.pull(i, param.list_grad(), priority=-i)

            for updater, weight, grad in zip(
                    self._updaters, param.list_data(), param.list_grad()):
                if not ignore_stale_grad or not weight._fresh_grad:
                    updater(i, grad, weight)
                    weight._fresh_grad = True
        # arm staleness tracking for the next backward
        for param in self._params:
            for data in param.list_data():
                data._fresh_grad = True

    def save_states(self, fname):
        """Reference trainer.py:162."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            blob = self._updaters[0].get_states(dump_optimizer=True)
            with open(fname, 'wb') as fout:
                fout.write(blob)

    def load_states(self, fname):
        """Reference trainer.py:178."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, 'rb') as f:
                blob = f.read()
            for updater in self._updaters:
                updater.set_states(blob)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
