"""Gluon Block / HybridBlock — define-by-run with optional compilation.

Reference: python/mxnet/gluon/block.py:115 (Block), :297 (HybridBlock —
_get_graph:348 traces hybrid_forward with Symbol proxies, _build_cache:375 →
CachedOp, _call_cached_op:388, deferred-shape param init), SymbolBlock.

TPU-native: ``hybridize()`` compiles the traced graph to ONE jitted XLA
computation (BASELINE.json's "hybridize → jit"). The cached graph executes
through the autograd tape as a single fused op (jax.vjp over the whole
graph), so ``loss.backward()`` gets one compiled backward too — this is
strictly stronger than the reference's CachedOp, which still dispatched
node-by-node through the engine (c_api_ndarray.cc:663-699).
"""
import copy
import threading

import numpy as np

import jax

from .. import autograd
from .. import ndarray as nd
from ..attribute import NameManager, Prefix
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..executor import _GraphProgram
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ['Block', 'HybridBlock', 'SymbolBlock']


class _BlockScope:
    """Name/parameter scoping (reference block.py:33)."""
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, 'value', None)
        if current is None:
            if prefix is None:
                prefix = NameManager.current().get(None, hint) + '_'
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = '%s%d_' % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = getattr(_BlockScope._current, 'value', None)
        _BlockScope._current.value = self
        self._name_scope = Prefix(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        self._name_scope.__exit__(ptype, value, trace)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope


def _flatten(args):
    if isinstance(args, NDArray):
        return [args], int(0)
    if isinstance(args, (list, tuple)):
        flat, fmts = [], []
        for i in args:
            arg, fmt = _flatten(i)
            flat.extend(arg)
            fmts.append(fmt)
        return flat, fmts
    return [args], None


def _regroup(args, fmt):
    if isinstance(fmt, int):
        if fmt == 0:
            return args[0], args[1:]
        return args[:fmt], args[fmt:]
    if fmt is None:
        return args[0], args[1:]
    ret = []
    for i in fmt:
        res, args = _regroup(args, i)
        ret.append(res)
    return ret, args


class Block:
    """Base building block (reference block.py:115)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ''
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith('_') \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = []

    def __repr__(self):
        s = '{name}(\n{modstr}\n)'
        modstr = '\n'.join(['  ({key}): {block}'.format(
            key=key, block=_indent(str(block), 2))
            for key, block in self.__dict__.items()
            if isinstance(block, Block)])
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.register_child(value)
        super().__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self):
        """Reference block.py:217 — this block's and all children's params."""
        ret = ParameterDict(self._params.prefix)
        ret.update(self.params)
        for cld in self._children:
            ret.update(cld.collect_params())
        return ret

    def save_params(self, filename):
        """Reference block.py:230."""
        strip_prefix = self.prefix if self._prefix.endswith('_') else ''
        self.collect_params().save(filename, strip_prefix=strip_prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        """Reference block.py:244."""
        restore_prefix = self.prefix if self._prefix.endswith('_') else ''
        self.collect_params().load(filename, ctx or current_context(),
                                   allow_missing, ignore_extra,
                                   restore_prefix=restore_prefix)

    def register_child(self, block):
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from ..initializer import Uniform
        self.collect_params().initialize(init or Uniform(), ctx, verbose,
                                         force_reinit)

    def hybridize(self, active=True):
        for cld in self._children:
            cld.hybridize(active)

    def cast(self, dtype):
        for child in self._children:
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError()


class HybridBlock(Block):
    """Reference block.py:297."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._reg_params = {}
        self._cached_graph = ()
        self._cached_op = None
        self._active = False

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, Parameter):
            assert name not in self._reg_params or \
                not isinstance(self._reg_params[name], Parameter), \
                'Overriding Parameter attribute %s is not allowed. ' \
                'Please pass in Parameters by specifying `params` at ' \
                'Block construction instead.'
            self._reg_params[name] = value

    def register_child(self, block):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                'Children of HybridBlock must also be HybridBlock, '
                'but %s has type %s.' % (str(block), str(type(block))))
        super().register_child(block)
        self._clear_cached_op()

    def hybridize(self, active=True):
        self._active = active
        self._clear_cached_op()
        super().hybridize(active)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def _clear_cached_op(self):
        self._cached_graph = ()
        self._cached_op = None

    def _get_graph(self, *args):
        """Trace hybrid_forward with Symbol proxies (reference block.py:348)."""
        if not self._cached_graph:
            from .. import symbol
            args, self._in_format = _flatten(args)
            if len(args) > 1:
                inputs = [symbol.var('data%d' % i) for i in range(len(args))]
            else:
                inputs = [symbol.var('data')]
            grouped_inputs = _regroup(inputs, self._in_format)[0]
            params = {i: j.var() for i, j in self._reg_params.items()}
            with self.name_scope():
                out = self.hybrid_forward(
                    symbol,
                    *(grouped_inputs if isinstance(grouped_inputs, (list, tuple))
                      else (grouped_inputs,)), **params)
            out, self._out_format = _flatten(out)
            self._cached_graph = inputs, symbol.Group(out)
        return self._cached_graph

    def infer_shape(self, *args):
        """Infer missing parameter shapes from inputs (reference :377)."""
        inputs, out = self._get_graph(*args)
        args, _ = _flatten(args)
        arg_shapes, _, aux_shapes = out.infer_shape_partial(
            **{i.name: j.shape for i, j in zip(inputs, args)})
        sdict = {i: j for i, j in zip(out.list_arguments(), arg_shapes)}
        sdict.update({name: shape for name, shape in
                      zip(out.list_auxiliary_states(), aux_shapes)})
        for _, v in self.collect_params().items():
            if v.name in sdict and sdict[v.name] is not None:
                v.shape = sdict[v.name]

    def _build_cache(self, *args):
        """Compile the traced graph into one jitted computation."""
        inputs, out = self._get_graph(*args)
        self._cached_prog = _GraphProgram(out)
        runner = self._cached_prog.make_runner()
        n_data = len(inputs)

        from ..executor import mirror_wrap

        @mirror_wrap
        def pure_fn(all_arrays, key):
            data_names = [i.name for i in inputs]
            arg_names = self._cached_prog.arg_names
            # all_arrays ordered: data inputs then non-data args then aux
            mapping = {}
            di = 0
            arg_arrays = []
            idx = 0
            for name in arg_names:
                arg_arrays.append(all_arrays[idx])
                idx += 1
            aux_arrays = list(all_arrays[idx:])
            outs, new_aux = runner(tuple(arg_arrays), tuple(aux_arrays), key,
                                   autograd.is_training())
            return outs + tuple(new_aux)

        self._cached_fn = jax.jit(pure_fn)
        # map canonical arg order -> source NDArray getter
        self._cached_arg_sources = []
        data_map = {inp.name: i for i, inp in enumerate(inputs)}
        params = {p.name: p for _, p in self.collect_params().items()}
        for name in self._cached_prog.arg_names:
            if name in data_map:
                self._cached_arg_sources.append(('data', data_map[name]))
            else:
                self._cached_arg_sources.append(('param', params[name]))
        self._cached_aux_sources = [params[name] for name in
                                    self._cached_prog.aux_names
                                    if name in params]
        self._cached_op = True

    def _call_cached_op(self, *args):
        """Execute the compiled graph as ONE tape op (reference :388)."""
        if self._cached_op is None:
            self._build_cache(*args)
        args_flat, fmt = _flatten(args)
        ctx = args_flat[0].context if args_flat else current_context()

        source_nds = []
        for kind, src in self._cached_arg_sources:
            if kind == 'data':
                source_nds.append(args_flat[src])
            else:
                source_nds.append(src.data(ctx))
        aux_nds = [p.data(ctx) for p in self._cached_aux_sources]

        all_arrays = tuple(a._data for a in source_nds + aux_nds)
        from .. import random as _random
        key = _random.next_key()

        n_out = len(self._cached_prog.outputs)
        recording = autograd.is_recording()
        if recording:
            outs_flat, vjp_fn = jax.vjp(
                lambda arrs: self._cached_fn(arrs, key), all_arrays)
            parents = [(a._node, a._out_idx) if a._node is not None else
                       ((a._leaf, 0) if a._leaf is not None else (None, 0))
                       for a in source_nds + aux_nds]

            def wrapped_vjp(cotangents):
                if not isinstance(cotangents, tuple):
                    cotangents = (cotangents,)
                (grads,) = vjp_fn(cotangents)
                return grads
            node = autograd.record_op(wrapped_vjp, parents,
                                      len(outs_flat), len(all_arrays))
            node.head_ids = [(o.shape, o.dtype) for o in outs_flat]
        else:
            outs_flat = self._cached_fn(all_arrays, key)
            node = None

        # write updated aux (BatchNorm moving stats) back to parameters
        for i, p in enumerate(self._cached_aux_sources):
            p.data(ctx)._data = outs_flat[n_out + i]

        outputs = []
        for i in range(n_out):
            r = NDArray(outs_flat[i], ctx)
            r._node = node
            r._out_idx = i
            outputs.append(r)
        ret, _ = _regroup(outputs, self._out_format)
        return ret

    def forward(self, x, *args):
        """Reference block.py:410."""
        if isinstance(x, NDArray):
            with x.context:
                if self._active:
                    try:
                        return self._call_cached_op(x, *args)
                    except DeferredInitializationError:
                        self._deferred_infer_init(x, *args)
                        return self._call_cached_op(x, *args)
                try:
                    params = {i: j.data(x.context)
                              for i, j in self._reg_params.items()}
                except DeferredInitializationError:
                    self._deferred_infer_init(x, *args)
                    params = {i: j.data(x.context)
                              for i, j in self._reg_params.items()}
                return self.hybrid_forward(nd, x, *args, **params)
        from .. import symbol
        assert isinstance(x, symbol.Symbol), \
            'HybridBlock requires the first argument to forward be either ' \
            'Symbol or NDArray, but got %s' % type(x)
        params = {i: j.var() for i, j in self._reg_params.items()}
        with self.name_scope():
            return self.hybrid_forward(symbol, x, *args, **params)

    def _deferred_infer_init(self, *args):
        self.infer_shape(*args)
        for _, i in self.collect_params().items():
            i._finish_deferred_init()

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError()


class SymbolBlock(HybridBlock):
    """Wrap a Symbol as a Block (reference block.py:459)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        from .. import symbol
        self._prefix = ''
        self._params = ParameterDict('', params)
        if isinstance(inputs, symbol.Symbol) and len(inputs.list_outputs()) == 1:
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if isinstance(outputs, (list, tuple)):
            outputs = symbol.Group(outputs)
        syms, self._in_format = _flatten(inputs)
        out, self._out_format = _flatten(outputs)
        out = symbol.Group(out)

        input_names = {i.name for i in syms}
        for i in out.list_arguments():
            if i not in input_names:
                self.params.get(i, allow_deferred_init=True)
        for i in out.list_auxiliary_states():
            self.params.get(i, grad_req='null', allow_deferred_init=True)

        self._cached_graph = syms, out

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            with x.context:
                try:
                    return self._call_cached_op(x, *args)
                except DeferredInitializationError:
                    self.infer_shape(x, *args)
                    for _, i in self.params.items():
                        i._finish_deferred_init()
                    return self._call_cached_op(x, *args)
        from .. import symbol
        assert isinstance(x, symbol.Symbol)
        ret = copy.copy(self._cached_graph[1])
        ret._compose(**{self._cached_graph[0][0].name: x})
        return _regroup(list(ret), self._out_format)[0]

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError()


def _indent(s_, num_spaces):
    s = str(s_).split('\n')
    if len(s) == 1:
        return s_
    first = s.pop(0)
    return first + '\n' + '\n'.join(' ' * num_spaces + line for line in s)
