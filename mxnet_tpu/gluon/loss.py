"""Gluon losses.

Reference: python/mxnet/gluon/loss.py — Loss base, L2Loss, L1Loss,
SigmoidBinaryCrossEntropyLoss, SoftmaxCrossEntropyLoss, KLDivLoss.
"""
from .block import HybridBlock

__all__ = ['Loss', 'L2Loss', 'L1Loss', 'SigmoidBinaryCrossEntropyLoss',
           'SigmoidBCELoss', 'SoftmaxCrossEntropyLoss', 'SoftmaxCELoss',
           'KLDivLoss', 'CTCLoss']


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """Reference loss.py:31."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (float, int)), 'weight must be a number'
        loss = loss * weight
    return loss


def _reshape_label_as_output(F, output, label):
    return F.reshape_like(label, output)


class Loss(HybridBlock):
    """Reference loss.py:49."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        s = '{name}(batch_axis={_batch_axis}, w={_weight})'
        return s.format(name=self.__class__.__name__, **self.__dict__)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError()


class L2Loss(Loss):
    """Reference loss.py:80: 0.5 * ||output - label||^2, mean over batch."""

    def __init__(self, weight=1., batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, output, label, sample_weight=None):
        label = _reshape_label_as_output(F, output, label)
        loss = F.square(output - label)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    """Reference loss.py:116."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, output, label, sample_weight=None):
        label = _reshape_label_as_output(F, output, label)
        loss = F.abs(output - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """Reference loss.py:152 (from_sigmoid variants, numerically stable)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, output, label, sample_weight=None):
        label = _reshape_label_as_output(F, output, label)
        if not self._from_sigmoid:
            max_val = F.relu(-output)
            loss = output - output * label + max_val + \
                F.log(F.exp(-max_val) + F.exp(-output - max_val))
        else:
            loss = -(F.log(output + 1e-12) * label +
                     F.log(1. - output + 1e-12) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Reference loss.py:224 (sparse_label / from_logits variants)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, output, label, sample_weight=None):
        if not self._from_logits:
            output = F.log_softmax(output, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(output, label, axis=self._axis, keepdims=True)
        else:
            loss = -F.sum(output * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """Reference loss.py:291."""

    def __init__(self, from_logits=True, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits

    def hybrid_forward(self, F, output, label, sample_weight=None):
        if not self._from_logits:
            output = F.log_softmax(output)
        loss = label * (F.log(label + 1e-12) - output)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class CTCLoss(Loss):
    """Connectionist Temporal Classification loss (reference loss.py:302).

    ``data`` is an unsoftmaxed activation tensor (``layout`` 'NTC' or
    'TNC'); ``label`` an index matrix ('NT' or 'TN'). With
    ``blank_label='first'`` (the contrib op default) index 0 is the
    blank, so label values are 1..alphabet_size-1. Label lengths come
    from ``label_lengths`` or the first occurrence of ``padding_mask``.
    Output shape (batch_size,).
    """

    def __init__(self, layout='NTC', label_layout='NT', padding_mask=-1,
                 weight=None, **kwargs):
        assert layout in ('NTC', 'TNC'), layout
        assert label_layout in ('NT', 'TN'), label_layout
        self._layout = layout
        self._label_layout = label_layout
        self._padding_mask = padding_mask
        batch_axis = label_layout.find('N')
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, data, label,
                       data_lengths=None, label_lengths=None,
                       sample_weight=None):
        if self._layout == 'NTC':
            data = F.swapaxes(data, 0, 1)
        if self._batch_axis == 1:
            label = F.swapaxes(label, 0, 1)
        kwargs = {'use_data_lengths': data_lengths is not None,
                  'use_label_lengths': label_lengths is not None}
        if self._padding_mask is not None:
            kwargs['padding_mask'] = self._padding_mask
        inputs = [data, label] + \
            [x for x in (data_lengths, label_lengths) if x is not None]
        loss = F.contrib.CTCLoss(*inputs, **kwargs)
        return _apply_weighting(F, loss, self._weight, sample_weight)
