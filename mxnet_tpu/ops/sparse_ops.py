"""Registry (symbol-world) forms of the sparse operators.

Reference: src/operator/tensor/cast_storage.cc, sparse_retain.cc.

The real sparse containers (RowSparse/CSR) live at the NDArray layer
(ndarray/sparse.py) — inside a compiled XLA program every operand is a
dense jax.Array, because TPU compute is dense-tiled. These registrations
give Symbol graphs the reference's op surface with faithful *dense
lowerings*: `cast_storage` is a storage-type annotation (value-identity),
and `_sparse_retain` zeroes every row not listed in `indices`, which is
exactly the dense image of the reference's sparse output.
"""
import jax.numpy as jnp

from .registry import register, register_alias


@register('cast_storage', param_defaults={'stype': 'default'})
def _cast_storage(attrs, x):
    """Value-identity in the dense symbol world; the NDArray-layer
    cast_storage (ndarray/sparse.py) performs the actual container
    conversion eagerly."""
    return x


@register('_sparse_retain', input_names=['data', 'indices'])
def _sparse_retain_op(attrs, data, indices):
    """Dense image of sparse_retain: out[i] = data[i] if i ∈ indices
    else 0 (reference sparse_retain-inl.h semantics on a row_sparse
    array whose every row is materialised). Differentiable: the vjp is
    the same row mask applied to the output gradient (reference
    _backward_sparse_retain)."""
    keep = jnp.zeros((data.shape[0],), bool).at[
        indices.astype(jnp.int32)].set(True, mode='drop')
    return jnp.where(keep.reshape((-1,) + (1,) * (data.ndim - 1)),
                     data, jnp.zeros_like(data))


register_alias('sparse_retain', '_sparse_retain')


@register('_square_sum', param_defaults={'axis': None, 'keepdims': False})
def _square_sum_op(attrs, x):
    """Dense form of square_sum (reference square_sum-inl.h): Σ x² along
    `axis`; the row-sparse-aware eager version is ndarray/sparse.py
    square_sum."""
    ax = attrs.get('axis', None)
    if isinstance(ax, (tuple, list)):
        ax = tuple(int(a) for a in ax)
        ax = ax if ax else None
    elif ax is not None:
        ax = int(ax)
    return jnp.sum(jnp.square(x), axis=ax,
                   keepdims=bool(attrs.get('keepdims', False)))
