"""Neural-network layer ops.

Reference: src/operator/{fully_connected,convolution,deconvolution,
batch_norm,pooling,activation,leaky_relu,dropout,lrn,l2_normalization,
instance_norm,softmax_output,make_loss,regression_output,sequence_*}-inl.h
and src/operator/nn/softmax.cc.

TPU-first notes: Convolution/FullyConnected lower to lax.conv_general_dilated
/ dot_general so XLA tiles them on the MXU; layouts stay NCHW at the API (the
reference's convention) and XLA's layout assignment re-tiles internally.
BatchNorm follows the aux-state protocol: it RETURNS updated moving stats as
extra outputs and the invoke layer writes them back (op_attr_types.h
FMutateInputs analog).
"""
import functools

import numpy as _np

import jax
import jax.numpy as jnp

from .registry import register, register_alias


# ---------------------------------------------------------------------------
# FullyConnected — reference fully_connected-inl.h:29-52 (linalg_gemm)
# ---------------------------------------------------------------------------
@register('FullyConnected', input_names=['data', 'weight', 'bias'],
          param_defaults={'num_hidden': 0, 'no_bias': False, 'flatten': True})
def _fully_connected(attrs, data, weight, bias=None):
    if attrs.get('flatten', True):
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    y = jax.lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if bias is not None and not attrs.get('no_bias', False):
        y = y + bias
    return y


def _fc_arg_names(attrs):
    if attrs and attrs.get('no_bias', False):
        return ['data', 'weight']
    return ['data', 'weight', 'bias']


# ---------------------------------------------------------------------------
# Convolution — reference convolution-inl.h (im2col+gemm) / cudnn. Here:
# one lax.conv_general_dilated call == the whole MXU-tiled conv.
# ---------------------------------------------------------------------------
def _pair(v, n=2):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v if len(v) == n else v * n


@register('Convolution', input_names=['data', 'weight', 'bias'],
          param_defaults={'kernel': (), 'stride': (), 'dilate': (), 'pad': (),
                          'num_filter': 0, 'num_group': 1, 'no_bias': False,
                          'workspace': 1024, 'cudnn_tune': None,
                          'cudnn_off': False, 'layout': None})
def _convolution(attrs, data, weight, bias=None):
    kernel = tuple(attrs['kernel'])
    nd = len(kernel)
    stride = tuple(attrs.get('stride') or (1,) * nd)
    dilate = tuple(attrs.get('dilate') or (1,) * nd)
    pad = tuple(attrs.get('pad') or (0,) * nd)
    groups = int(attrs.get('num_group', 1))

    if nd == 1:  # lift 1D conv to 2D (reference does the same via mshadow)
        data2 = data[:, :, None, :]
        w2 = weight[:, :, None, :]
        out = _conv_nd(data2, w2, (1,) + stride, (1,) + dilate, (0,) + pad, groups)
        out = out[:, :, 0, :]
    else:
        out = _conv_nd(data, weight, stride, dilate, pad, groups)
    if bias is not None and not attrs.get('no_bias', False):
        out = out + bias.reshape((1, -1) + (1,) * (out.ndim - 2))
    return out


def _channels_last_conv(data, weight, w_layout, **conv_kwargs):
    """Run a conv with channels-last compute behind the NCHW API (the
    reference's convention). Measured 1.3x faster fwd+bwd than
    logical-NCHW dimension_numbers on v5e: XLA's layout assignment
    handles the NHWC gradient convs far better, and the boundary
    transposes are pushed/cancelled between adjacent convs
    (elementwise/broadcast ops commute with them).

    ``w_layout`` is the weight's leading-dims order, 'OI' (Convolution)
    or 'IO' (Deconvolution). No preferred_element_type anywhere: jax's
    conv transpose rule can't mix an f32 cotangent with bf16 operands,
    and XLA:TPU accumulates bf16 convs in f32 on the MXU regardless."""
    nd = data.ndim - 2
    # NCHW -> NHWC / NCDHW -> NDHWC
    to_last = (0,) + tuple(range(2, nd + 2)) + (1,)
    to_first = (0, nd + 1) + tuple(range(1, nd + 1))
    io = (1, 0) if w_layout == 'OI' else (0, 1)      # -> <sp>IO
    w_last = tuple(range(2, nd + 2)) + io
    dn = ('NHWC', 'HWIO', 'NHWC') if nd == 2 else ('NDHWC', 'DHWIO', 'NDHWC')
    out = jax.lax.conv_general_dilated(
        jnp.transpose(data, to_last), jnp.transpose(weight, w_last),
        dimension_numbers=dn, **conv_kwargs).astype(data.dtype)
    return jnp.transpose(out, to_first)


def _bn_onepass():
    from ..config import flags as _flags
    _flags.reload('MXTPU_BN_ONEPASS')  # read at trace time only; the
    # parity tests flip it between fresh program builds in one process
    return _flags.get('MXTPU_BN_ONEPASS')


def _conv_nd(data, weight, stride, dilate, pad, groups):
    from ..config import flags as _flags
    if (_flags.get('MXTPU_CONV_STEM_S2D') and groups == 1
            and data.ndim == 4 and data.shape[1] <= 4
            and min(stride) > 1 and dilate == (1,) * len(dilate)):
        return _conv2d_stem_s2d(data, weight, stride, pad)
    if (_flags.get('MXTPU_CONV_BWD_PATCHES') and groups == 1
            and data.ndim == 4):
        return _conv2d_patches_bwd(data, weight, stride, dilate, pad)
    return _channels_last_conv(
        data, weight, 'OI', window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        feature_group_count=groups)


def _conv2d_stem_s2d(data, weight, stride, pad):
    """Thin-input strided conv as space-to-depth + stride-1 conv.

    The image-network stem (ResNet 7x7/s2, AlexNet 11x11/s4,
    Inception 3x3/s2 — all cin=3) is the worst conv shape on the MXU:
    3 input channels leave the 128x128 systolic array ~98% idle and the
    stride-2 footprint defeats XLA's tiling (measured 11-13% MFU,
    docs/tpu_artifacts/conv_breakdown_*.json). Re-expressing it over
    the s-strided phase decomposition x2[qh, qw, c*s^2 + rh*s + rw] =
    x[s*qh+rh, s*qw+rw] turns it into a dense stride-1 conv with
    cin*s^2 channels — exactly the MLPerf-ResNet space-to-depth trick,
    derived here as a pure reparametrization (no train-recipe change):

      y[p] = sum_j w[j] x[s*p + j - p0]          (original, per dim)

    Shift the kernel by d = (-p0) mod s so p0+d = s*P, split the tap
    index j+d = s*t + r; then y[p] = sum_{t,r} w'[s*t+r] x2[p+t-P, r]
    — a T-tap stride-1 conv over q with T = ceil((k+d)/s). Zero-padded
    taps add (T*s/k)^2-fold nominal FLOPs on a shape whose utilization
    improves by much more (A/B'd on chip; opt-in MXTPU_CONV_STEM_S2D).
    Backward needs no custom rule: the transforms are linear jnp ops,
    and the weight gradient of the stride-1 conv flows back through
    their transpose onto the original 7x7 layout.
    """
    N, C, H, W = data.shape
    O = weight.shape[0]
    sh, sw = stride
    kh, kw = int(weight.shape[2]), int(weight.shape[3])
    ph, pw = pad
    out_h = (H + 2 * ph - kh) // sh + 1
    out_w = (W + 2 * pw - kw) // sw + 1

    def _geom(k, s, p, size, out):
        d = (-p) % s                  # kernel left-shift to align phases
        P = (p + d) // s              # q-space left margin
        T = -((k + d) // -s)          # taps over q (ceil)
        lo = s * P                    # input left pad
        hi = s * (out - 1 + T - P) - size  # right pad to cover last tap
        hi = max(hi, 0)
        hi += (s - (lo + size + hi) % s) % s  # phase split needs s | len
        return d, T, lo, hi

    dh, Th, lo_h, hi_h = _geom(kh, sh, ph, H, out_h)
    dw, Tw, lo_w, hi_w = _geom(kw, sw, pw, W, out_w)

    x = jnp.pad(data, ((0, 0), (0, 0), (lo_h, hi_h), (lo_w, hi_w)))
    qh, qw = x.shape[2] // sh, x.shape[3] // sw
    x = x.reshape(N, C, qh, sh, qw, sw)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4)).reshape(N, C * sh * sw, qh, qw)

    w = jnp.pad(weight, ((0, 0), (0, 0),
                         (dh, Th * sh - kh - dh), (dw, Tw * sw - kw - dw)))
    w = w.reshape(O, C, Th, sh, Tw, sw)
    w = jnp.transpose(w, (0, 1, 3, 5, 2, 4)).reshape(O, C * sh * sw, Th, Tw)

    out = _channels_last_conv(
        x, w, 'OI', window_strides=(1, 1), padding=[(0, 0), (0, 0)],
        rhs_dilation=(1, 1), feature_group_count=1)
    return out[:, :, :out_h, :out_w]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _conv2d_patches_bwd(data, weight, stride, dilate, pad):
    """Conv2d whose WEIGHT gradient is an explicit patches-matmul.

    The measured MFU gap (docs/perf.md:34) is XLA's grad-weight conv at
    small spatial sizes: conv_backprop_filter becomes a long skinny
    contraction the MXU tiles poorly. im2col + dot_general instead
    turns it into one large (C*kh*kw, N*H'*W') x (N*H'*W', O) matmul —
    the shape the MXU is built for. Data gradient stays the standard
    transposed conv (XLA is already good at it). Opt-in via
    MXTPU_CONV_BWD_PATCHES=1; numerics parity-tested vs the plain path
    (tests/unittest/test_conv_patches.py)."""
    return _channels_last_conv(
        data, weight, 'OI', window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        feature_group_count=1)


def _conv2d_patches_fwd(data, weight, stride, dilate, pad):
    out = _conv2d_patches_bwd(data, weight, stride, dilate, pad)
    return out, (data, weight)


def _conv2d_patches_rev(stride, dilate, pad, res, gout):
    data, weight = res
    padding = [(p, p) for p in pad]

    # grad wrt data: transposed conv, same as the default rule
    def fwd_data(d):
        return _channels_last_conv(
            d, weight, 'OI', window_strides=stride, padding=padding,
            rhs_dilation=dilate, feature_group_count=1)
    g_data = jax.vjp(fwd_data, data)[1](gout)[0]

    # grad wrt weight: im2col patches, one big MXU matmul.
    # patches: (N, C*kh*kw, H', W') with feature dim ordered (C, kh, kw)
    kh, kw = int(weight.shape[2]), int(weight.shape[3])
    patches = jax.lax.conv_general_dilated_patches(
        data, filter_shape=(kh, kw), window_strides=stride,
        padding=padding, rhs_dilation=dilate,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    # contract batch+space of patches (N,CKK,H',W') with gout (N,O,H',W')
    g_w = jax.lax.dot_general(
        patches, gout,
        dimension_numbers=(((0, 2, 3), (0, 2, 3)), ((), ())),
        preferred_element_type=jnp.float32)          # (CKK, O)
    c = int(weight.shape[1])
    g_w = g_w.reshape(c, kh, kw, g_w.shape[-1])      # (C,kh,kw,O)
    g_w = jnp.transpose(g_w, (3, 0, 1, 2)).astype(weight.dtype)
    return g_data, g_w


_conv2d_patches_bwd.defvjp(_conv2d_patches_fwd, _conv2d_patches_rev)


@register('Deconvolution', input_names=['data', 'weight', 'bias'],
          param_defaults={'kernel': (), 'stride': (), 'dilate': (), 'pad': (),
                          'adj': (), 'target_shape': (), 'num_filter': 0,
                          'num_group': 1, 'no_bias': True, 'workspace': 512})
def _deconvolution(attrs, data, weight, bias=None):
    """Reference deconvolution-inl.h — conv transpose = gradient of conv."""
    kernel = tuple(attrs['kernel'])
    nd = len(kernel)
    stride = tuple(attrs.get('stride') or (1,) * nd)
    dilate = tuple(attrs.get('dilate') or (1,) * nd)
    pad = tuple(attrs.get('pad') or (0,) * nd)
    groups = int(attrs.get('num_group', 1))
    adj = tuple(attrs.get('adj') or (0,) * nd)

    # weight layout is (in_ch, out_ch/g, *kernel) in MXNet deconv; the
    # kernel must be spatially flipped: deconv is the input-gradient of
    # the (correlation-style) forward conv, which correlates against the
    # reversed kernel (deconvolution-inl.h pack_col2im == conv backward)
    weight = weight[(slice(None), slice(None)) +
                    (slice(None, None, -1),) * nd]
    if groups > 1:
        # jax wants rhs (C/g, F, *k) with the O dim group-major; mxnet
        # stores (C, F/g, *k) with groups stacked along C
        C = weight.shape[0]
        fpg = weight.shape[1]
        w = weight.reshape((groups, C // groups, fpg) + kernel)
        w = jnp.moveaxis(w, 0, 1)  # (C/g, g, F/g, *k)
        weight = w.reshape((C // groups, groups * fpg) + kernel)
    pads = []
    for k, s, p, d, a in zip(kernel, stride, pad, dilate, adj):
        eff_k = (k - 1) * d + 1
        pads.append((eff_k - 1 - p, eff_k - 1 - p + a))
    out = _channels_last_conv(
        data, weight, 'IO', window_strides=(1,) * nd, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate,
        feature_group_count=groups)
    if bias is not None and not attrs.get('no_bias', True):
        out = out + bias.reshape((1, -1) + (1,) * (out.ndim - 2))
    return out


# ---------------------------------------------------------------------------
# Pooling — reference pooling-inl.h; lax.reduce_window == the pool kernel
# ---------------------------------------------------------------------------
@register('Pooling',
          param_defaults={'kernel': (), 'pool_type': 'max', 'stride': (),
                          'pad': (), 'global_pool': False,
                          'pooling_convention': 'valid', 'cudnn_off': False})
def _pooling(attrs, data):
    nd = data.ndim - 2
    if attrs.get('global_pool', False):
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = tuple(attrs['kernel'])
        stride = tuple(attrs.get('stride') or (1,) * nd)
        pad = tuple(attrs.get('pad') or (0,) * nd)
    ptype = attrs.get('pool_type', 'max')
    full = attrs.get('pooling_convention', 'valid') == 'full'

    window = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = [(0, 0), (0, 0)]
    for i, p in enumerate(pad):
        hi = p
        if full:
            # ceil-mode: add extra padding on the high side if needed
            size = data.shape[2 + i] + 2 * p
            rem = (size - kernel[i]) % stride[i]
            if rem:
                hi = p + (stride[i] - rem)
        pads.append((p, hi))

    if ptype == 'max':
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(data, _np.asarray(init, data.dtype),
                                     jax.lax.max, window, strides, pads)
    if ptype in ('avg', 'sum'):
        s = jax.lax.reduce_window(data, _np.asarray(0, data.dtype),
                                  jax.lax.add, window, strides, pads)
        if ptype == 'sum':
            return s
        # count_include_pad=True (the reference default for avg pooling)
        return s / _np.prod(kernel)
    raise ValueError('unknown pool_type ' + ptype)


register_alias('Pooling_v1', 'Pooling')


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------
@register('Activation', param_defaults={'act_type': 'relu'})
def _activation(attrs, x):
    act = attrs.get('act_type', 'relu')
    if act == 'relu':
        return jax.nn.relu(x)
    if act == 'sigmoid':
        return jax.nn.sigmoid(x)
    if act == 'tanh':
        return jnp.tanh(x)
    if act == 'softrelu':
        return jax.nn.softplus(x)
    if act == 'softsign':
        return x / (1 + jnp.abs(x))
    raise ValueError('unknown act_type ' + act)


@register('LeakyReLU', input_names=['data', 'gamma'],
          param_defaults={'act_type': 'leaky', 'slope': 0.25,
                          'lower_bound': 0.125, 'upper_bound': 0.334},
          needs_rng=True, train_aware=True)
def _leaky_relu(attrs, x, *rest):
    """Reference leaky_relu-inl.h: leaky/prelu/elu/rrelu."""
    act = attrs.get('act_type', 'leaky')
    key = rest[-1]
    if act == 'leaky':
        return jnp.where(x > 0, x, attrs.get('slope', 0.25) * x)
    if act == 'elu':
        s = attrs.get('slope', 0.25)
        return jnp.where(x > 0, x, s * (jnp.exp(x) - 1))
    if act == 'prelu':
        gamma = rest[0]
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 else gamma
        return jnp.where(x > 0, x, g * x)
    if act == 'rrelu':
        lo, hi = attrs.get('lower_bound', 0.125), attrs.get('upper_bound', 0.334)
        if attrs.get('__is_train__', False):
            slope = jax.random.uniform(key, (x.shape[1] if x.ndim > 1 else 1,),
                                       minval=lo, maxval=hi, dtype=x.dtype)
            s = slope.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 else slope
        else:
            s = (lo + hi) / 2.0
        return jnp.where(x > 0, x, s * x)
    raise ValueError('unknown act_type ' + act)


# ---------------------------------------------------------------------------
# BatchNorm — reference batch_norm-inl.h. Aux protocol: returns
# (y, updated_moving_mean, updated_moving_var); invoke writes the extra
# outputs back into the moving_mean/moving_var input NDArrays.
# ---------------------------------------------------------------------------
@register('BatchNorm',
          input_names=['data', 'gamma', 'beta', 'moving_mean', 'moving_var'],
          param_defaults={'eps': 1e-3, 'momentum': 0.9, 'fix_gamma': True,
                          'use_global_stats': False, 'output_mean_var': False,
                          'axis': 1, 'cudnn_off': False},
          aux_inputs=('moving_mean', 'moving_var'),
          mutate_inputs={3: 1, 4: 2}, num_visible_outputs=1,
          num_outputs=3, train_aware=True)
def _batch_norm(attrs, data, gamma, beta, moving_mean, moving_var):
    eps = attrs.get('eps', 1e-3)
    momentum = attrs.get('momentum', 0.9)
    axis = int(attrs.get('axis', 1)) % data.ndim
    fix_gamma = attrs.get('fix_gamma', True)
    use_global = attrs.get('use_global_stats', False) or not attrs.get('__is_train__', False)

    reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1 for i in range(data.ndim))

    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if use_global:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    else:
        x32 = data.astype(jnp.float32)
        if _bn_onepass():
            # one-pass SHIFTED moments: sum and sum-of-squares of
            # (x - pivot) reduce over the SAME read of x, so XLA's
            # multi-output fusion computes the stats in ONE HBM pass of
            # the activation instead of jnp.var's two (mean, then
            # (x-mean)^2 — a data dependency no compiler can
            # single-pass). The per-channel pivot (x's first element)
            # centers the accumulation near the mean, so the
            # E[x^2]-E[x]^2 cancellation operates at std-scale — no
            # precision loss even for large-mean f32 activations; var
            # is clamped at 0. Role of the reference's single-pass
            # CUDA stats kernel (src/operator/batch_norm.cu
            # BatchNormalizationUpdateOutput).
            n = x32.size // x32.shape[axis]
            pivot_idx = tuple(slice(None) if i == axis else 0
                              for i in range(x32.ndim))
            pivot = jax.lax.stop_gradient(x32[pivot_idx])
            xc = x32 - pivot.reshape(bshape)
            s1 = jnp.sum(xc, axis=reduce_axes)
            s2 = jnp.sum(xc * xc, axis=reduce_axes)
            m0 = s1 / n
            mean = pivot + m0
            var = jnp.maximum(s2 / n - m0 * m0, 0.0)
        else:               # MXTPU_BN_ONEPASS=0: the two-pass escape
            # hatch — byte-identical to the pre-flip default lowering
            # (pinned by test_bn_onepass.py), kept for A/B evidence
            mean = jnp.mean(x32, axis=reduce_axes)
            var = jnp.var(x32, axis=reduce_axes)
        new_mm = momentum * moving_mean + (1 - momentum) * mean.astype(moving_mean.dtype)
        new_mv = momentum * moving_var + (1 - momentum) * var.astype(moving_var.dtype)
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps).astype(data.dtype)
    y = (data - mean.astype(data.dtype).reshape(bshape)) * \
        (g * inv).reshape(bshape) + beta.reshape(bshape)
    return y, jax.lax.stop_gradient(new_mm), jax.lax.stop_gradient(new_mv)


register_alias('BatchNorm_v1', 'BatchNorm')


@register('InstanceNorm', input_names=['data', 'gamma', 'beta'],
          param_defaults={'eps': 1e-3})
def _instance_norm(attrs, x, gamma, beta):
    """Reference instance_norm-inl.h (normalize over spatial dims per sample/channel)."""
    eps = attrs.get('eps', 1e-3)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(bshape) + \
        beta.reshape(bshape)


@register('LayerNorm', input_names=['data', 'gamma', 'beta'],
          param_defaults={'axis': -1, 'eps': 1e-5})
def _layer_norm(attrs, x, gamma, beta):
    ax = int(attrs.get('axis', -1)) % x.ndim
    eps = attrs.get('eps', 1e-5)
    if ax == x.ndim - 1:
        from . import pallas_kernels as pk
        if pk.use_fused():
            return pk.fused_layernorm(x, gamma, beta, eps)
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=ax, keepdims=True)
    var = jnp.var(x32, axis=ax, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    bshape = tuple(x.shape[ax] if i == ax else 1 for i in range(x.ndim))
    return (y.astype(x.dtype) * gamma.reshape(bshape) + beta.reshape(bshape))


@register('L2Normalization', param_defaults={'eps': 1e-10, 'mode': 'instance'})
def _l2_normalization(attrs, x):
    """Reference l2_normalization-inl.h."""
    eps = attrs.get('eps', 1e-10)
    mode = attrs.get('mode', 'instance')
    if mode == 'instance':
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    elif mode == 'channel':
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / n


@register('LRN', param_defaults={'alpha': 1e-4, 'beta': 0.75, 'knorm': 2.0,
                                 'nsize': 5})
def _lrn(attrs, x):
    """Reference lrn-inl.h (cross-channel local response normalization)."""
    alpha, beta = attrs.get('alpha', 1e-4), attrs.get('beta', 0.75)
    knorm, nsize = attrs.get('knorm', 2.0), int(attrs.get('nsize', 5))
    sq = jnp.square(x)
    half = nsize // 2
    sq_pad = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (x.ndim - 2))
    window = (1, nsize) + (1,) * (x.ndim - 2)
    ssum = jax.lax.reduce_window(sq_pad, _np.asarray(0, x.dtype), jax.lax.add,
                                 window, (1,) * x.ndim,
                                 [(0, 0)] * x.ndim)
    return x * jnp.power(knorm + alpha / nsize * ssum, -beta)


# ---------------------------------------------------------------------------
# Dropout — reference dropout-inl.h; RNG key comes in as trailing arg
# ---------------------------------------------------------------------------
@register('Dropout', param_defaults={'p': 0.5, 'mode': 'training'},
          needs_rng=True, train_aware=True)
def _dropout(attrs, x, key):
    p = attrs.get('p', 0.5)
    training = attrs.get('__is_train__', False) or attrs.get('mode') == 'always'
    if not training or p <= 0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


# ---------------------------------------------------------------------------
# Softmax family — reference nn/softmax.cc + softmax_output-inl.h
# ---------------------------------------------------------------------------
@register('softmax', param_defaults={'axis': -1, 'temperature': None})
def _softmax(attrs, x):
    t = attrs.get('temperature', None)
    if t:
        x = x / t
    ax = int(attrs.get('axis', -1)) % x.ndim
    if ax == x.ndim - 1:
        from . import pallas_kernels as pk
        if pk.use_fused():
            return pk.fused_softmax(x)
    return jax.nn.softmax(x, axis=ax)


@register('log_softmax', param_defaults={'axis': -1, 'temperature': None})
def _log_softmax(attrs, x):
    t = attrs.get('temperature', None)
    if t:
        x = x / t
    return jax.nn.log_softmax(x, axis=int(attrs.get('axis', -1)))


@register('SoftmaxActivation', param_defaults={'mode': 'instance'})
def _softmax_activation(attrs, x):
    if attrs.get('mode', 'instance') == 'channel':
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


@register('softmax_cross_entropy', input_names=['data', 'label'])
def _softmax_cross_entropy(attrs, data, label):
    lab = label.astype(jnp.int32)
    from . import pallas_kernels as pk
    if pk.use_fused():
        # fused logsumexp+gather — never materializes softmax in HBM
        return pk.softmax_xent(data, lab).sum().astype(data.dtype)
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked)


@register('SoftmaxOutput', input_names=['data', 'label'],
          param_defaults={'grad_scale': 1.0, 'ignore_label': -1.0,
                          'multi_output': False, 'use_ignore': False,
                          'preserve_shape': False, 'normalization': 'null',
                          'out_grad': False, 'smooth_alpha': 0.0})
def _softmax_output(attrs, data, label):
    """Reference softmax_output-inl.h.

    Forward = softmax(data). The custom gradient (softmax - one_hot(label),
    scaled/masked per attrs) is wired via jax.custom_vjp so the imperative
    tape and the symbolic executor both get the reference's exact backward.
    """
    return _softmax_output_cvjp(data, label, _SoftmaxOutputCfg(attrs))


class _SoftmaxOutputCfg:
    """Hashable static config for the custom_vjp."""

    def __init__(self, attrs):
        self.grad_scale = attrs.get('grad_scale', 1.0)
        self.ignore_label = attrs.get('ignore_label', -1.0)
        self.use_ignore = attrs.get('use_ignore', False)
        self.multi_output = attrs.get('multi_output', False)
        self.normalization = attrs.get('normalization', 'null')
        self.smooth_alpha = attrs.get('smooth_alpha', 0.0)
        self._k = (self.grad_scale, self.ignore_label, self.use_ignore,
                   self.multi_output, self.normalization, self.smooth_alpha)

    def __hash__(self):
        return hash(self._k)

    def __eq__(self, other):
        return isinstance(other, _SoftmaxOutputCfg) and self._k == other._k


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_output_cvjp(data, label, cfg):
    return _softmax_fwd_impl(data, cfg)


def _softmax_fwd_impl(data, cfg):
    if cfg.multi_output:
        return jax.nn.softmax(data, axis=1)
    if data.ndim > 2:
        return jax.nn.softmax(data, axis=-1)
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(data, label, cfg):
    out = _softmax_fwd_impl(data, cfg)
    return out, (out, label)


def _softmax_output_bwd(cfg, res, g):
    out, label = res
    axis = 1 if cfg.multi_output else -1
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, out.shape[axis], axis=axis, dtype=out.dtype)
    smooth = cfg.smooth_alpha
    if smooth:
        k = out.shape[axis]
        onehot = onehot * (1 - smooth) + smooth / (k - 1) * (1 - onehot)
    grad = out - onehot
    if cfg.use_ignore:
        mask = (label != cfg.ignore_label).astype(out.dtype)
        mask = jnp.expand_dims(mask, axis if axis >= 0 else out.ndim - 1)
        grad = grad * mask
    scale = cfg.grad_scale
    if cfg.normalization == 'batch':
        scale = scale / out.shape[0]
    elif cfg.normalization == 'valid':
        if cfg.use_ignore:
            valid = jnp.maximum(jnp.sum((label != cfg.ignore_label)), 1)
        else:
            valid = label.size
        scale = scale / valid
    return (grad * scale, None)


_softmax_output_cvjp.defvjp(_softmax_output_fwd, _softmax_output_bwd)
register_alias('Softmax', 'SoftmaxOutput')


# ---------------------------------------------------------------------------
# Regression outputs & MakeLoss — reference regression_output-inl.h,
# make_loss-inl.h. Same custom-gradient trick.
# ---------------------------------------------------------------------------
def _make_regression(name, fwd, bwd):
    @_partial(jax.custom_vjp, nondiff_argnums=(2,))
    def op(data, label, grad_scale):
        return fwd(data)

    def op_fwd(data, label, grad_scale):
        return fwd(data), (fwd(data), label)

    def op_bwd(grad_scale, res, g):
        out, label = res
        n = out.shape[0]
        return (bwd(out, label) * (grad_scale / n), None)

    op.defvjp(op_fwd, op_bwd)

    @register(name, input_names=['data', 'label'],
              param_defaults={'grad_scale': 1.0})
    def wrapper(attrs, data, label):
        return op(data, label.reshape(data.shape), attrs.get('grad_scale', 1.0))
    return wrapper


_make_regression('LinearRegressionOutput', lambda x: x, lambda o, l: (o - l))
_make_regression('LogisticRegressionOutput', jax.nn.sigmoid, lambda o, l: (o - l))
_make_regression('MAERegressionOutput', lambda x: x, lambda o, l: jnp.sign(o - l))


@register('MakeLoss', param_defaults={'grad_scale': 1.0,
                                      'normalization': 'null',
                                      'valid_thresh': 0.0})
def _make_loss(attrs, x):
    """Reference make_loss-inl.h: forward=identity, backward=grad_scale."""
    scale = attrs.get('grad_scale', 1.0)
    if attrs.get('normalization') == 'batch':
        scale = scale / x.shape[0]
    elif attrs.get('normalization') == 'valid':
        scale = scale / jnp.maximum((x > attrs.get('valid_thresh', 0.0)).sum(), 1)
    return _make_loss_cvjp(x, scale)


@_partial(jax.custom_vjp, nondiff_argnums=())
def _make_loss_cvjp(x, scale):
    return x


def _make_loss_fwd(x, scale):
    # residual must be a jax pytree: carry the broadcast gradient itself
    # (shape/dtype objects are not valid leaves)
    return x, jnp.broadcast_to(jnp.asarray(scale, x.dtype), x.shape)


def _make_loss_bwd(res, g):
    return (res, None)


_make_loss_cvjp.defvjp(_make_loss_fwd, _make_loss_bwd)
register_alias('make_loss', 'MakeLoss')


@register('SVMOutput', input_names=['data', 'label'],
          param_defaults={'margin': 1.0, 'regularization_coefficient': 1.0,
                          'use_linear': False})
def _svm_output(attrs, data, label):
    """Reference svm_output-inl.h: forward is identity (scores)."""
    return _svm_cvjp(data, label, (attrs.get('margin', 1.0),
                                   attrs.get('regularization_coefficient', 1.0),
                                   attrs.get('use_linear', False)))


@_partial(jax.custom_vjp, nondiff_argnums=(2,))
def _svm_cvjp(data, label, cfg):
    return data


def _svm_fwd(data, label, cfg):
    return data, (data, label)


def _svm_bwd(cfg, res, g):
    margin, reg, linear = cfg
    data, label = res
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, data.shape[1], dtype=data.dtype)
    score_correct = jnp.take_along_axis(data, lab[:, None], axis=1)
    viol = (margin - (score_correct - data)) > 0
    if linear:
        gdata = jnp.where(viol, reg * jnp.ones_like(data), 0.0)
    else:
        gdata = jnp.where(viol, 2 * reg * (margin - (score_correct - data)), 0.0)
    gdata = gdata * (1 - onehot)
    gcorrect = -jnp.sum(gdata, axis=1, keepdims=True)
    gdata = gdata + gcorrect * onehot
    return (gdata, None)


_svm_cvjp.defvjp(_svm_fwd, _svm_bwd)


# ---------------------------------------------------------------------------
# Sequence ops — reference sequence_last/mask/reverse-inl.h
# ---------------------------------------------------------------------------
@register('SequenceLast', input_names=['data', 'sequence_length'],
          optional_inputs={'sequence_length': 'use_sequence_length'},
          param_defaults={'use_sequence_length': False, 'axis': 0})
def _sequence_last(attrs, data, seq_len=None):
    if not attrs.get('use_sequence_length', False) or seq_len is None:
        return data[-1]
    idx = (seq_len.astype(jnp.int32) - 1)
    batch = jnp.arange(data.shape[1])
    return data[idx, batch]


@register('SequenceMask', input_names=['data', 'sequence_length'],
          optional_inputs={'sequence_length': 'use_sequence_length'},
          param_defaults={'use_sequence_length': False, 'value': 0.0,
                          'axis': 0})
def _sequence_mask(attrs, data, seq_len=None):
    if not attrs.get('use_sequence_length', False) or seq_len is None:
        return data
    T = data.shape[0]
    mask = jnp.arange(T)[:, None] < seq_len.astype(jnp.int32)[None, :]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(attrs.get('value', 0.0), data.dtype))


@register('SequenceReverse', input_names=['data', 'sequence_length'],
          optional_inputs={'sequence_length': 'use_sequence_length'},
          param_defaults={'use_sequence_length': False, 'axis': 0})
def _sequence_reverse(attrs, data, seq_len=None):
    if not attrs.get('use_sequence_length', False) or seq_len is None:
        return jnp.flip(data, 0)
    T = data.shape[0]
    sl = seq_len.astype(jnp.int32)
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < sl[None, :], sl[None, :] - 1 - t, t)
    batch = jnp.arange(data.shape[1])[None, :]
    return data[src, batch]
