"""Shape-manipulation, indexing, init and control-flow ops.

Reference: src/operator/tensor/matrix_op.cc (Reshape/transpose/slice/clip/
repeat/tile/flip/Concat/stack), indexing_op.cc (take/one_hot/pick/
batch_take/gather_nd/Embedding grad path), init_op.cc (zeros/ones/arange),
control_flow.cc (where), src/operator/{concat,slice_channel,swapaxis,pad,
crop,upsampling}-inl.h.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..base import np_dtype
from .registry import register, register_alias


@register('Reshape', param_defaults={'shape': (), 'reverse': False,
                                     'target_shape': (), 'keep_highest': False})
def _reshape(attrs, x):
    """Reference matrix_op.cc Reshape incl. special codes 0,-1,-2,-3,-4
    (matrix_op-inl.h InferReshapeShape) and the deprecated legacy
    ``target_shape``/``keep_highest`` params (matrix_op-inl.h:159-182:
    0 = the one inferred dim, keep_highest pins dim0 to the input's)
    that 2017-era scripts like bi-lstm-sort's lstm.py:117 still use."""
    target = list(attrs.get('shape') or ())
    legacy = list(attrs.get('target_shape') or ())
    if not target and legacy:
        out = list(legacy)
        keep = attrs.get('keep_highest', False)
        if keep:
            out[0] = x.shape[0]
        start = 1 if keep else 0
        inferred = [i for i in range(start, len(out)) if out[i] == 0]
        if len(inferred) == 1:
            out[inferred[0]] = -1      # jnp.reshape infers the open dim
        return jnp.reshape(x, tuple(out))
    if attrs.get('reverse', False):
        # reverse semantics: match trailing dims first
        src = list(x.shape)[::-1]
        tgt = target[::-1]
        out = _infer_reshape(src, tgt)
        out = out[::-1]
    else:
        out = _infer_reshape(list(x.shape), target)
    return jnp.reshape(x, tuple(out))


def _infer_reshape(src, target):
    out = []
    src_idx = 0
    i = 0
    while i < len(target):
        t = target[i]
        if t == 0:
            out.append(src[src_idx]); src_idx += 1
        elif t == -1:
            out.append(-1); src_idx += 1
        elif t == -2:
            out.extend(src[src_idx:]); src_idx = len(src)
        elif t == -3:
            out.append(src[src_idx] * src[src_idx + 1]); src_idx += 2
        elif t == -4:
            a, b = target[i + 1], target[i + 2]
            cur = src[src_idx]
            if a == -1:
                a = cur // b
            if b == -1:
                b = cur // a
            out.extend([a, b]); src_idx += 1; i += 2
        else:
            out.append(t); src_idx += 1
        i += 1
    # resolve a single -1
    if -1 in out:
        known = int(np.prod([d for d in out if d != -1])) or 1
        total = int(np.prod(src)) if src else 1
        out[out.index(-1)] = total // known
    return out


register_alias('reshape', 'Reshape')


@register('reshape_like', input_names=['lhs', 'rhs'])
def _reshape_like(attrs, lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register('Flatten')
def _flatten(attrs, x):
    return jnp.reshape(x, (x.shape[0], -1))


register_alias('flatten', 'Flatten')


@register('transpose', param_defaults={'axes': ()})
def _transpose(attrs, x):
    axes = attrs.get('axes', ())
    return jnp.transpose(x, axes if axes else None)


@register('expand_dims', param_defaults={'axis': 0})
def _expand_dims(attrs, x):
    return jnp.expand_dims(x, int(attrs['axis']))


@register('squeeze', param_defaults={'axis': None})
def _squeeze(attrs, x):
    ax = attrs.get('axis', None)
    if isinstance(ax, int):
        ax = (ax,)
    return jnp.squeeze(x, ax)


@register('SwapAxis', param_defaults={'dim1': 0, 'dim2': 0})
def _swapaxis(attrs, x):
    return jnp.swapaxes(x, int(attrs['dim1']), int(attrs['dim2']))


register_alias('swapaxes', 'SwapAxis')


def _slice_tuple(attrs, ndim):
    begin, end = attrs['begin'], attrs['end']
    step = attrs.get('step', None) or (None,) * len(begin)
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return idx + (slice(None),) * (ndim - len(idx))


@register('slice', param_defaults={'begin': (), 'end': (), 'step': None})
def _slice(attrs, x):
    return x[_slice_tuple(attrs, x.ndim)]


register_alias('crop', 'slice')


@register('_slice_assign', input_names=['lhs', 'rhs'],
          param_defaults={'begin': (), 'end': (), 'step': None})
def _slice_assign(attrs, lhs, rhs):
    """Reference matrix_op.cc _slice_assign (alias _crop_assign):
    functional form of ``lhs[begin:end:step] = rhs``."""
    return lhs.at[_slice_tuple(attrs, lhs.ndim)].set(rhs)


register_alias('_crop_assign', '_slice_assign')


@register('_slice_assign_scalar',
          param_defaults={'scalar': 0.0, 'begin': (), 'end': (), 'step': None})
def _slice_assign_scalar(attrs, x):
    """Reference matrix_op.cc _slice_assign_scalar (alias
    _crop_assign_scalar): ``x[begin:end:step] = scalar``."""
    return x.at[_slice_tuple(attrs, x.ndim)].set(
        jnp.asarray(attrs['scalar'], dtype=x.dtype))


register_alias('_crop_assign_scalar', '_slice_assign_scalar')


@register('slice_axis', param_defaults={'axis': 0, 'begin': 0, 'end': None})
def _slice_axis(attrs, x):
    ax = int(attrs['axis']) % x.ndim
    idx = [slice(None)] * x.ndim
    idx[ax] = slice(attrs['begin'], attrs['end'])
    return x[tuple(idx)]


@register('slice_like', input_names=['data', 'shape_like'],
          param_defaults={'axes': ()})
def _slice_like(attrs, x, like):
    axes = attrs.get('axes', ()) or tuple(range(x.ndim))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, like.shape[a])
    return x[tuple(idx)]


@register('clip', param_defaults={'a_min': 0.0, 'a_max': 0.0})
def _clip(attrs, x):
    return jnp.clip(x, attrs['a_min'], attrs['a_max'])


@register('repeat', param_defaults={'repeats': 1, 'axis': None})
def _repeat(attrs, x):
    return jnp.repeat(x, int(attrs['repeats']), axis=attrs.get('axis', None))


@register('tile', param_defaults={'reps': ()})
def _tile(attrs, x):
    return jnp.tile(x, attrs['reps'])


@register('reverse', param_defaults={'axis': ()})
def _reverse(attrs, x):
    ax = attrs['axis']
    return jnp.flip(x, (ax,) if isinstance(ax, int) else tuple(ax))


register_alias('flip', 'reverse')


@register('Concat', variadic=True, key_var_num_args='num_args',
          param_defaults={'dim': 1, 'num_args': 0})
def _concat(attrs, *xs):
    """Reference src/operator/concat-inl.h."""
    return jnp.concatenate(xs, axis=int(attrs.get('dim', 1)))


register_alias('concat', 'Concat')


@register('stack', variadic=True, key_var_num_args='num_args',
          param_defaults={'axis': 0, 'num_args': 0})
def _stack(attrs, *xs):
    return jnp.stack(xs, axis=int(attrs.get('axis', 0)))


def _num_slice_outputs(attrs):
    return int(attrs.get('num_outputs', 1))


@register('SliceChannel', num_outputs=_num_slice_outputs,
          param_defaults={'num_outputs': 1, 'axis': 1, 'squeeze_axis': False})
def _slice_channel(attrs, x):
    """Reference src/operator/slice_channel-inl.h."""
    n = int(attrs['num_outputs'])
    ax = int(attrs.get('axis', 1))
    parts = jnp.split(x, n, axis=ax)
    if attrs.get('squeeze_axis', False):
        parts = [jnp.squeeze(p, ax) for p in parts]
    return tuple(parts)


register_alias('split', 'SliceChannel')


@register('where', input_names=['condition', 'x', 'y'])
def _where(attrs, cond, x, y):
    """Reference src/operator/tensor/control_flow.cc."""
    if cond.ndim < x.ndim and cond.ndim == 1:
        cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(cond != 0, x, y)


@register('take', input_names=['a', 'indices'],
          param_defaults={'axis': 0, 'mode': 'clip'})
def _take(attrs, a, indices):
    """Reference indexing_op.cc take."""
    mode = attrs.get('mode', 'clip')
    idx = indices.astype(jnp.int32)
    ax = int(attrs.get('axis', 0))
    if mode == 'wrap':
        idx = jnp.mod(idx, a.shape[ax])
    return jnp.take(a, idx, axis=ax, mode='clip')


@register('batch_take', input_names=['a', 'indices'])
def _batch_take(attrs, a, indices):
    idx = indices.astype(jnp.int32).ravel()
    return a[jnp.arange(a.shape[0]), idx]


@register('Embedding', input_names=['data', 'weight'],
          param_defaults={'input_dim': 0, 'output_dim': 0, 'dtype': 'float32'})
def _embedding(attrs, data, weight):
    """Reference indexing_op.cc Embedding (lookup = take on rows)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0, mode='clip')


@register('one_hot', param_defaults={'depth': 0, 'on_value': 1.0,
                                     'off_value': 0.0, 'dtype': 'float32'},
          differentiable=False)
def _one_hot(attrs, indices):
    return jax.nn.one_hot(indices.astype(jnp.int32), int(attrs['depth']),
                          dtype=np_dtype(attrs.get('dtype', 'float32'))) * \
        (attrs.get('on_value', 1.0) - attrs.get('off_value', 0.0)) + \
        attrs.get('off_value', 0.0)


@register('pick', input_names=['data', 'index'],
          param_defaults={'axis': -1, 'keepdims': False})
def _pick(attrs, data, index):
    ax = int(attrs.get('axis', -1)) % data.ndim
    idx = index.astype(jnp.int32)
    picked = jnp.take_along_axis(data, jnp.expand_dims(idx, ax), axis=ax)
    if not attrs.get('keepdims', False):
        picked = jnp.squeeze(picked, ax)
    return picked


@register('gather_nd', input_names=['data', 'indices'])
def _gather_nd(attrs, data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register('scatter_nd', input_names=['data', 'indices'],
          param_defaults={'shape': ()})
def _scatter_nd(attrs, data, indices):
    out = jnp.zeros(attrs['shape'], dtype=data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].add(data)


@register('Pad', param_defaults={'mode': 'constant', 'pad_width': (),
                                 'constant_value': 0.0})
def _pad(attrs, x):
    """Reference src/operator/pad.cc."""
    pw = attrs['pad_width']
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    mode = attrs.get('mode', 'constant')
    if mode == 'constant':
        return jnp.pad(x, pairs, mode='constant',
                       constant_values=attrs.get('constant_value', 0.0))
    if mode == 'edge':
        return jnp.pad(x, pairs, mode='edge')
    return jnp.pad(x, pairs, mode='reflect')


register_alias('pad', 'Pad')


@register('_zeros', param_defaults={'shape': (), 'dtype': 'float32'},
          differentiable=False, input_names=[])
def _zeros_op(attrs, *a):
    return jnp.zeros(attrs['shape'], dtype=np_dtype(attrs.get('dtype', 'float32')))


@register('_ones', param_defaults={'shape': (), 'dtype': 'float32'},
          differentiable=False, input_names=[])
def _ones_op(attrs, *a):
    return jnp.ones(attrs['shape'], dtype=np_dtype(attrs.get('dtype', 'float32')))


@register('_arange', param_defaults={'start': 0, 'stop': None, 'step': 1.0,
                                     'repeat': 1, 'dtype': 'float32'},
          differentiable=False, input_names=[])
def _arange_op(attrs, *a):
    arr = jnp.arange(attrs.get('start', 0), attrs.get('stop'),
                     attrs.get('step', 1.0),
                     dtype=np_dtype(attrs.get('dtype', 'float32')))
    r = int(attrs.get('repeat', 1))
    return jnp.repeat(arr, r) if r > 1 else arr


@register('UpSampling', variadic=True, key_var_num_args='num_args',
          param_defaults={'scale': 1, 'sample_type': 'nearest',
                          'num_args': 1, 'num_filter': 0})
def _upsampling(attrs, *xs):
    """Reference src/operator/upsampling-inl.h (nearest mode)."""
    scale = int(attrs['scale'])
    outs = []
    for x in xs:
        y = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        outs.append(y)
    if len(outs) == 1:
        return outs[0]
    return jnp.concatenate(outs, axis=1)


@register('Crop', variadic=True, key_var_num_args='num_args',
          param_defaults={'offset': (0, 0), 'h_w': (0, 0),
                          'center_crop': False, 'num_args': 1})
def _crop(attrs, *xs):
    """Reference src/operator/crop-inl.h (NCHW spatial crop)."""
    x = xs[0]
    if len(xs) == 2:
        h, w = xs[1].shape[2], xs[1].shape[3]
    else:
        h, w = attrs['h_w']
    if attrs.get('center_crop', False):
        oh = (x.shape[2] - h) // 2
        ow = (x.shape[3] - w) // 2
    else:
        oh, ow = attrs.get('offset', (0, 0))
    return x[:, :, oh:oh + h, ow:ow + w]


@register('space_to_depth', param_defaults={'block_size': 1})
def _space_to_depth(attrs, x):
    b = int(attrs['block_size'])
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // b, b, w // b, b)
    return y.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * b * b, h // b, w // b)


@register('depth_to_space', param_defaults={'block_size': 1})
def _depth_to_space(attrs, x):
    b = int(attrs['block_size'])
    n, c, h, w = x.shape
    y = x.reshape(n, b, b, c // (b * b), h, w)
    return y.transpose(0, 3, 4, 1, 5, 2).reshape(n, c // (b * b), h * b, w * b)


@register('_state_zeros', param_defaults={'shape': (), 'dtype': 'float32',
                                          'batch_axis': 0},
          input_names=['data'], differentiable=False)
def _state_zeros_op(attrs, data):
    """RNN begin-state zeros with the batch dim taken from `data`.

    The reference leaves batch as 0 in state_info shapes (rnn_cell.py
    state_info {'shape': (0, H)}) and lets bidirectional shape inference
    fill it; here inference is forward-only (jax.eval_shape), so the
    state explicitly depends on the input symbol instead."""
    shape = tuple(int(d) for d in attrs['shape'])
    b = data.shape[int(attrs.get('batch_axis', 0))]
    out = tuple(b if d == 0 else d for d in shape)
    return jnp.zeros(out, dtype=np_dtype(attrs.get('dtype', 'float32')))
