"""Linear algebra + ordering ops.

Reference: src/operator/tensor/dot.cc (dot/batch_dot incl. transpose flags),
la_op.cc (linalg_gemm/gemm2/potrf/potri/trsm/trmm/syrk/sumlogdiag),
ordering_op.cc (sort/argsort/topk).

dot/batch_dot lower to lax.dot_general → MXU. Orderings lower to lax.sort /
lax.top_k.
"""
import jax
import jax.numpy as jnp

from .registry import register, register_alias


@register('dot', input_names=['lhs', 'rhs'],
          param_defaults={'transpose_a': False, 'transpose_b': False})
def _dot(attrs, lhs, rhs):
    ta, tb = attrs.get('transpose_a', False), attrs.get('transpose_b', False)
    a = lhs.T if ta and lhs.ndim == 2 else (jnp.transpose(lhs) if ta else lhs)
    b = rhs.T if tb and rhs.ndim == 2 else (jnp.transpose(rhs) if tb else rhs)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    # MXNet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]),
                         preferred_element_type=jnp.float32).astype(a.dtype)


@register('batch_dot', input_names=['lhs', 'rhs'],
          param_defaults={'transpose_a': False, 'transpose_b': False})
def _batch_dot(attrs, lhs, rhs):
    a = jnp.swapaxes(lhs, -1, -2) if attrs.get('transpose_a', False) else lhs
    b = jnp.swapaxes(rhs, -1, -2) if attrs.get('transpose_b', False) else rhs
    return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)


@register('khatri_rao', variadic=True, key_var_num_args='num_args')
def _khatri_rao(attrs, *mats):
    """Reference contrib krprod.cc — column-wise Kronecker product."""
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum('ik,jk->ijk', out, m).reshape(-1, out.shape[1])
    return out


# linalg_* family (la_op.cc); operate on batched trailing 2D matrices
@register('_linalg_gemm', input_names=['A', 'B', 'C'],
          param_defaults={'transpose_a': False, 'transpose_b': False,
                          'alpha': 1.0, 'beta': 1.0, 'axis': -2})
def _linalg_gemm(attrs, A, B, C):
    a = jnp.swapaxes(A, -1, -2) if attrs.get('transpose_a', False) else A
    b = jnp.swapaxes(B, -1, -2) if attrs.get('transpose_b', False) else B
    return attrs.get('alpha', 1.0) * jnp.matmul(a, b) + attrs.get('beta', 1.0) * C


register_alias('linalg_gemm', '_linalg_gemm')


@register('_linalg_gemm2', input_names=['A', 'B'],
          param_defaults={'transpose_a': False, 'transpose_b': False,
                          'alpha': 1.0})
def _linalg_gemm2(attrs, A, B):
    a = jnp.swapaxes(A, -1, -2) if attrs.get('transpose_a', False) else A
    b = jnp.swapaxes(B, -1, -2) if attrs.get('transpose_b', False) else B
    return attrs.get('alpha', 1.0) * jnp.matmul(a, b)


register_alias('linalg_gemm2', '_linalg_gemm2')


@register('_linalg_potrf')
def _linalg_potrf(attrs, A):
    return jnp.linalg.cholesky(A)


register_alias('linalg_potrf', '_linalg_potrf')


@register('_linalg_potri')
def _linalg_potri(attrs, A):
    L = A
    n = L.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=L.dtype), L.shape)
    Linv = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)


register_alias('linalg_potri', '_linalg_potri')


@register('_linalg_trsm', input_names=['A', 'B'],
          param_defaults={'transpose': False, 'rightside': False, 'alpha': 1.0,
                          'lower': True})
def _linalg_trsm(attrs, A, B):
    t = attrs.get('transpose', False)
    lower = attrs.get('lower', True)
    a = jnp.swapaxes(A, -1, -2) if t else A
    lo = (not lower) if t else lower
    if attrs.get('rightside', False):
        x = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(B, -1, -2), lower=not lo)
        sol = jnp.swapaxes(x, -1, -2)
    else:
        sol = jax.scipy.linalg.solve_triangular(a, B, lower=lo)
    return attrs.get('alpha', 1.0) * sol


register_alias('linalg_trsm', '_linalg_trsm')


@register('_linalg_trmm', input_names=['A', 'B'],
          param_defaults={'transpose': False, 'rightside': False, 'alpha': 1.0,
                          'lower': True})
def _linalg_trmm(attrs, A, B):
    a = jnp.swapaxes(A, -1, -2) if attrs.get('transpose', False) else A
    tri = jnp.tril(a) if attrs.get('lower', True) != attrs.get('transpose', False) else jnp.triu(a)
    if attrs.get('rightside', False):
        return attrs.get('alpha', 1.0) * jnp.matmul(B, tri)
    return attrs.get('alpha', 1.0) * jnp.matmul(tri, B)


register_alias('linalg_trmm', '_linalg_trmm')


@register('_linalg_syrk', param_defaults={'transpose': False, 'alpha': 1.0})
def _linalg_syrk(attrs, A):
    a = jnp.swapaxes(A, -1, -2) if attrs.get('transpose', False) else A
    return attrs.get('alpha', 1.0) * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


register_alias('linalg_syrk', '_linalg_syrk')


@register('_linalg_sumlogdiag')
def _linalg_sumlogdiag(attrs, A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


register_alias('linalg_sumlogdiag', '_linalg_sumlogdiag')


# ---------------------------------------------------------------------------
# ordering — reference ordering_op.cc
# ---------------------------------------------------------------------------
@register('sort', param_defaults={'axis': -1, 'is_ascend': True})
def _sort(attrs, x):
    ax = attrs.get('axis', -1)
    if ax is None:
        x, ax = x.ravel(), 0
    y = jnp.sort(x, axis=int(ax))
    if not attrs.get('is_ascend', True):
        y = jnp.flip(y, int(ax))
    return y


@register('argsort', param_defaults={'axis': -1, 'is_ascend': True,
                                     'dtype': 'float32'},
          differentiable=False)
def _argsort(attrs, x):
    ax = attrs.get('axis', -1)
    if ax is None:
        x, ax = x.ravel(), 0
    idx = jnp.argsort(x, axis=int(ax))
    if not attrs.get('is_ascend', True):
        idx = jnp.flip(idx, int(ax))
    return idx.astype(jnp.float32)


def _topk_num_outputs(attrs):
    return 2 if attrs.get('ret_typ', 'indices') == 'both' else 1


@register('topk', num_outputs=_topk_num_outputs, differentiable=False,
          param_defaults={'axis': -1, 'k': 1, 'ret_typ': 'indices',
                          'is_ascend': False, 'dtype': 'float32'})
def _topk(attrs, x):
    ax = attrs.get('axis', -1)
    if ax is None:
        x, ax = x.ravel(), 0
    ax = int(ax) % x.ndim
    k = int(attrs.get('k', 1))
    ascend = attrs.get('is_ascend', False)
    xm = jnp.moveaxis(x, ax, -1)
    vals, idx = jax.lax.top_k(-xm if ascend else xm, k)
    if ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax)
    ret = attrs.get('ret_typ', 'indices')
    if ret == 'value':
        return vals
    if ret == 'both':
        return vals, idx.astype(jnp.float32)
    if ret == 'mask':
        mask = jnp.zeros_like(jnp.moveaxis(x, ax, -1))
        mask = mask.at[..., :].set(0)
        onehots = jax.nn.one_hot(jnp.moveaxis(idx, ax, -1), x.shape[ax],
                                 dtype=x.dtype).sum(-2)
        return jnp.moveaxis(onehots, -1, ax)
    return idx.astype(jnp.float32)


@register('_linalg_gelqf', num_outputs=2)
def _linalg_gelqf(attrs, A):
    """LQ factorization A = L @ Q, Q with orthonormal rows (reference
    la_op.cc gelqf, outputs [Q, L]); via QR of A^T on the MXU."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(q, -1, -2), jnp.swapaxes(r, -1, -2)


register_alias('linalg_gelqf', '_linalg_gelqf')
