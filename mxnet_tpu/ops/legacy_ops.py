"""Legacy registration names — completes the registry superset.

The reference registers ~110 legacy/alias names beyond the modern op
set: capitalized NDArray-function forms (src/operator/tensor/
elemwise_binary_op.cc `.add_alias("_Plus")` etc.), `_sample_*` alias
names (src/operator/random/sample_op.cc:50-148), `_sparse_*` alias
names, opencv host codecs (src/io/image_io.cc), legacy plugin bridges
(plugin/, src/operator/native_op.cc, ndarray_op.cc), Convolution_v1
(src/operator/convolution_v1.cc) and CuDNNBatchNorm
(src/operator/cudnn_batch_norm.cc). Here every one of those names
resolves: aliases point at the same OpDef; the rest are real
implementations (host ops for the codecs/bridges).
"""
import numpy as np

import jax
import jax.numpy as jnp

from . import registry as _reg
from ..base import MXNetError

# ---------------------------------------------------------------------------
# pure aliases: legacy name -> modern registration (same OpDef object)
# ---------------------------------------------------------------------------

_ALIASES = {
    # capitalized NDArray-function binary forms (elemwise_binary_op.cc)
    '_Plus': '_plus', '_Minus': '_minus', '_Mul': '_mul', '_Div': '_div',
    '_Mod': '_mod', '_Power': '_power', '_Maximum': '_maximum',
    '_Minimum': '_minimum', '_Hypot': '_hypot', '_Equal': '_equal',
    '_Not_Equal': '_not_equal', '_Greater': '_greater',
    '_Greater_Equal': '_greater_equal', '_Lesser': '_lesser',
    '_Lesser_Equal': '_lesser_equal',
    # ...and their scalar forms (elemwise_binary_scalar_op_*.cc)
    '_PlusScalar': '_plus_scalar', '_MinusScalar': '_minus_scalar',
    '_RMinusScalar': '_rminus_scalar', '_MulScalar': '_mul_scalar',
    '_DivScalar': '_div_scalar', '_RDivScalar': '_rdiv_scalar',
    '_ModScalar': '_mod_scalar', '_RModScalar': '_rmod_scalar',
    '_PowerScalar': '_power_scalar', '_RPowerScalar': '_rpower_scalar',
    '_MaximumScalar': '_maximum_scalar', '_MinimumScalar': '_minimum_scalar',
    '_HypotScalar': '_hypot_scalar', '_EqualScalar': '_equal_scalar',
    '_NotEqualScalar': '_not_equal_scalar', '_GreaterScalar': '_greater_scalar',
    '_GreaterEqualScalar': '_greater_equal_scalar',
    '_LesserScalar': '_lesser_scalar',
    '_LesserEqualScalar': '_lesser_equal_scalar',
    # broadcast arithmetic aliases (elemwise_binary_broadcast_op_basic.cc)
    'broadcast_plus': 'broadcast_add', 'broadcast_minus': 'broadcast_sub',
    # sampler alias names (sample_op.cc:50-148)
    '_sample_negbinomial': '_random_negative_binomial',
    '_sample_gennegbinomial': '_random_generalized_negative_binomial',
    # sparse alias names (storage-variant registrations; compute here is
    # the dense lowering per the sparse ADR)
    '_sparse_ElementWiseSum': 'add_n', '_sparse_add_n': 'add_n',
    '_sparse_elemwise_add': 'elemwise_add',
    '_sparse_cast_storage': 'cast_storage', '_sparse_dot': 'dot',
    '_sparse_slice': 'slice', '_sparse_zeros_like': 'zeros_like',
    # ctc loss contrib alias (contrib/ctc_loss.cc)
    '_contrib_ctc_loss': 'ctc_loss',
    # cudnn batch norm: same math, cudnn is a GPU implementation detail
    # (cudnn_batch_norm.cc) — XLA owns the kernel choice here
    'CuDNNBatchNorm': 'BatchNorm',
    # backward of broadcast_to = sum over the broadcast axes with
    # ReduceAxesParam, identical to `sum` (broadcast_reduce_op_value.cc:217)
    '_broadcast_backward': 'sum',
}

for _alias, _target in _ALIASES.items():
    _reg.register_alias(_alias, _target)


# ---------------------------------------------------------------------------
# real legacy ops
# ---------------------------------------------------------------------------

@_reg.register('Convolution_v1', input_names=['data', 'weight', 'bias'],
               param_defaults={'kernel': None, 'stride': None, 'dilate': None,
                               'pad': None, 'num_filter': 0, 'num_group': 1,
                               'workspace': 1024, 'no_bias': False,
                               'cudnn_tune': None, 'cudnn_off': False,
                               'layout': None})
def _convolution_v1(attrs, *arrays):
    """Legacy convolution (src/operator/convolution_v1.cc) — identical
    math to Convolution; v1 differed only in GPU workspace strategy."""
    return _reg.apply_op('Convolution', attrs, *arrays)


@_reg.register('_CrossDeviceCopy')
def _cross_device_copy(attrs, x):
    """Cross-device copy (src/operator/cross_device_copy.cc). Placement
    is expressed through shardings here; inside one program this is
    identity (XLA inserts the transfer)."""
    return x


@_reg.register('_NoGradient', differentiable=False)
def _no_gradient(attrs, x):
    """Gradient blocker (the reference's kNullOp grad convention)."""
    return jax.lax.stop_gradient(x)


# -- opencv host codecs (src/io/image_io.cc) --------------------------------

@_reg.register('_cvimdecode', host=True, differentiable=False,
               param_defaults={'flag': 1, 'to_rgb': True})
def _cvimdecode(attrs, buf):
    """Decode JPEG/PNG bytes to a uint8 HWC image (image_io.cc Imdecode;
    PIL replaces opencv)."""
    from ..image.image import imdecode
    raw = np.asarray(buf).astype(np.uint8).tobytes()
    img = imdecode(raw, to_rgb=bool(attrs.get('to_rgb', True)),
                   flag=int(attrs.get('flag', 1)))
    return jnp.asarray(np.asarray(img, np.uint8))


@_reg.register('_cvimread', host=True, differentiable=False, input_names=[],
               param_defaults={'filename': '', 'flag': 1, 'to_rgb': True})
def _cvimread(attrs, *_):
    """Read + decode an image file (image_io.cc Imread)."""
    filename = attrs.get('filename', '')
    with open(filename, 'rb') as f:
        raw = f.read()
    from ..image.image import imdecode
    img = imdecode(raw, to_rgb=bool(attrs.get('to_rgb', True)),
                   flag=int(attrs.get('flag', 1)))
    return jnp.asarray(np.asarray(img, np.uint8))


def _cvimresize_shape(attrs, in_shapes):
    s = in_shapes[0]
    return [(int(attrs['h']), int(attrs['w'])) + tuple(s[2:])], [None]


@_reg.register('_cvimresize', host=True, differentiable=False,
               shape_fn=_cvimresize_shape,
               param_defaults={'w': 0, 'h': 0, 'interp': 1})
def _cvimresize(attrs, src):
    """Resize an HWC image (image_io.cc Imresize; bilinear numpy)."""
    from ..image.image import imresize
    img = np.asarray(src)
    out = imresize(img.astype(np.float32), int(attrs['w']), int(attrs['h']),
                   interp=int(attrs.get('interp', 1)))
    if np.issubdtype(img.dtype, np.integer):
        info = np.iinfo(img.dtype)
        out = np.clip(np.rint(out), info.min, info.max)
    return jnp.asarray(out.astype(img.dtype))


def _cvborder_shape(attrs, in_shapes):
    s = in_shapes[0]
    out = (s[0] + int(attrs.get('top', 0)) + int(attrs.get('bot', 0)),
           s[1] + int(attrs.get('left', 0)) + int(attrs.get('right', 0)))
    return [out + tuple(s[2:])], [None]


@_reg.register('_cvcopyMakeBorder', host=True, differentiable=False,
               shape_fn=_cvborder_shape,
               param_defaults={'top': 0, 'bot': 0, 'left': 0, 'right': 0,
                               'type': 0, 'value': 0.0})
def _cvcopy_make_border(attrs, src):
    """Pad an HWC image with a constant border (image_io.cc
    copyMakeBorder; type 0 = cv2.BORDER_CONSTANT is the only mode the
    reference's io path uses)."""
    img = np.asarray(src)
    pad = ((int(attrs['top']), int(attrs['bot'])),
           (int(attrs['left']), int(attrs['right'])))
    if img.ndim == 3:
        pad = pad + ((0, 0),)
    out = np.pad(img, pad, mode='constant',
                 constant_values=float(attrs.get('value', 0.0)))
    return jnp.asarray(out)


# -- legacy python-callback bridges -----------------------------------------
# The reference passes C callback-struct pointers through the `info` attr
# (native_op.cc / ndarray_op.cc / custom_function.cc); here `info` keys a
# process-local table of live python objects (operator.py registers them).

_LEGACY_CALLBACKS = {}


def register_legacy_callback(obj):
    key = str(id(obj))
    _LEGACY_CALLBACKS[key] = obj
    return key


def _lookup_info(attrs, opname):
    key = str(attrs.get('info', ''))
    obj = _LEGACY_CALLBACKS.get(key)
    if obj is None:
        raise MXNetError(
            '%s: no live python operator for info=%r — construct the '
            'symbol through mx.operator.PythonOp/NDArrayOp.get_symbol() '
            'in this process' % (opname, key))
    return obj


def _legacy_forward(inst, arrays):
    np_in = [np.asarray(a, np.float32) for a in arrays]
    _, out_shapes = inst.infer_shape([list(a.shape) for a in np_in])
    out = [np.zeros(tuple(s), np.float32) for s in out_shapes]
    inst.forward(in_data=np_in, out_data=out)
    if len(out) == 1:
        return jnp.asarray(out[0])
    return tuple(jnp.asarray(o) for o in out)


def _legacy_shape(attrs, in_shapes):
    """shape_fn: delegate to the instance's infer_shape (the reference
    routes NativeOpProp::InferShape to the same python callback)."""
    inst = _lookup_info(attrs, 'legacy python op')
    _, out_shapes = inst.infer_shape([list(s) for s in in_shapes])
    return [tuple(s) for s in out_shapes], [np.float32] * len(out_shapes)


@_reg.register('_Native', host=True, variadic=True, shape_fn=_legacy_shape,
               train_aware=True, param_defaults={'info': ''})
def _native(attrs, *arrays):
    """Legacy numpy-callback op (src/operator/native_op.cc + the
    plugin's NativeOpInfo protocol): forward runs the registered
    PythonOp on host numpy buffers."""
    return _legacy_forward(_lookup_info(attrs, '_Native'), arrays)


def _native_backward(attrs, gouts, ins, outs):
    """legacy_backward hook (host_bridge): the user's python backward
    (reference NativeOpInfo.backward protocol)."""
    inst = _lookup_info(attrs, '_Native')
    np_in = [np.asarray(a, np.float32) for a in ins]
    np_out = [np.asarray(o, np.float32) for o in outs]
    np_gout = [np.asarray(g, np.float32) for g in gouts]
    in_grad = [np.zeros_like(a) for a in np_in]
    inst.backward(out_grad=np_gout, in_data=np_in, out_data=np_out,
                  in_grad=in_grad)
    return tuple(in_grad)


_reg.get('_Native').legacy_backward = _native_backward


@_reg.register('_NDArray', host=True, variadic=True, shape_fn=_legacy_shape,
               train_aware=True, param_defaults={'info': ''})
def _ndarray_op(attrs, *arrays):
    """Legacy NDArray-callback op (src/operator/ndarray_op.cc): like
    _Native but the callback sees NDArrays instead of numpy."""
    from ..ndarray.ndarray import NDArray
    inst = _lookup_info(attrs, '_NDArray')
    nd_in = [NDArray(jnp.asarray(a)) for a in arrays]
    _, out_shapes = inst.infer_shape([list(a.shape) for a in nd_in])
    from ..ndarray import zeros
    out = [zeros(tuple(s)) for s in out_shapes]
    inst.forward(in_data=nd_in, out_data=out)
    if len(out) == 1:
        return out[0]._data
    return tuple(o._data for o in out)


def _ndarray_backward(attrs, gouts, ins, outs):
    from ..ndarray.ndarray import NDArray
    from ..ndarray import zeros
    inst = _lookup_info(attrs, '_NDArray')
    nd_in = [NDArray(jnp.asarray(a)) for a in ins]
    nd_out = [NDArray(jnp.asarray(o)) for o in outs]
    nd_gout = [NDArray(jnp.asarray(g)) for g in gouts]
    in_grad = [zeros(tuple(a.shape)) for a in ins]
    inst.backward(out_grad=nd_gout, in_data=nd_in, out_data=nd_out,
                  in_grad=in_grad)
    return tuple(np.asarray(g._data, np.float32) for g in in_grad)


_reg.get('_NDArray').legacy_backward = _ndarray_backward


@_reg.register('_CustomFunction', host=True, differentiable=False,
               variadic=True, param_defaults={'info': ''})
def _custom_function(attrs, *arrays):
    """Imperative autograd Function bridge (src/operator/
    custom_function.cc): applies the registered Function's forward."""
    from ..ndarray.ndarray import NDArray
    inst = _lookup_info(attrs, '_CustomFunction')
    nd_in = [NDArray(jnp.asarray(a)) for a in arrays]
    out = inst.forward(*nd_in)
    if isinstance(out, (tuple, list)):
        return tuple(o._data for o in out)
    return out._data
