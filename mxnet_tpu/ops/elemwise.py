"""Elementwise unary/binary/scalar operator families.

Reference: src/operator/tensor/elemwise_unary_op.{cc,cu},
elemwise_binary_op.cc, elemwise_binary_broadcast_op*.cc,
elemwise_binary_scalar_op*.cc, mshadow_op.h (scalar functors).

Everything lowers to jnp primitives; XLA fuses chains of these into single
VPU kernels, which replaces the reference's Kernel<OP,xpu>::Launch
(mxnet_op.h:217) hand-rolled elementwise launcher.
"""
import jax
import jax.numpy as jnp
from jax.scipy.special import erf as _erf, gammaln as _gammaln

from .registry import register, register_alias

_F32_EPS = 1e-20


def _u(name, f, differentiable=True, aliases=()):
    @register(name, differentiable=differentiable)
    def op(attrs, x, _f=f):
        return _f(x)
    for a in aliases:
        register_alias(a, name)
    return op


# unary math (reference elemwise_unary_op.cc registration list)
_u('abs', jnp.abs)
_u('sign', jnp.sign)
_u('round', jnp.round)
_u('rint', jnp.rint)
_u('ceil', jnp.ceil)
_u('floor', jnp.floor)
_u('trunc', jnp.trunc)
_u('fix', jnp.trunc)
_u('square', jnp.square)
_u('sqrt', jnp.sqrt)
_u('rsqrt', lambda x: jax.lax.rsqrt(x))
_u('cbrt', jnp.cbrt)
_u('rcbrt', lambda x: 1.0 / jnp.cbrt(x))
_u('exp', jnp.exp)
_u('log', jnp.log)
_u('log10', jnp.log10)
_u('log2', jnp.log2)
_u('log1p', jnp.log1p)
_u('expm1', jnp.expm1)
_u('sin', jnp.sin)
_u('cos', jnp.cos)
_u('tan', jnp.tan)
_u('arcsin', jnp.arcsin)
_u('arccos', jnp.arccos)
_u('arctan', jnp.arctan)
_u('sinh', jnp.sinh)
_u('cosh', jnp.cosh)
_u('tanh', jnp.tanh)
_u('arcsinh', jnp.arcsinh)
_u('arccosh', jnp.arccosh)
_u('arctanh', jnp.arctanh)
_u('degrees', jnp.degrees)
_u('radians', jnp.radians)
_u('negative', jnp.negative)
_u('reciprocal', lambda x: 1.0 / x)
_u('sigmoid', jax.nn.sigmoid)
_u('softsign', lambda x: x / (1.0 + jnp.abs(x)))
_u('relu', jax.nn.relu)
_u('erf', _erf)
_u('gamma', lambda x: jnp.exp(_gammaln(x)))
_u('gammaln', _gammaln)
_u('logical_not', lambda x: (x == 0).astype(x.dtype))
_u('zeros_like', jnp.zeros_like, differentiable=False)
_u('ones_like', jnp.ones_like, differentiable=False)
_u('identity', lambda x: x, aliases=('_copy', 'stop_gradient_off'))
register_alias('_identity_with_attr_like_rhs', 'identity')


@register('BlockGrad')
def _block_grad(attrs, x):
    """Reference: elemwise_unary_op.cc BlockGrad / stop_gradient."""
    return jax.lax.stop_gradient(x)


register_alias('stop_gradient', 'BlockGrad')


@register('Cast', differentiable=True)
def _cast(attrs, x):
    from ..base import np_dtype
    return x.astype(np_dtype(attrs['dtype']))


register_alias('cast', 'Cast')


# binary broadcast family (reference elemwise_binary_broadcast_op_basic.cc)
def _b(name, f, differentiable=True, elem_alias=None):
    @register(name, input_names=['lhs', 'rhs'], differentiable=differentiable)
    def op(attrs, lhs, rhs, _f=f):
        return _f(lhs, rhs)
    if elem_alias:
        register_alias(elem_alias, name)
    return op


_b('broadcast_add', jnp.add, elem_alias='elemwise_add')
register_alias('_plus', 'broadcast_add')
register_alias('_add', 'broadcast_add')
_b('broadcast_sub', jnp.subtract, elem_alias='elemwise_sub')
register_alias('_minus', 'broadcast_sub')
register_alias('_sub', 'broadcast_sub')
_b('broadcast_mul', jnp.multiply, elem_alias='elemwise_mul')
register_alias('_mul', 'broadcast_mul')
_b('broadcast_div', jnp.divide, elem_alias='elemwise_div')
register_alias('_div', 'broadcast_div')
register_alias('_grad_add', 'broadcast_add')
_b('broadcast_mod', jnp.mod)
register_alias('_mod', 'broadcast_mod')
_b('broadcast_power', jnp.power)
register_alias('_power', 'broadcast_power')
register_alias('pow', 'broadcast_power')
_b('broadcast_maximum', jnp.maximum)
_b('broadcast_minimum', jnp.minimum)
_b('broadcast_hypot', jnp.hypot)
register_alias('_hypot', 'broadcast_hypot')
_b('_maximum', jnp.maximum)
_b('_minimum', jnp.minimum)


def _cmp(name, f):
    @register(name, input_names=['lhs', 'rhs'], differentiable=False)
    def op(attrs, lhs, rhs, _f=f):
        return _f(lhs, rhs).astype(lhs.dtype)
    return op


_cmp('broadcast_equal', jnp.equal)
_cmp('broadcast_not_equal', jnp.not_equal)
_cmp('broadcast_greater', jnp.greater)
_cmp('broadcast_greater_equal', jnp.greater_equal)
_cmp('broadcast_lesser', jnp.less)
_cmp('broadcast_lesser_equal', jnp.less_equal)
# same-shape elemwise comparison registrations (reference
# elemwise_binary_op_logic.cc _equal.._lesser_equal); broadcasting is a
# superset of the same-shape contract, so these alias the broadcast forms
for _elem in ('equal', 'not_equal', 'greater', 'greater_equal',
              'lesser', 'lesser_equal'):
    register_alias('_' + _elem, 'broadcast_' + _elem)
_cmp('broadcast_logical_and', lambda a, b: jnp.logical_and(a != 0, b != 0))
_cmp('broadcast_logical_or', lambda a, b: jnp.logical_or(a != 0, b != 0))
_cmp('broadcast_logical_xor', lambda a, b: jnp.logical_xor(a != 0, b != 0))


# scalar family (reference elemwise_binary_scalar_op_basic.cc)
def _s(name, f, differentiable=True):
    @register(name, param_defaults={'scalar': 0.0}, differentiable=differentiable)
    def op(attrs, x, _f=f):
        return _f(x, jnp.asarray(attrs['scalar'], dtype=x.dtype))
    return op


_s('_plus_scalar', jnp.add)
_s('_minus_scalar', jnp.subtract)
_s('_rminus_scalar', lambda x, s: s - x)
_s('_mul_scalar', jnp.multiply)
_s('_div_scalar', jnp.divide)
_s('_rdiv_scalar', lambda x, s: s / x)
_s('_mod_scalar', jnp.mod)
_s('_rmod_scalar', lambda x, s: jnp.mod(s, x))
_s('_power_scalar', jnp.power)
_s('_rpower_scalar', lambda x, s: jnp.power(s, x))
_s('_maximum_scalar', jnp.maximum)
_s('_minimum_scalar', jnp.minimum)
_s('_hypot_scalar', jnp.hypot)


def _scmp(name, f):
    @register(name, param_defaults={'scalar': 0.0}, differentiable=False)
    def op(attrs, x, _f=f):
        return _f(x, attrs['scalar']).astype(x.dtype)
    return op


_scmp('_equal_scalar', jnp.equal)
_scmp('_not_equal_scalar', jnp.not_equal)
_scmp('_greater_scalar', jnp.greater)
_scmp('_greater_equal_scalar', jnp.greater_equal)
_scmp('_lesser_scalar', jnp.less)
_scmp('_lesser_equal_scalar', jnp.less_equal)


@register('smooth_l1', param_defaults={'scalar': 1.0})
def _smooth_l1(attrs, x):
    """Reference: elemwise_binary_scalar_op_extended.cc smooth_l1."""
    sigma2 = attrs.get('scalar', 1.0) ** 2
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / sigma2, 0.5 * sigma2 * x * x, absx - 0.5 / sigma2)


@register('add_n', variadic=True, key_var_num_args='num_args')
def _add_n(attrs, *xs):
    """Reference: elemwise_sum.cc add_n/ElementWiseSum."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


register_alias('ElementWiseSum', 'add_n')
register_alias('_sum', 'add_n')
