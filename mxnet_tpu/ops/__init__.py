"""Operator library — importing this package registers all ops.

Reference analog: the static-initializer op registrations across
src/operator/*.cc collected by the NNVM registry at library load.
"""
from . import registry
from . import elemwise            # noqa: F401
from . import reduce_ops          # noqa: F401
from . import shape_ops           # noqa: F401
from . import nn                  # noqa: F401
from . import linalg_sort         # noqa: F401
from . import random_ops          # noqa: F401
from . import optimizer_ops       # noqa: F401
from . import rnn_ops             # noqa: F401
from . import contrib_ops         # noqa: F401
from . import sparse_ops          # noqa: F401
from . import legacy_ops          # noqa: F401  (alias/legacy names last)

from .registry import register, get, list_ops, exists
from . import pallas_kernels      # noqa: F401  (TPU kernels for hot ops)
from .pallas_kernels import (flash_attention, fused_rmsnorm,  # noqa: F401
                             fused_layernorm, softmax_xent)
