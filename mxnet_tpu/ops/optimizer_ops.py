"""Fused optimizer update ops.

Reference: src/operator/optimizer_op.cc — sgd_update, sgd_mom_update,
mp_sgd_update/mp_sgd_mom_update (fp16 master weights), adam_update,
rmsprop_update, rmspropalex_update, ftrl_update.

These mutate weight/state inputs in the reference (FMutateInputs); here each
returns the updated tensors and invoke() writes them back — under jit the
whole update fuses into one HBM-bandwidth-bound kernel per parameter.
"""
import jax.numpy as jnp

from .registry import register


def _rescale_clip(grad, attrs):
    g = grad * attrs.get('rescale_grad', 1.0)
    c = attrs.get('clip_gradient', -1.0)
    if c is not None and c > 0:
        g = jnp.clip(g, -c, c)
    return g


@register('sgd_update', input_names=['weight', 'grad'],
          param_defaults={'lr': 0.01, 'wd': 0.0, 'rescale_grad': 1.0,
                          'clip_gradient': -1.0},
          mutate_inputs={0: 0}, differentiable=False)
def _sgd_update(attrs, weight, grad):
    g = _rescale_clip(grad, attrs)
    return weight - attrs['lr'] * (g + attrs.get('wd', 0.0) * weight)


@register('sgd_mom_update', input_names=['weight', 'grad', 'mom'],
          param_defaults={'lr': 0.01, 'momentum': 0.0, 'wd': 0.0,
                          'rescale_grad': 1.0, 'clip_gradient': -1.0},
          mutate_inputs={0: 0, 2: 1}, num_visible_outputs=1, num_outputs=2,
          differentiable=False)
def _sgd_mom_update(attrs, weight, grad, mom):
    g = _rescale_clip(grad, attrs)
    new_mom = attrs.get('momentum', 0.0) * mom - \
        attrs['lr'] * (g + attrs.get('wd', 0.0) * weight)
    return weight + new_mom, new_mom


@register('mp_sgd_update', input_names=['weight', 'grad', 'weight32'],
          param_defaults={'lr': 0.01, 'wd': 0.0, 'rescale_grad': 1.0,
                          'clip_gradient': -1.0},
          mutate_inputs={0: 0, 2: 1}, num_visible_outputs=1, num_outputs=2,
          differentiable=False)
def _mp_sgd_update(attrs, weight, grad, weight32):
    g = _rescale_clip(grad.astype(jnp.float32), attrs)
    w32 = weight32 - attrs['lr'] * (g + attrs.get('wd', 0.0) * weight32)
    return w32.astype(weight.dtype), w32


@register('mp_sgd_mom_update',
          input_names=['weight', 'grad', 'mom', 'weight32'],
          param_defaults={'lr': 0.01, 'momentum': 0.0, 'wd': 0.0,
                          'rescale_grad': 1.0, 'clip_gradient': -1.0},
          mutate_inputs={0: 0, 2: 1, 3: 2}, num_visible_outputs=1,
          num_outputs=3, differentiable=False)
def _mp_sgd_mom_update(attrs, weight, grad, mom, weight32):
    g = _rescale_clip(grad.astype(jnp.float32), attrs)
    new_mom = attrs.get('momentum', 0.0) * mom - \
        attrs['lr'] * (g + attrs.get('wd', 0.0) * weight32)
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register('nag_mom_update', input_names=['weight', 'grad', 'mom'],
          param_defaults={'lr': 0.01, 'momentum': 0.0, 'wd': 0.0,
                          'rescale_grad': 1.0, 'clip_gradient': -1.0},
          mutate_inputs={0: 0, 2: 1}, num_visible_outputs=1, num_outputs=2,
          differentiable=False)
def _nag_mom_update(attrs, weight, grad, mom):
    """Nesterov momentum (reference optimizer_op.cc nag_mom_update):
    the lookahead gradient g + momentum * new_mom steps the weight."""
    g = _rescale_clip(grad, attrs) + attrs.get('wd', 0.0) * weight
    m = attrs.get('momentum', 0.0)
    new_mom = m * mom + g
    return weight - attrs['lr'] * (g + m * new_mom), new_mom


@register('adam_update', input_names=['weight', 'grad', 'mean', 'var'],
          param_defaults={'lr': 0.001, 'beta1': 0.9, 'beta2': 0.999,
                          'epsilon': 1e-8, 'wd': 0.0, 'rescale_grad': 1.0,
                          'clip_gradient': -1.0},
          mutate_inputs={0: 0, 2: 1, 3: 2}, num_visible_outputs=1,
          num_outputs=3, differentiable=False)
def _adam_update(attrs, weight, grad, mean, var):
    g = _rescale_clip(grad, attrs) + attrs.get('wd', 0.0) * weight
    b1, b2 = attrs.get('beta1', 0.9), attrs.get('beta2', 0.999)
    m = b1 * mean + (1 - b1) * g
    v = b2 * var + (1 - b2) * jnp.square(g)
    w = weight - attrs['lr'] * m / (jnp.sqrt(v) + attrs.get('epsilon', 1e-8))
    return w, m, v


@register('rmsprop_update', input_names=['weight', 'grad', 'n'],
          param_defaults={'lr': 0.001, 'gamma1': 0.95, 'epsilon': 1e-8,
                          'wd': 0.0, 'rescale_grad': 1.0,
                          'clip_gradient': -1.0, 'clip_weights': -1.0},
          mutate_inputs={0: 0, 2: 1}, num_visible_outputs=1, num_outputs=2,
          differentiable=False)
def _rmsprop_update(attrs, weight, grad, n):
    g = _rescale_clip(grad, attrs) + attrs.get('wd', 0.0) * weight
    g1 = attrs.get('gamma1', 0.95)
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    w = weight - attrs['lr'] * g / jnp.sqrt(new_n + attrs.get('epsilon', 1e-8))
    cw = attrs.get('clip_weights', -1.0)
    if cw and cw > 0:
        w = jnp.clip(w, -cw, cw)
    return w, new_n


@register('rmspropalex_update',
          input_names=['weight', 'grad', 'n', 'g', 'delta'],
          param_defaults={'lr': 0.001, 'gamma1': 0.95, 'gamma2': 0.9,
                          'epsilon': 1e-8, 'wd': 0.0, 'rescale_grad': 1.0,
                          'clip_gradient': -1.0, 'clip_weights': -1.0},
          mutate_inputs={0: 0, 2: 1, 3: 2, 4: 3}, num_visible_outputs=1,
          num_outputs=4, differentiable=False)
def _rmspropalex_update(attrs, weight, grad, n, g_state, delta):
    grd = _rescale_clip(grad, attrs) + attrs.get('wd', 0.0) * weight
    g1, g2 = attrs.get('gamma1', 0.95), attrs.get('gamma2', 0.9)
    new_n = (1 - g1) * jnp.square(grd) + g1 * n
    new_g = (1 - g1) * grd + g1 * g_state
    new_delta = g2 * delta - attrs['lr'] * grd / \
        jnp.sqrt(new_n - jnp.square(new_g) + attrs.get('epsilon', 1e-8))
    w = weight + new_delta
    cw = attrs.get('clip_weights', -1.0)
    if cw and cw > 0:
        w = jnp.clip(w, -cw, cw)
    return w, new_n, new_g, new_delta


@register('ftrl_update', input_names=['weight', 'grad', 'z', 'n'],
          param_defaults={'lr': 0.1, 'lamda1': 0.01, 'beta': 1.0, 'wd': 0.0,
                          'rescale_grad': 1.0, 'clip_gradient': -1.0},
          mutate_inputs={0: 0, 2: 1, 3: 2}, num_visible_outputs=1,
          num_outputs=3, differentiable=False)
def _ftrl_update(attrs, weight, grad, z, n):
    g = _rescale_clip(grad, attrs)
    lr, l1 = attrs['lr'], attrs.get('lamda1', 0.01)
    beta, wd = attrs.get('beta', 1.0), attrs.get('wd', 0.0)
    new_z = z + g - (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr * weight
    new_n = n + jnp.square(g)
    w = (jnp.sign(new_z) * l1 - new_z) / \
        ((beta + jnp.sqrt(new_n)) / lr + wd) * (jnp.abs(new_z) > l1)
    return w, new_z, new_n
