"""Contrib + vision-specific ops.

Reference: src/operator/contrib/ (ctc_loss, count_sketch, fft, dequantize,
multibox_*, proposal), roi_pooling.cc, spatial_transformer.cc,
bilinear_sampler.cc, grid_generator.cc, correlation.cc.
"""
import jax
import jax.numpy as jnp

from .registry import register, register_alias


# ---------------------------------------------------------------------------
# ROIPooling — reference src/operator/roi_pooling.cc
# ---------------------------------------------------------------------------
@register('ROIPooling', input_names=['data', 'rois'],
          param_defaults={'pooled_size': (0, 0), 'spatial_scale': 1.0})
def _roi_pooling(attrs, data, rois):
    ph, pw = attrs['pooled_size']
    scale = attrs.get('spatial_scale', 1.0)
    N, C, H, W = data.shape

    def pool_one(roi):
        batch = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[batch]
        ys = jnp.arange(H)
        xs = jnp.arange(W)
        out = jnp.full((C, ph, pw), -jnp.inf, dtype=data.dtype)
        for py in range(ph):
            for px in range(pw):
                ys_lo = y1 + (py * rh) // ph
                ys_hi = y1 + ((py + 1) * rh + ph - 1) // ph
                xs_lo = x1 + (px * rw) // pw
                xs_hi = x1 + ((px + 1) * rw + pw - 1) // pw
                mask = ((ys[:, None] >= ys_lo) & (ys[:, None] < ys_hi) &
                        (xs[None, :] >= xs_lo) & (xs[None, :] < xs_hi))
                vals = jnp.where(mask[None], img, -jnp.inf)
                out = out.at[:, py, px].set(jnp.max(vals, axis=(1, 2)))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(pool_one)(rois)


# ---------------------------------------------------------------------------
# BilinearSampler / GridGenerator / SpatialTransformer
# ---------------------------------------------------------------------------
def _bilinear_sample(data, grid):
    """data (N,C,H,W), grid (N,2,Ho,Wo) with coords in [-1,1]."""
    N, C, H, W = data.shape
    gx = (grid[:, 0] + 1) * (W - 1) / 2
    gy = (grid[:, 1] + 1) * (H - 1) / 2
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(img, yy, xx):
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        valid = ((yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1))
        vals = img[:, yi, xi]
        return jnp.where(valid[None], vals, 0.0)

    def sample_one(img, x0_, y0_, wx_, wy_):
        v00 = gather(img, y0_, x0_)
        v01 = gather(img, y0_, x0_ + 1)
        v10 = gather(img, y0_ + 1, x0_)
        v11 = gather(img, y0_ + 1, x0_ + 1)
        return (v00 * (1 - wx_)[None] * (1 - wy_)[None] +
                v01 * wx_[None] * (1 - wy_)[None] +
                v10 * (1 - wx_)[None] * wy_[None] +
                v11 * wx_[None] * wy_[None])

    return jax.vmap(sample_one)(data, x0, y0, wx, wy)


@register('BilinearSampler', input_names=['data', 'grid'])
def _bilinear_sampler(attrs, data, grid):
    return _bilinear_sample(data, grid)


@register('GridGenerator', input_names=['data'],
          param_defaults={'transform_type': 'affine', 'target_shape': (0, 0)})
def _grid_generator(attrs, data):
    th, tw = attrs['target_shape']
    if attrs.get('transform_type', 'affine') == 'affine':
        N = data.shape[0]
        theta = data.reshape(N, 2, 3)
        ys = jnp.linspace(-1, 1, th)
        xs = jnp.linspace(-1, 1, tw)
        gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        out = jnp.einsum('nij,jk->nik', theta, coords)  # (N,2,HW)
        return out.reshape(N, 2, th, tw)
    # warp type: data is flow field (N,2,H,W)
    N, _, H, W = data.shape
    ys = jnp.arange(H, dtype=data.dtype)
    xs = jnp.arange(W, dtype=data.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
    fx = (data[:, 0] + gx) * 2 / max(W - 1, 1) - 1
    fy = (data[:, 1] + gy) * 2 / max(H - 1, 1) - 1
    return jnp.stack([fx, fy], axis=1)


@register('SpatialTransformer', input_names=['data', 'loc'],
          param_defaults={'target_shape': (0, 0), 'transform_type': 'affine',
                          'sampler_type': 'bilinear'})
def _spatial_transformer(attrs, data, loc):
    grid = _grid_generator({'transform_type': 'affine',
                            'target_shape': attrs['target_shape']}, loc)
    return _bilinear_sample(data, grid)


# ---------------------------------------------------------------------------
# Correlation — reference correlation.cc (FlowNet-style)
# ---------------------------------------------------------------------------
@register('Correlation', input_names=['data1', 'data2'],
          param_defaults={'kernel_size': 1, 'max_displacement': 1, 'stride1': 1,
                          'stride2': 1, 'pad_size': 0, 'is_multiply': True})
def _correlation(attrs, a, b):
    d = int(attrs.get('max_displacement', 1))
    s2 = int(attrs.get('stride2', 1))
    mult = attrs.get('is_multiply', True)
    shifts = range(-d, d + 1, s2)
    outs = []
    for dy in shifts:
        for dx in shifts:
            shifted = jnp.roll(b, (dy, dx), axis=(2, 3))
            if mult:
                corr = jnp.mean(a * shifted, axis=1)
            else:
                corr = jnp.mean(jnp.abs(a - shifted), axis=1)
            outs.append(corr)
    return jnp.stack(outs, axis=1)


# ---------------------------------------------------------------------------
# contrib: FFT / count_sketch / dequantize / CTC
# ---------------------------------------------------------------------------
@register('_contrib_fft', param_defaults={'compute_size': 128})
def _fft(attrs, x):
    """Reference contrib/fft.cc — output interleaves re/im along last dim."""
    y = jnp.fft.fft(x.astype(jnp.complex64), axis=-1)
    out = jnp.stack([y.real, y.imag], axis=-1)
    return out.reshape(x.shape[:-1] + (2 * x.shape[-1],)).astype(x.dtype)


@register('_contrib_ifft', param_defaults={'compute_size': 128})
def _ifft(attrs, x):
    n = x.shape[-1] // 2
    xr = x.reshape(x.shape[:-1] + (n, 2))
    y = jnp.fft.ifft(xr[..., 0] + 1j * xr[..., 1], axis=-1)
    return (y.real * n).astype(x.dtype)


@register('_contrib_count_sketch', input_names=['data', 'h', 's'],
          param_defaults={'out_dim': 0, 'processing_batch_size': 32})
def _count_sketch(attrs, data, h, s):
    out_dim = int(attrs['out_dim'])
    idx = h.ravel().astype(jnp.int32)
    sign = s.ravel()
    out = jnp.zeros(data.shape[:-1] + (out_dim,), dtype=data.dtype)
    return out.at[..., idx].add(data * sign)


@register('_contrib_dequantize', input_names=['data', 'min_range', 'max_range'],
          param_defaults={'out_type': 'float32'}, differentiable=False)
def _dequantize(attrs, data, min_range, max_range):
    qmin = float(jnp.iinfo(jnp.int8).min) if data.dtype == jnp.int8 else 0.0
    qmax = float(jnp.iinfo(jnp.int8).max) if data.dtype == jnp.int8 else 255.0
    scale = (max_range - min_range) / (qmax - qmin)
    return (data.astype(jnp.float32) - qmin) * scale + min_range


@register('_contrib_CTCLoss',
          input_names=['data', 'label', 'data_lengths', 'label_lengths'],
          optional_inputs={'data_lengths': 'use_data_lengths',
                           'label_lengths': 'use_label_lengths'},
          param_defaults={'use_data_lengths': False, 'use_label_lengths': False,
                          'blank_label': 'first', 'padding_mask': None})
def _ctc_loss(attrs, data, label, *opt):
    """Reference contrib/ctc_loss.cc (warp-ctc). Forward-backward in log
    space via lax.scan. blank_label 'first' reserves index 0 for blank
    (labels 1..V-1), 'last' reserves V-1 (labels 0..V-2). Label lengths
    come from the label_lengths input (use_label_lengths), the first
    occurrence of padding_mask, or the count of non-blank-convention
    padding entries; data_lengths freezes the alpha recursion per sample
    past its length."""
    use_dl = attrs.get('use_data_lengths', False)
    use_ll = attrs.get('use_label_lengths', False)
    opt = [o for o in opt if o is not None]
    data_lengths = opt.pop(0) if use_dl and opt else None
    label_lengths = opt.pop(0) if use_ll and opt else None

    T, N, V = data.shape
    blank_first = attrs.get('blank_label', 'first') == 'first'
    blank = 0 if blank_first else V - 1
    logp = jax.nn.log_softmax(data, axis=-1)
    labels = label.astype(jnp.int32)  # (N, L)
    L = labels.shape[1]

    pad = attrs.get('padding_mask', None)
    if label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    elif pad is not None:
        is_pad = labels == int(pad)
        lab_len = jnp.where(is_pad.any(axis=1),
                            jnp.argmax(is_pad, axis=1), L)
    elif blank_first:
        lab_len = jnp.sum(labels > 0, axis=1)
    else:
        lab_len = jnp.sum((labels >= 0) & (labels < V - 1), axis=1)

    # entries past each sample's length must not poison the `same` mask
    # or gather with out-of-range values (padding_mask may be -1)
    valid = jnp.arange(L)[None, :] < lab_len[:, None]
    labels = jnp.where(valid, jnp.clip(labels, 0, V - 1), blank)

    # extended label seq: blank interleaved — length 2L+1
    S = 2 * L + 1
    ext = jnp.full((N, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * lab_len + 1

    neg_inf = -1e10
    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(logp[0], ext[:, 1:2], 1)[:, 0])

    same = jnp.concatenate([jnp.zeros((N, 2), bool),
                            ext[:, 2:] == ext[:, :-2]], axis=1)
    is_blank = (ext == blank)

    def step(alpha, xs):
        logp_t, t = xs
        a1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        a2 = jnp.where(is_blank | same, neg_inf, a2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        new_alpha = merged + emit
        if data_lengths is not None:
            live = (t < data_lengths.astype(jnp.int32))[:, None]
            new_alpha = jnp.where(live, new_alpha, alpha)
        return new_alpha, None

    alphaT, _ = jax.lax.scan(step, alpha0, (logp[1:], jnp.arange(1, T)))
    idx_last = jnp.clip(ext_len - 1, 0, S - 1)
    idx_prev = jnp.clip(ext_len - 2, 0, S - 1)
    ll = jnp.logaddexp(
        jnp.take_along_axis(alphaT, idx_last[:, None], 1)[:, 0],
        jnp.take_along_axis(alphaT, idx_prev[:, None], 1)[:, 0])
    return -ll


register_alias('ctc_loss', '_contrib_CTCLoss')


# ---------------------------------------------------------------------------
# MultiBox family (SSD) — reference contrib/multibox_prior.cc,
# multibox_target.cc, multibox_detection.cc
# ---------------------------------------------------------------------------
@register('_contrib_MultiBoxPrior',
          param_defaults={'sizes': (1.0,), 'ratios': (1.0,), 'clip': False,
                          'steps': (-1.0, -1.0), 'offsets': (0.5, 0.5)},
          differentiable=False)
def _multibox_prior(attrs, data):
    H, W = data.shape[2], data.shape[3]
    sizes = attrs.get('sizes', (1.0,))
    ratios = attrs.get('ratios', (1.0,))
    steps = attrs.get('steps', (-1.0, -1.0))
    offs = attrs.get('offsets', (0.5, 0.5))
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H) + offs[0]) * step_y
    cx = (jnp.arange(W) + offs[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing='ij')
    boxes = []
    # anchor set: sizes[0] with each ratio + each size with ratio[0]
    combos = [(sizes[0], r) for r in ratios] + \
             [(s, ratios[0]) for s in sizes[1:]]
    for s, r in combos:
        w = s * jnp.sqrt(r) / 2
        h = s / jnp.sqrt(r) / 2
        boxes.append(jnp.stack([cxg - w, cyg - h, cxg + w, cyg + h], axis=-1))
    out = jnp.stack(boxes, axis=2).reshape(-1, 4)
    if attrs.get('clip', False):
        out = jnp.clip(out, 0, 1)
    return out[None]


def _box_iou(a, b):
    """a (A,4), b (B,4) corner boxes → (A,B)."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]), 0)
    area_b = jnp.maximum((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), 0)
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-12)


@register('_contrib_MultiBoxTarget',
          input_names=['anchor', 'label', 'cls_pred'],
          param_defaults={'overlap_threshold': 0.5, 'ignore_label': -1.0,
                          'negative_mining_ratio': -1.0,
                          'negative_mining_thresh': 0.5, 'minimum_negative_samples': 0,
                          'variances': (0.1, 0.1, 0.2, 0.2)},
          num_outputs=3, differentiable=False)
def _multibox_target(attrs, anchor, label, cls_pred):
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    var = attrs.get('variances', (0.1, 0.1, 0.2, 0.2))
    thresh = attrs.get('overlap_threshold', 0.5)

    def per_sample(lab):
        valid = lab[:, 0] >= 0
        gt = lab[:, 1:5]
        iou = _box_iou(anchors, gt)  # (A, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou > thresh
        # force-match the best anchor for each gt
        best_anchor = jnp.argmax(iou, axis=0)  # (M,)
        force = jnp.zeros(A, bool).at[best_anchor].set(valid)
        matched = matched | force
        cls = jnp.where(matched, lab[best_gt, 0] + 1, 0.0)
        g = gt[best_gt]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-8)
        ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-8)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
        tx = (gcx - acx) / aw / var[0]
        ty = (gcy - acy) / ah / var[1]
        tw = jnp.log(gw / aw) / var[2]
        th = jnp.log(gh / ah) / var[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0).ravel()
        loc_mask = jnp.where(matched[:, None],
                             jnp.ones((A, 4)), 0.0).ravel()
        return loc_t, loc_mask, cls

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(label)
    return loc_t, loc_m, cls_t


@register('_contrib_MultiBoxDetection',
          input_names=['cls_prob', 'loc_pred', 'anchor'],
          param_defaults={'clip': True, 'threshold': 0.01, 'background_id': 0,
                          'nms_threshold': 0.5, 'force_suppress': False,
                          'variances': (0.1, 0.1, 0.2, 0.2), 'nms_topk': -1},
          differentiable=False)
def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    var = attrs.get('variances', (0.1, 0.1, 0.2, 0.2))
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    nms_thresh = attrs.get('nms_threshold', 0.5)
    score_thresh = attrs.get('threshold', 0.01)

    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]

    def per_sample(probs, locs):
        l = locs.reshape(-1, 4)
        cx = l[:, 0] * var[0] * aw + acx
        cy = l[:, 1] * var[1] * ah + acy
        w = jnp.exp(l[:, 2] * var[2]) * aw
        h = jnp.exp(l[:, 3] * var[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        if attrs.get('clip', True):
            boxes = jnp.clip(boxes, 0, 1)
        scores = probs[1:]  # drop background row; (C-1, A)
        cls_id = jnp.argmax(scores, axis=0).astype(jnp.float32)
        score = jnp.max(scores, axis=0)
        keep_score = score > score_thresh
        order = jnp.argsort(-score)
        boxes_s = boxes[order]
        iou = _box_iou(boxes_s, boxes_s)
        same_cls = cls_id[order][:, None] == cls_id[order][None, :]
        if attrs.get('force_suppress', False):
            same_cls = jnp.ones_like(same_cls)
        sup = (iou > nms_thresh) & same_cls & \
            (jnp.arange(A)[:, None] > jnp.arange(A)[None, :])
        suppressed = jnp.any(sup & keep_score[order][None, :] * True, axis=1)
        valid = keep_score[order] & ~suppressed
        out_id = jnp.where(valid, cls_id[order], -1.0)
        return jnp.concatenate([out_id[:, None], score[order][:, None],
                                boxes_s], axis=1)

    return jax.vmap(per_sample)(cls_prob, loc_pred)


@register('_contrib_box_iou', input_names=['lhs', 'rhs'],
          param_defaults={'format': 'corner'}, differentiable=False)
def _box_iou_op(attrs, lhs, rhs):
    return _box_iou(lhs.reshape(-1, 4), rhs.reshape(-1, 4))


# ---------------------------------------------------------------------------
# DeformableConvolution — reference contrib/deformable_convolution-inl.h
# (deformable_im2col + group gemm). TPU formulation: bilinear gather builds
# the deformed im2col tensor, one einsum does the group conv on the MXU.
# ---------------------------------------------------------------------------
def _bilinear_at(img, y, x):
    """img (C,H,W); y,x arbitrary same-shaped float coords → (C,) + y.shape.
    Out-of-range samples contribute 0, matching deformable_im2col's
    zero-padding behavior."""
    H, W = img.shape[1], img.shape[2]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = y - y0
    wx = x - x0

    def g(yy, xx):
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        ok = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
        return jnp.where(ok[None], img[:, yi, xi], 0.0)

    return (g(y0, x0) * ((1 - wy) * (1 - wx))[None] +
            g(y0, x0 + 1) * ((1 - wy) * wx)[None] +
            g(y0 + 1, x0) * (wy * (1 - wx))[None] +
            g(y0 + 1, x0 + 1) * (wy * wx)[None])


@register('_contrib_DeformableConvolution',
          input_names=['data', 'offset', 'weight', 'bias'],
          param_defaults={'kernel': (1, 1), 'stride': (1, 1), 'dilate': (1, 1),
                          'pad': (0, 0), 'num_filter': 1, 'num_group': 1,
                          'num_deformable_group': 1, 'workspace': 1024,
                          'no_bias': False, 'layout': None})
def _deformable_convolution(attrs, data, offset, weight, bias=None):
    kh, kw = attrs['kernel']
    sh, sw = attrs.get('stride', (1, 1))
    dh, dw = attrs.get('dilate', (1, 1))
    ph, pw = attrs.get('pad', (0, 0))
    G = int(attrs.get('num_group', 1))
    DG = int(attrs.get('num_deformable_group', 1))
    N, C, H, W = data.shape
    F = int(attrs['num_filter'])
    OH = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    OW = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    # base sampling grid per tap: (KH*KW, OH, OW)
    oy = jnp.arange(OH) * sh - ph
    ox = jnp.arange(OW) * sw - pw
    ky, kx = jnp.meshgrid(jnp.arange(kh) * dh, jnp.arange(kw) * dw,
                          indexing='ij')
    base_y = oy[None, :, None] + ky.ravel()[:, None, None]  # (K, OH, 1)
    base_x = ox[None, None, :] + kx.ravel()[:, None, None].transpose(0, 2, 1)
    base_y = jnp.broadcast_to(base_y, (kh * kw, OH, OW)).astype(data.dtype)
    base_x = jnp.broadcast_to(base_x, (kh * kw, OH, OW)).astype(data.dtype)

    cpg = C // DG  # channels per deformable group

    def sample_one(img, off):
        # img (C,H,W); off (DG*2*K, OH, OW) laid out [dg][ (y,x) per tap ]
        off = off.reshape(DG, kh * kw, 2, OH, OW)

        def per_dg(img_dg, off_dg):
            y = base_y + off_dg[:, 0]  # (K, OH, OW)
            x = base_x + off_dg[:, 1]
            return _bilinear_at(img_dg, y, x)  # (cpg, K, OH, OW)

        sampled = jax.vmap(per_dg)(img.reshape(DG, cpg, H, W), off)
        return sampled.reshape(C, kh * kw, OH, OW)

    cols = jax.vmap(sample_one)(data, offset)  # (N, C, K, OH, OW)
    # group conv: split C and F into G groups, contract (C/G * K) on the MXU
    cols = cols.reshape(N, G, C // G, kh * kw, OH, OW)
    wg = weight.reshape(G, F // G, C // G, kh * kw)
    out = jnp.einsum('ngckhw,gfck->ngfhw', cols, wg,
                     preferred_element_type=jnp.float32)
    out = out.reshape(N, F, OH, OW).astype(data.dtype)
    if bias is not None and not attrs.get('no_bias', False):
        out = out + bias[None, :, None, None]
    return out


# ---------------------------------------------------------------------------
# DeformablePSROIPooling — reference contrib/deformable_psroi_pooling-inl.h
# (position-sensitive score maps + learned per-part offsets, R-FCN style)
# ---------------------------------------------------------------------------
@register('_contrib_DeformablePSROIPooling',
          input_names=['data', 'rois', 'trans'],
          param_defaults={'spatial_scale': 1.0, 'output_dim': 1,
                          'group_size': 1, 'pooled_size': 1, 'part_size': 0,
                          'sample_per_part': 1, 'trans_std': 0.0,
                          'no_trans': False})
def _deformable_psroi_pooling(attrs, data, rois, trans=None):
    scale = float(attrs['spatial_scale'])
    out_dim = int(attrs['output_dim'])
    gs = int(attrs['group_size'])
    ps = int(attrs['pooled_size'])
    part = int(attrs.get('part_size', 0)) or ps
    spp = int(attrs.get('sample_per_part', 1))
    tstd = float(attrs.get('trans_std', 0.0))
    no_trans = attrs.get('no_trans', False) or trans is None
    N, C, H, W = data.shape

    iy, ix = jnp.meshgrid(jnp.arange(ps), jnp.arange(ps), indexing='ij')
    sy, sx = jnp.meshgrid(jnp.arange(spp), jnp.arange(spp), indexing='ij')

    def pool_one(roi, tr):
        b = roi[0].astype(jnp.int32)
        # reference rounds rois to the feature grid and enforces min size 0.1
        x1 = jnp.round(roi[1]) * scale - 0.5
        y1 = jnp.round(roi[2]) * scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / ps
        bin_h = rh / ps
        sub_w = bin_w / spp
        sub_h = bin_h / spp
        img = data[b]  # (C, H, W)

        # per-bin learned offset, scaled by roi size (deformable_psroi:
        # trans (R, 2, part, part), class-agnostic)
        if no_trans:
            off_y = jnp.zeros((ps, ps))
            off_x = jnp.zeros((ps, ps))
        else:
            py = (iy * part) // ps
            px = (ix * part) // ps
            off_y = tr[0, py, px] * tstd * rh
            off_x = tr[1, py, px] * tstd * rw

        # sample grid: (ps, ps, spp, spp)
        yy = (y1 + iy[..., None, None] * bin_h + off_y[..., None, None]
              + (sy + 0.5) * sub_h)
        xx = (x1 + ix[..., None, None] * bin_w + off_x[..., None, None]
              + (sx + 0.5) * sub_w)
        # reference skips samples outside [-0.5, dim-0.5) and divides by
        # the in-bounds count only, clamping kept coords to the border
        valid = ((yy > -0.5) & (yy < H - 0.5) &
                 (xx > -0.5) & (xx < W - 0.5))
        yc = jnp.clip(yy, 0.0, H - 1.0)
        xc = jnp.clip(xx, 0.0, W - 1.0)
        sampled = _bilinear_at(img, yc, xc)  # (C, ps, ps, spp, spp)
        count = jnp.maximum(valid.sum(axis=(-2, -1)), 1)
        avg = (sampled * valid[None]).sum(axis=(-2, -1)) / count[None]
        # position-sensitive channel selection:
        # channel(c, bin) = (c*gs + gy)*gs + gx with gy,gx = bin scaled to gs
        gy = (iy * gs) // ps
        gx = (ix * gs) // ps
        chan = (jnp.arange(out_dim)[:, None, None] * gs + gy) * gs + gx
        return avg[chan, iy[None], ix[None]]  # (out_dim, ps, ps)

    if no_trans:
        tr_in = jnp.zeros((rois.shape[0], 2, part, part), dtype=data.dtype)
    else:
        tr_in = trans
    return jax.vmap(pool_one)(rois, tr_in)


# ---------------------------------------------------------------------------
# MultiProposal — reference contrib/multi_proposal-inl.h (batched RPN
# proposal generation: anchor decode + clip + min-size filter + NMS)
# ---------------------------------------------------------------------------
def _gen_anchors(feature_stride, scales, ratios):
    """Base anchors centered on a feature_stride cell (generate_anchors)."""
    base = jnp.array([0, 0, feature_stride - 1, feature_stride - 1],
                     dtype=jnp.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + (w - 1) / 2
    cy = base[1] + (h - 1) / 2
    anchors = []
    for r in ratios:
        size = w * h
        ws = jnp.round(jnp.sqrt(size / r))
        hs = jnp.round(ws * r)
        for s in scales:
            wss = ws * s
            hss = hs * s
            anchors.append(jnp.stack([cx - (wss - 1) / 2, cy - (hss - 1) / 2,
                                      cx + (wss - 1) / 2, cy + (hss - 1) / 2]))
    return jnp.stack(anchors)  # (A, 4)


@register('_contrib_MultiProposal',
          input_names=['cls_prob', 'bbox_pred', 'im_info'],
          param_defaults={'rpn_pre_nms_top_n': 6000, 'rpn_post_nms_top_n': 300,
                          'threshold': 0.7, 'rpn_min_size': 16,
                          'scales': (4.0, 8.0, 16.0, 32.0),
                          'ratios': (0.5, 1.0, 2.0), 'feature_stride': 16,
                          'output_score': False, 'iou_loss': False},
          num_outputs=lambda attrs: 2 if attrs.get('output_score') else 1,
          differentiable=False)
def _multi_proposal(attrs, cls_prob, bbox_pred, im_info):
    stride = int(attrs.get('feature_stride', 16))
    scales = tuple(attrs.get('scales', (4.0, 8.0, 16.0, 32.0)))
    ratios = tuple(attrs.get('ratios', (0.5, 1.0, 2.0)))
    pre_n = int(attrs.get('rpn_pre_nms_top_n', 6000))
    post_n = int(attrs.get('rpn_post_nms_top_n', 300))
    nms_thresh = float(attrs.get('threshold', 0.7))
    min_size = float(attrs.get('rpn_min_size', 16))

    N, _, FH, FW = cls_prob.shape
    A = len(scales) * len(ratios)
    base = _gen_anchors(stride, scales, ratios)  # (A,4)
    shift_x = jnp.arange(FW) * stride
    shift_y = jnp.arange(FH) * stride
    sy, sx = jnp.meshgrid(shift_y, shift_x, indexing='ij')
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 4)  # (HW,4)
    anchors = (base[None] + shifts[:, None]).reshape(-1, 4)  # (HW*A,4)
    K = anchors.shape[0]
    pre_n = min(pre_n, K)
    post_n = min(post_n, pre_n)

    def per_image(probs, deltas, info):
        ih, iw, im_scale = info[0], info[1], info[2]
        # scores: foreground half, layout (A, H, W) after the first A bg maps
        fg = probs[A:].reshape(A, FH, FW).transpose(1, 2, 0).reshape(-1)
        d = deltas.reshape(A, 4, FH, FW).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ah = anchors[:, 3] - anchors[:, 1] + 1
        acx = anchors[:, 0] + aw * 0.5
        acy = anchors[:, 1] + ah * 0.5
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        x1 = jnp.clip(cx - w * 0.5, 0, iw - 1)
        y1 = jnp.clip(cy - h * 0.5, 0, ih - 1)
        x2 = jnp.clip(cx + w * 0.5, 0, iw - 1)
        y2 = jnp.clip(cy + h * 0.5, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
        keep = ((x2 - x1 + 1 >= min_size * im_scale) &
                (y2 - y1 + 1 >= min_size * im_scale))
        score = jnp.where(keep, fg, -1.0)
        order = jnp.argsort(-score)[:pre_n]
        b = boxes[order]
        s = score[order]
        iou = _box_iou(b, b)
        earlier = jnp.arange(pre_n)[:, None] > jnp.arange(pre_n)[None, :]
        # greedy NMS as a scan over rank: kept[i] = no earlier kept box
        # overlaps it above threshold
        def nms_step(kept, i):
            sup = jnp.any(kept & earlier[i] & (iou[i] > nms_thresh))
            kept = kept.at[i].set(~sup & (s[i] > -1.0))
            return kept, None
        kept, _ = jax.lax.scan(nms_step, jnp.zeros(pre_n, bool),
                               jnp.arange(pre_n))
        # compact kept boxes (in score order) into the first post_n slots;
        # unfilled tail stays zero, as in the reference's workspace memset
        rank = jnp.cumsum(kept) - 1
        sel = kept & (rank < post_n)
        idx = jnp.clip(rank, 0, post_n - 1)
        out_boxes = jnp.zeros((post_n, 4), dtype=boxes.dtype).at[idx].add(
            jnp.where(sel[:, None], b, 0.0))
        out_scores = jnp.zeros((post_n,), dtype=s.dtype).at[idx].add(
            jnp.where(sel, s, 0.0))
        return out_boxes, out_scores

    boxes, scores = jax.vmap(per_image)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(N, dtype=boxes.dtype), post_n)
    rois = jnp.concatenate([batch_idx[:, None],
                            boxes.reshape(N * post_n, 4)], axis=1)
    if attrs.get('output_score', False):
        return rois, scores.reshape(N * post_n, 1)
    return rois


@register('_contrib_Proposal',
          input_names=['cls_prob', 'bbox_pred', 'im_info'],
          param_defaults={'rpn_pre_nms_top_n': 6000, 'rpn_post_nms_top_n': 300,
                          'threshold': 0.7, 'rpn_min_size': 16,
                          'scales': (4.0, 8.0, 16.0, 32.0),
                          'ratios': (0.5, 1.0, 2.0), 'feature_stride': 16,
                          'output_score': False, 'iou_loss': False},
          num_outputs=lambda attrs: 2 if attrs.get('output_score') else 1,
          differentiable=False)
def _proposal(attrs, cls_prob, bbox_pred, im_info):
    """Reference contrib/proposal.cc — single-image form of MultiProposal."""
    return _multi_proposal(attrs, cls_prob, bbox_pred, im_info)


# ---------------------------------------------------------------------------
# quantize — reference contrib/quantize-inl.h (fp32 → uint8 affine, carries
# the calibration range through as outputs 1/2); pairs with
# _contrib_dequantize above.
# ---------------------------------------------------------------------------
@register('_contrib_quantize', input_names=['data', 'min_range', 'max_range'],
          param_defaults={'out_type': 'uint8'}, num_outputs=3,
          differentiable=False)
def _quantize(attrs, data, min_range, max_range):
    out_type = attrs.get('out_type', 'uint8')
    scale_den = max_range - min_range
    if out_type == 'int8':
        # signed path needs true rounding (the reference's +0.5-then-
        # truncate trick only rounds correctly for non-negative values)
        scale = 255.0 / scale_den
        q = jnp.clip(jnp.round((data - min_range) * scale) - 128.0,
                     -128.0, 127.0)
        return q.astype(jnp.int8), min_range, max_range
    scale = 255.0 / scale_den
    q = jnp.clip(jnp.floor((data - min_range) * scale + 0.5), 0.0, 255.0)
    return q.astype(jnp.uint8), min_range, max_range


# ---------------------------------------------------------------------------
# PSROIPooling (non-deformable) — reference contrib/psroi_pooling-inl.h:
# position-sensitive score maps, each bin averages the pixels inside it
# ---------------------------------------------------------------------------
@register('_contrib_PSROIPooling', input_names=['data', 'rois'],
          param_defaults={'spatial_scale': 1.0, 'output_dim': 1,
                          'pooled_size': 1, 'group_size': 0})
def _psroi_pooling(attrs, data, rois):
    scale = float(attrs['spatial_scale'])
    out_dim = int(attrs['output_dim'])
    ps = int(attrs['pooled_size'])
    gs = int(attrs.get('group_size', 0)) or ps
    N, C, H, W = data.shape

    iy, ix = jnp.meshgrid(jnp.arange(ps), jnp.arange(ps), indexing='ij')
    ys = jnp.arange(H, dtype=data.dtype)
    xs = jnp.arange(W, dtype=data.dtype)

    def pool_one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * scale
        y1 = jnp.round(roi[2]) * scale
        x2 = (jnp.round(roi[3]) + 1.0) * scale
        y2 = (jnp.round(roi[4]) + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / ps
        bin_h = rh / ps
        img = data[b]
        # per-bin pixel masks: pixel p in bin (by,bx) iff floor coords in
        # [start, end) (reference psroi kernel's floor/ceil bin bounds)
        y_lo = jnp.floor(y1 + iy * bin_h)[..., None]         # (ps,ps,1)
        y_hi = jnp.ceil(y1 + (iy + 1) * bin_h)[..., None]
        x_lo = jnp.floor(x1 + ix * bin_w)[..., None]
        x_hi = jnp.ceil(x1 + (ix + 1) * bin_w)[..., None]
        ymask = (ys >= jnp.maximum(y_lo, 0)) & (ys < jnp.minimum(y_hi, H))
        xmask = (xs >= jnp.maximum(x_lo, 0)) & (xs < jnp.minimum(x_hi, W))
        mask = ymask[:, :, :, None] & xmask[:, :, None, :]   # (ps,ps,H,W)
        count = jnp.maximum(mask.sum(axis=(-2, -1)), 1)
        sums = jnp.einsum('chw,pqhw->cpq', img, mask.astype(data.dtype))
        avg = sums / count[None]
        gy = (iy * gs) // ps
        gx = (ix * gs) // ps
        chan = (jnp.arange(out_dim)[:, None, None] * gs + gy) * gs + gx
        return avg[chan, iy[None], ix[None]]

    return jax.vmap(pool_one)(rois)


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg — reference identity_attach_KL_sparse_reg-inl.h
# (identity forward; backward adds the KL-sparseness penalty derivative
# against a moving average of per-unit activations)
# ---------------------------------------------------------------------------
@register('IdentityAttachKLSparseReg',
          input_names=['data', 'moving_avg'],
          param_defaults={'sparseness_target': 0.1, 'penalty': 0.001,
                          'momentum': 0.9},
          aux_inputs=('moving_avg',), mutate_inputs={1: 1},
          num_visible_outputs=1, num_outputs=2, train_aware=True)
def _identity_attach_kl_sparse_reg(attrs, data, moving_avg):
    t = float(attrs.get('sparseness_target', 0.1))
    penalty = float(attrs.get('penalty', 0.001))
    momentum = float(attrs.get('momentum', 0.9))
    is_train = attrs.get('__is_train__', False)

    flat = data.reshape(data.shape[0], -1)
    if is_train:
        avg = jnp.mean(flat, axis=0)
        new_moving = momentum * moving_avg + (1 - momentum) * avg
    else:
        new_moving = moving_avg

    @jax.custom_vjp
    def ident(x, moving):
        return x

    def fwd(x, moving):
        return x, moving

    def bwd(moving, g):
        reg = penalty * (-t / moving + (1 - t) / (1 - moving))
        gflat = g.reshape(g.shape[0], -1) + reg[None, :]
        return gflat.reshape(g.shape).astype(g.dtype), None

    ident.defvjp(fwd, bwd)
    return ident(data, new_moving), new_moving
