"""Contrib + vision-specific ops.

Reference: src/operator/contrib/ (ctc_loss, count_sketch, fft, dequantize,
multibox_*, proposal), roi_pooling.cc, spatial_transformer.cc,
bilinear_sampler.cc, grid_generator.cc, correlation.cc.
"""
import jax
import jax.numpy as jnp

from .registry import register, register_alias


# ---------------------------------------------------------------------------
# ROIPooling — reference src/operator/roi_pooling.cc
# ---------------------------------------------------------------------------
@register('ROIPooling', input_names=['data', 'rois'],
          param_defaults={'pooled_size': (0, 0), 'spatial_scale': 1.0})
def _roi_pooling(attrs, data, rois):
    ph, pw = attrs['pooled_size']
    scale = attrs.get('spatial_scale', 1.0)
    N, C, H, W = data.shape

    def pool_one(roi):
        batch = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[batch]
        ys = jnp.arange(H)
        xs = jnp.arange(W)
        out = jnp.full((C, ph, pw), -jnp.inf, dtype=data.dtype)
        for py in range(ph):
            for px in range(pw):
                ys_lo = y1 + (py * rh) // ph
                ys_hi = y1 + ((py + 1) * rh + ph - 1) // ph
                xs_lo = x1 + (px * rw) // pw
                xs_hi = x1 + ((px + 1) * rw + pw - 1) // pw
                mask = ((ys[:, None] >= ys_lo) & (ys[:, None] < ys_hi) &
                        (xs[None, :] >= xs_lo) & (xs[None, :] < xs_hi))
                vals = jnp.where(mask[None], img, -jnp.inf)
                out = out.at[:, py, px].set(jnp.max(vals, axis=(1, 2)))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(pool_one)(rois)


# ---------------------------------------------------------------------------
# BilinearSampler / GridGenerator / SpatialTransformer
# ---------------------------------------------------------------------------
def _bilinear_sample(data, grid):
    """data (N,C,H,W), grid (N,2,Ho,Wo) with coords in [-1,1]."""
    N, C, H, W = data.shape
    gx = (grid[:, 0] + 1) * (W - 1) / 2
    gy = (grid[:, 1] + 1) * (H - 1) / 2
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(img, yy, xx):
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        valid = ((yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1))
        vals = img[:, yi, xi]
        return jnp.where(valid[None], vals, 0.0)

    def sample_one(img, x0_, y0_, wx_, wy_):
        v00 = gather(img, y0_, x0_)
        v01 = gather(img, y0_, x0_ + 1)
        v10 = gather(img, y0_ + 1, x0_)
        v11 = gather(img, y0_ + 1, x0_ + 1)
        return (v00 * (1 - wx_)[None] * (1 - wy_)[None] +
                v01 * wx_[None] * (1 - wy_)[None] +
                v10 * (1 - wx_)[None] * wy_[None] +
                v11 * wx_[None] * wy_[None])

    return jax.vmap(sample_one)(data, x0, y0, wx, wy)


@register('BilinearSampler', input_names=['data', 'grid'])
def _bilinear_sampler(attrs, data, grid):
    return _bilinear_sample(data, grid)


@register('GridGenerator', input_names=['data'],
          param_defaults={'transform_type': 'affine', 'target_shape': (0, 0)})
def _grid_generator(attrs, data):
    th, tw = attrs['target_shape']
    if attrs.get('transform_type', 'affine') == 'affine':
        N = data.shape[0]
        theta = data.reshape(N, 2, 3)
        ys = jnp.linspace(-1, 1, th)
        xs = jnp.linspace(-1, 1, tw)
        gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        out = jnp.einsum('nij,jk->nik', theta, coords)  # (N,2,HW)
        return out.reshape(N, 2, th, tw)
    # warp type: data is flow field (N,2,H,W)
    N, _, H, W = data.shape
    ys = jnp.arange(H, dtype=data.dtype)
    xs = jnp.arange(W, dtype=data.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
    fx = (data[:, 0] + gx) * 2 / max(W - 1, 1) - 1
    fy = (data[:, 1] + gy) * 2 / max(H - 1, 1) - 1
    return jnp.stack([fx, fy], axis=1)


@register('SpatialTransformer', input_names=['data', 'loc'],
          param_defaults={'target_shape': (0, 0), 'transform_type': 'affine',
                          'sampler_type': 'bilinear'})
def _spatial_transformer(attrs, data, loc):
    grid = _grid_generator({'transform_type': 'affine',
                            'target_shape': attrs['target_shape']}, loc)
    return _bilinear_sample(data, grid)


# ---------------------------------------------------------------------------
# Correlation — reference correlation.cc (FlowNet-style)
# ---------------------------------------------------------------------------
@register('Correlation', input_names=['data1', 'data2'],
          param_defaults={'kernel_size': 1, 'max_displacement': 1, 'stride1': 1,
                          'stride2': 1, 'pad_size': 0, 'is_multiply': True})
def _correlation(attrs, a, b):
    d = int(attrs.get('max_displacement', 1))
    s2 = int(attrs.get('stride2', 1))
    mult = attrs.get('is_multiply', True)
    shifts = range(-d, d + 1, s2)
    outs = []
    for dy in shifts:
        for dx in shifts:
            shifted = jnp.roll(b, (dy, dx), axis=(2, 3))
            if mult:
                corr = jnp.mean(a * shifted, axis=1)
            else:
                corr = jnp.mean(jnp.abs(a - shifted), axis=1)
            outs.append(corr)
    return jnp.stack(outs, axis=1)


# ---------------------------------------------------------------------------
# contrib: FFT / count_sketch / dequantize / CTC
# ---------------------------------------------------------------------------
@register('_contrib_fft', param_defaults={'compute_size': 128})
def _fft(attrs, x):
    """Reference contrib/fft.cc — output interleaves re/im along last dim."""
    y = jnp.fft.fft(x.astype(jnp.complex64), axis=-1)
    out = jnp.stack([y.real, y.imag], axis=-1)
    return out.reshape(x.shape[:-1] + (2 * x.shape[-1],)).astype(x.dtype)


@register('_contrib_ifft', param_defaults={'compute_size': 128})
def _ifft(attrs, x):
    n = x.shape[-1] // 2
    xr = x.reshape(x.shape[:-1] + (n, 2))
    y = jnp.fft.ifft(xr[..., 0] + 1j * xr[..., 1], axis=-1)
    return (y.real * n).astype(x.dtype)


@register('_contrib_count_sketch', input_names=['data', 'h', 's'],
          param_defaults={'out_dim': 0, 'processing_batch_size': 32})
def _count_sketch(attrs, data, h, s):
    out_dim = int(attrs['out_dim'])
    idx = h.ravel().astype(jnp.int32)
    sign = s.ravel()
    out = jnp.zeros(data.shape[:-1] + (out_dim,), dtype=data.dtype)
    return out.at[..., idx].add(data * sign)


@register('_contrib_dequantize', input_names=['data', 'min_range', 'max_range'],
          param_defaults={'out_type': 'float32'}, differentiable=False)
def _dequantize(attrs, data, min_range, max_range):
    qmin = float(jnp.iinfo(jnp.int8).min) if data.dtype == jnp.int8 else 0.0
    qmax = float(jnp.iinfo(jnp.int8).max) if data.dtype == jnp.int8 else 255.0
    scale = (max_range - min_range) / (qmax - qmin)
    return (data.astype(jnp.float32) - qmin) * scale + min_range


@register('_contrib_CTCLoss', input_names=['data', 'label'],
          param_defaults={'use_data_lengths': False, 'use_label_lengths': False,
                          'blank_label': 'first'})
def _ctc_loss(attrs, data, label):
    """Reference contrib/ctc_loss.cc (warp-ctc). Forward-backward in log
    space via lax.scan; blank index 0 ('first' convention)."""
    T, N, V = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    labels = label.astype(jnp.int32)  # (N, L)
    L = labels.shape[1]
    # extended label seq: blank interleaved — length 2L+1
    S = 2 * L + 1
    ext = jnp.zeros((N, S), dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    lab_len = jnp.sum(labels > 0, axis=1) if not attrs.get('use_label_lengths') \
        else jnp.sum(labels >= 0, axis=1)
    ext_len = 2 * lab_len + 1

    neg_inf = -1e10
    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(logp[0], ext[:, 1:2], 1)[:, 0])

    same = jnp.concatenate([jnp.zeros((N, 2), bool),
                            ext[:, 2:] == ext[:, :-2]], axis=1)
    is_blank = (ext == 0)

    def step(alpha, logp_t):
        a1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        a2 = jnp.where(is_blank | same, neg_inf, a2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        new_alpha = merged + emit
        return new_alpha, None

    alphaT, _ = jax.lax.scan(step, alpha0, logp[1:])
    idx_last = jnp.clip(ext_len - 1, 0, S - 1)
    idx_prev = jnp.clip(ext_len - 2, 0, S - 1)
    ll = jnp.logaddexp(
        jnp.take_along_axis(alphaT, idx_last[:, None], 1)[:, 0],
        jnp.take_along_axis(alphaT, idx_prev[:, None], 1)[:, 0])
    return -ll


register_alias('ctc_loss', '_contrib_CTCLoss')


# ---------------------------------------------------------------------------
# MultiBox family (SSD) — reference contrib/multibox_prior.cc,
# multibox_target.cc, multibox_detection.cc
# ---------------------------------------------------------------------------
@register('_contrib_MultiBoxPrior',
          param_defaults={'sizes': (1.0,), 'ratios': (1.0,), 'clip': False,
                          'steps': (-1.0, -1.0), 'offsets': (0.5, 0.5)},
          differentiable=False)
def _multibox_prior(attrs, data):
    H, W = data.shape[2], data.shape[3]
    sizes = attrs.get('sizes', (1.0,))
    ratios = attrs.get('ratios', (1.0,))
    steps = attrs.get('steps', (-1.0, -1.0))
    offs = attrs.get('offsets', (0.5, 0.5))
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H) + offs[0]) * step_y
    cx = (jnp.arange(W) + offs[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing='ij')
    boxes = []
    # anchor set: sizes[0] with each ratio + each size with ratio[0]
    combos = [(sizes[0], r) for r in ratios] + \
             [(s, ratios[0]) for s in sizes[1:]]
    for s, r in combos:
        w = s * jnp.sqrt(r) / 2
        h = s / jnp.sqrt(r) / 2
        boxes.append(jnp.stack([cxg - w, cyg - h, cxg + w, cyg + h], axis=-1))
    out = jnp.stack(boxes, axis=2).reshape(-1, 4)
    if attrs.get('clip', False):
        out = jnp.clip(out, 0, 1)
    return out[None]


def _box_iou(a, b):
    """a (A,4), b (B,4) corner boxes → (A,B)."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]), 0)
    area_b = jnp.maximum((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), 0)
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-12)


@register('_contrib_MultiBoxTarget',
          input_names=['anchor', 'label', 'cls_pred'],
          param_defaults={'overlap_threshold': 0.5, 'ignore_label': -1.0,
                          'negative_mining_ratio': -1.0,
                          'negative_mining_thresh': 0.5, 'minimum_negative_samples': 0,
                          'variances': (0.1, 0.1, 0.2, 0.2)},
          num_outputs=3, differentiable=False)
def _multibox_target(attrs, anchor, label, cls_pred):
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    var = attrs.get('variances', (0.1, 0.1, 0.2, 0.2))
    thresh = attrs.get('overlap_threshold', 0.5)

    def per_sample(lab):
        valid = lab[:, 0] >= 0
        gt = lab[:, 1:5]
        iou = _box_iou(anchors, gt)  # (A, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou > thresh
        # force-match the best anchor for each gt
        best_anchor = jnp.argmax(iou, axis=0)  # (M,)
        force = jnp.zeros(A, bool).at[best_anchor].set(valid)
        matched = matched | force
        cls = jnp.where(matched, lab[best_gt, 0] + 1, 0.0)
        g = gt[best_gt]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-8)
        ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-8)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
        tx = (gcx - acx) / aw / var[0]
        ty = (gcy - acy) / ah / var[1]
        tw = jnp.log(gw / aw) / var[2]
        th = jnp.log(gh / ah) / var[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0).ravel()
        loc_mask = jnp.where(matched[:, None],
                             jnp.ones((A, 4)), 0.0).ravel()
        return loc_t, loc_mask, cls

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(label)
    return loc_t, loc_m, cls_t


@register('_contrib_MultiBoxDetection',
          input_names=['cls_prob', 'loc_pred', 'anchor'],
          param_defaults={'clip': True, 'threshold': 0.01, 'background_id': 0,
                          'nms_threshold': 0.5, 'force_suppress': False,
                          'variances': (0.1, 0.1, 0.2, 0.2), 'nms_topk': -1},
          differentiable=False)
def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    var = attrs.get('variances', (0.1, 0.1, 0.2, 0.2))
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    nms_thresh = attrs.get('nms_threshold', 0.5)
    score_thresh = attrs.get('threshold', 0.01)

    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]

    def per_sample(probs, locs):
        l = locs.reshape(-1, 4)
        cx = l[:, 0] * var[0] * aw + acx
        cy = l[:, 1] * var[1] * ah + acy
        w = jnp.exp(l[:, 2] * var[2]) * aw
        h = jnp.exp(l[:, 3] * var[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        if attrs.get('clip', True):
            boxes = jnp.clip(boxes, 0, 1)
        scores = probs[1:]  # drop background row; (C-1, A)
        cls_id = jnp.argmax(scores, axis=0).astype(jnp.float32)
        score = jnp.max(scores, axis=0)
        keep_score = score > score_thresh
        order = jnp.argsort(-score)
        boxes_s = boxes[order]
        iou = _box_iou(boxes_s, boxes_s)
        same_cls = cls_id[order][:, None] == cls_id[order][None, :]
        if attrs.get('force_suppress', False):
            same_cls = jnp.ones_like(same_cls)
        sup = (iou > nms_thresh) & same_cls & \
            (jnp.arange(A)[:, None] > jnp.arange(A)[None, :])
        suppressed = jnp.any(sup & keep_score[order][None, :] * True, axis=1)
        valid = keep_score[order] & ~suppressed
        out_id = jnp.where(valid, cls_id[order], -1.0)
        return jnp.concatenate([out_id[:, None], score[order][:, None],
                                boxes_s], axis=1)

    return jax.vmap(per_sample)(cls_prob, loc_pred)


@register('_contrib_box_iou', input_names=['lhs', 'rhs'],
          param_defaults={'format': 'corner'}, differentiable=False)
def _box_iou_op(attrs, lhs, rhs):
    return _box_iou(lhs.reshape(-1, 4), rhs.reshape(-1, 4))
