"""Single operator registry — the NNVM Op registry re-imagined for XLA.

Reference: nnvm Op registry + include/mxnet/op_attr_types.h (FCompute,
FResourceRequest, mutable inputs) and src/nnvm/legacy_op_util.cc (which
bridged two registries — here there is deliberately ONE registry, per
SURVEY.md §2.1 N7's note).

Each op declares a pure JAX implementation ``fn(attrs, *arrays)``; everything
else (shape/type inference, gradient, kernel fusion, memory planning) is
derived by tracing/compiling that function with XLA — the whole
attach-op/plan-memory pass pipeline of src/executor collapses into jax.jit.

Conventions:
- ``fn`` returns a single array or a tuple of arrays.
- ops mutating inputs in the reference (BatchNorm moving stats — see
  include/mxnet/op_attr_types.h FMutateInputs) declare ``mutate_inputs``:
  a dict {input_index: extra_output_index}; the invoke layer writes those
  extra outputs back into the input NDArrays, preserving the reference's
  aux-state semantics under a functional compiler.
- ``train_aware`` ops receive ``__is_train__`` in attrs.
- ``needs_rng`` ops receive a uint32 PRNG key as their LAST array argument.
"""
import functools
import os

import jax
import jax.numpy as jnp

from ..base import MXNetError, normalize_attrs, attr_key

__all__ = ['OpDef', 'register', 'get', 'list_ops', 'jitted']

_OPS = {}


class OpDef:
    def __init__(self, name, fn, num_outputs=1, input_names=None,
                 param_defaults=None, differentiable=True, variadic=False,
                 mutate_inputs=None, needs_rng=False, num_visible_outputs=None,
                 train_aware=False, aux_inputs=(), key_var_num_args=None,
                 host=False, shape_fn=None, doc=None, optional_inputs=None):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs  # int or callable(attrs)->int
        # an explicit [] means a nullary op (_zeros, _arange, samplers);
        # only None falls back to the single-'data' convention
        self.input_names = (['data'] if input_names is None
                            else list(input_names))
        self.param_defaults = param_defaults or {}
        self.differentiable = differentiable
        self.variadic = variadic  # takes *args (Concat/add_n style)
        self.mutate_inputs = mutate_inputs or {}
        self.needs_rng = needs_rng
        self.num_visible_outputs = num_visible_outputs  # int or callable
        self.train_aware = train_aware
        self.aux_inputs = tuple(aux_inputs)  # names of inputs that are aux states
        self.key_var_num_args = key_var_num_args  # attr naming the input count
        # host ops run python/numpy on concrete arrays (image codecs,
        # legacy callback bridges). Inside traced programs they ride
        # jax.pure_callback, which needs shape_fn(attrs, in_shapes) ->
        # (out_shapes, out_dtypes); without one the op is imperative-only.
        self.host = host
        self.shape_fn = shape_fn
        # {input_name: gate_attr}: the input exists only when the gate
        # attr is truthy (CTCLoss lengths, Sequence* sequence_length) —
        # keeps symbol compose from fabricating variables for them
        self.optional_inputs = dict(optional_inputs or {})
        self.doc = doc or (fn.__doc__ or '')

    def n_outputs(self, attrs):
        n = self.num_outputs
        return n(attrs) if callable(n) else n

    def n_visible_outputs(self, attrs):
        n = self.num_visible_outputs
        if n is None:
            return self.n_outputs(attrs)
        return n(attrs) if callable(n) else n

    def arg_names(self, attrs=None, num_args=None):
        """Input names; variadic ops expand arg0..argN-1. Optional
        inputs are dropped unless their gate attr is truthy."""
        if self.variadic:
            n = num_args if num_args is not None else 0
            return ['arg%d' % i for i in range(n)]
        names = list(self.input_names)
        if self.optional_inputs:
            attrs = attrs or {}
            def _on(gate):
                v = attrs.get(gate, self.param_defaults.get(gate, False))
                return v not in (False, 'False', '0', 0, None, 'false')
            names = [n for n in names
                     if n not in self.optional_inputs
                     or _on(self.optional_inputs[n])]
        return names


def register(name, **kwargs):
    """Decorator registering ``fn(attrs, *arrays)`` as operator ``name``."""
    def deco(fn):
        op = OpDef(name, fn, **kwargs)
        _OPS[name] = op
        return fn
    return deco


def register_alias(alias, name):
    _OPS[alias] = _OPS[name]


def get(name):
    op = _OPS.get(name)
    if op is None:
        raise KeyError('operator %r is not registered' % (name,))
    return op


def exists(name):
    return name in _OPS


def list_ops():
    return sorted(_OPS)


def op_alias_groups():
    """Registration names grouped by shared OpDef: [[name, alias, ...]].
    The single source of alias resolution for the coverage gates
    (tests/conftest.py, test_op_sweep.py) — invoking any name in a
    group covers the whole group."""
    groups = {}
    for n in list_ops():
        groups.setdefault(id(_OPS[n]), []).append(n)
    return list(groups.values())


# -- execution-based coverage bookkeeping (tests/conftest.py gate) ----------
# Recording is keyed off the env at import so the per-invoke cost is a
# single branch when off. Canonical op names land in _INVOKED at every
# execution chokepoint (eager jit closures, apply_op, host bridges, the
# executor's traced/staged node loops); atexit appends them to
# MXTPU_OP_COVERAGE_FILE so subprocess test cases (examples, compat
# scripts) count toward the suite-wide union.
_INVOKED = set()
_COVERAGE_FILE = os.environ.get('MXTPU_OP_COVERAGE_FILE', '')
_COVERING = bool(_COVERAGE_FILE) or \
    os.environ.get('MXTPU_OP_COVERAGE', '') not in ('', '0')


def record(op):
    if _COVERING:
        _INVOKED.add(op.name)


def invoked_names():
    return frozenset(_INVOKED)


def _flush_invoked():
    if _COVERAGE_FILE and _INVOKED:
        try:
            with open(_COVERAGE_FILE, 'a') as f:
                f.write('\n'.join(sorted(_INVOKED)) + '\n')
        except OSError:
            pass


if _COVERING:
    import atexit
    atexit.register(_flush_invoked)


@functools.lru_cache(maxsize=None)
def _jitted_impl(name, akey):
    op = _OPS[name]
    record(op)
    attrs = dict(akey)

    def f(*arrays):
        return op.fn(attrs, *arrays)
    f.__name__ = name
    return jax.jit(f)


def lazy_op_module(module_globals, make_fn, underscore_only=False):
    """Build (__getattr__, __dir__) for a generated-op module path
    (nd/sym ``op`` and ``_internal`` — reference ndarray/op.py etc.).
    Resolved functions are cached into the module's globals."""
    def __getattr__(name):
        if exists(name):
            fn = make_fn(name)
            module_globals[name] = fn
            return fn
        raise AttributeError('operator %r is not registered' % (name,))

    def __dir__():
        ops = list_ops()
        return [n for n in ops if n.startswith('_')] \
            if underscore_only else ops
    return __getattr__, __dir__


def jitted(name, attrs):
    """Cached jit-compiled closure for (op, attrs). jax.jit adds the
    shape/dtype-keyed cache on top — together these are the CachedOp
    (src/c_api/c_api_ndarray.cc:628) analog for the eager path."""
    return _jitted_impl(name, attr_key(normalize_attrs(attrs)))


def apply_op(name, attrs, *arrays):
    """Uncached direct application (used inside symbol executors where the
    surrounding graph is already being traced under one jit)."""
    op = _OPS[name]
    record(op)
    return op.fn(attrs, *arrays)


def host_bridge(op, attrs):
    """Traceable wrapper for a host op: jax.pure_callback (so the python
    runs host-side at execution time, the reference's ExecType::kLocal)
    plus a custom_vjp that calls the op's registered python `backward`
    when one exists (legacy PythonOp/NDArrayOp protocol) and returns
    zero cotangents otherwise (codecs are non-differentiable).

    Requires op.shape_fn; host ops without one (data-dependent output
    shapes, e.g. _cvimdecode) cannot enter traced programs."""
    record(op)
    import numpy as np
    if op.shape_fn is None:
        raise MXNetError(
            'host op %r has a data-dependent output shape and can only '
            'be used imperatively (nd.*), not inside a traced graph'
            % op.name)

    def specs_for(arrays):
        in_shapes = [tuple(a.shape) for a in arrays]
        out_shapes, out_dtypes = op.shape_fn(attrs, in_shapes)
        # a None dtype means "same as input 0"
        fallback = arrays[0].dtype if arrays else np.float32
        specs = tuple(jax.ShapeDtypeStruct(tuple(s),
                                           np.dtype(fallback if d is None else d))
                      for s, d in zip(out_shapes, out_dtypes))
        # single-output ops return a bare array (the op-fn convention)
        return specs[0] if len(specs) == 1 else specs

    def run_host(*arrays):
        outs = op.fn(attrs, *arrays)
        if isinstance(outs, (tuple, list)):
            return tuple(np.asarray(o) for o in outs)
        return np.asarray(outs)

    @jax.custom_vjp
    def call(*arrays):
        return jax.pure_callback(run_host, specs_for(arrays), *arrays)

    def fwd(*arrays):
        outs = jax.pure_callback(run_host, specs_for(arrays), *arrays)
        return outs, (arrays, outs)

    def bwd(res, gouts):
        arrays, outs = res
        backward = getattr(op, 'legacy_backward', None)
        if backward is None:
            return tuple(jnp.zeros(a.shape, a.dtype) for a in arrays)
        in_specs = tuple(jax.ShapeDtypeStruct(tuple(a.shape), np.dtype(a.dtype))
                         for a in arrays)
        gouts_t = gouts if isinstance(gouts, (tuple, list)) else (gouts,)
        outs_t = outs if isinstance(outs, (tuple, list)) else (outs,)

        def run_bwd(gouts_, ins_, outs_):
            return backward(attrs, gouts_, ins_, outs_)
        return jax.pure_callback(run_bwd, in_specs, gouts_t, arrays, outs_t)

    call.defvjp(fwd, bwd)
    return call
