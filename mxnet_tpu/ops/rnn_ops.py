"""Fused multi-layer RNN op.

Reference: src/operator/rnn-inl.h (RNNParam :118, rnn_param_size/weight
layout) + cudnn_rnn-inl.h:40 (the only real implementation in 0.11 — the CPU
path is an empty TODO, rnn-inl.h:124-153). This rebuild provides a complete
implementation on every backend: per-layer ``jax.lax.scan`` over time, which
XLA compiles into a fused loop with MXU-tiled gate matmuls.

Weight layout matches the cuDNN canonical order the reference uses
(i2h weights, h2h weights per layer/direction, then i2h/h2h biases), so
FusedRNNCell.unfuse()-style round trips hold.
Gate order: LSTM [i, f, g, o]; GRU [r, z, n].
"""
import jax
import jax.numpy as jnp

from .registry import register


def _gates(mode):
    return {'rnn_relu': 1, 'rnn_tanh': 1, 'lstm': 4, 'gru': 3}[mode]


def rnn_param_size(num_layers, state_size, input_size, bidirectional, mode):
    """Total flat parameter count (reference rnn-inl.h GetParamSize)."""
    g = _gates(mode)
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * dirs
        size += dirs * g * state_size * (isz + state_size)   # W + R
    size += num_layers * dirs * g * state_size * 2           # biases
    return size


def _unpack(params, num_layers, state_size, input_size, dirs, mode):
    g = _gates(mode)
    H = state_size
    ws, offset = [], 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else H * dirs
        layer_ws = []
        for d in range(dirs):
            W = params[offset:offset + g * H * isz].reshape(g * H, isz)
            offset += g * H * isz
            R = params[offset:offset + g * H * H].reshape(g * H, H)
            offset += g * H * H
            layer_ws.append((W, R))
        ws.append(layer_ws)
    bs = []
    for layer in range(num_layers):
        layer_bs = []
        for d in range(dirs):
            bW = params[offset:offset + g * H]
            offset += g * H
            bR = params[offset:offset + g * H]
            offset += g * H
            layer_bs.append((bW, bR))
        bs.append(layer_bs)
    return ws, bs


def _cell_step(mode, H):
    if mode == 'lstm':
        def step(carry, gates_x, R, bR):
            h, c = carry
            gates = gates_x + jnp.dot(h, R.T) + bR
            i = jax.nn.sigmoid(gates[:, 0 * H:1 * H])
            f = jax.nn.sigmoid(gates[:, 1 * H:2 * H])
            g = jnp.tanh(gates[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(gates[:, 3 * H:4 * H])
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        return step
    if mode == 'gru':
        def step(carry, gates_x, R, bR):
            h, _ = carry
            rz_x = gates_x[:, :2 * H]
            n_x = gates_x[:, 2 * H:]
            rz_h = jnp.dot(h, R[:2 * H].T) + bR[:2 * H]
            r = jax.nn.sigmoid(rz_x[:, :H] + rz_h[:, :H])
            z = jax.nn.sigmoid(rz_x[:, H:] + rz_h[:, H:])
            n = jnp.tanh(n_x + r * (jnp.dot(h, R[2 * H:].T) + bR[2 * H:]))
            h_new = (1 - z) * n + z * h
            return (h_new, h_new), h_new
        return step
    act = jax.nn.relu if mode == 'rnn_relu' else jnp.tanh

    def step(carry, gates_x, R, bR):
        h, _ = carry
        h_new = act(gates_x + jnp.dot(h, R.T) + bR)
        return (h_new, h_new), h_new
    return step


def _run_layer(x, W, R, bW, bR, h0, c0, mode, H, reverse=False):
    """One direction of one layer. x: (T, N, I) → (T, N, H)."""
    # hoist the input projection out of the scan: one big MXU matmul
    gates_x = jnp.einsum('tni,gi->tng', x, W) + bW
    step = _cell_step(mode, H)

    def body(carry, gx):
        return step(carry, gx, R, bR)

    (hT, cT), ys = jax.lax.scan(body, (h0, c0), gates_x, reverse=reverse)
    if reverse:
        pass  # lax.scan(reverse=True) already emits outputs in input order
    return ys, hT, cT


@register('RNN', input_names=['data', 'parameters', 'state', 'state_cell'],
          param_defaults={'state_size': 0, 'num_layers': 1,
                          'bidirectional': False, 'mode': 'lstm', 'p': 0.0,
                          'state_outputs': False, 'lstm_state_clip_min': None,
                          'lstm_state_clip_max': None},
          num_outputs=lambda attrs: (3 if attrs.get('mode') == 'lstm' else 2)
          if attrs.get('state_outputs', False) else 1,
          needs_rng=True, train_aware=True)
def _rnn(attrs, data, parameters, state, *rest):
    mode = attrs.get('mode', 'lstm')
    key = rest[-1]
    state_cell = rest[0] if (mode == 'lstm' and len(rest) > 1) else None
    H = int(attrs['state_size'])
    L = int(attrs.get('num_layers', 1))
    dirs = 2 if attrs.get('bidirectional', False) else 1
    p = attrs.get('p', 0.0)
    training = attrs.get('__is_train__', False)

    T, N, I = data.shape
    ws, bs = _unpack(parameters, L, H, I, dirs, mode)

    x = data
    h_out, c_out = [], []
    for layer in range(L):
        outs = []
        for d in range(dirs):
            W, R = ws[layer][d]
            bW, bR = bs[layer][d]
            idx = layer * dirs + d
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None else jnp.zeros_like(h0)
            ys, hT, cT = _run_layer(x, W, R, bW, bR, h0, c0, mode, H,
                                    reverse=(d == 1))
            outs.append(ys)
            h_out.append(hT)
            c_out.append(cT)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0 and training and layer < L - 1:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1 - p, x.shape)
            x = jnp.where(mask, x / (1 - p), 0.0)

    outputs = [x]
    if attrs.get('state_outputs', False):
        outputs.append(jnp.stack(h_out))
        if mode == 'lstm':
            outputs.append(jnp.stack(c_out))
    return tuple(outputs) if len(outputs) > 1 else outputs[0]
