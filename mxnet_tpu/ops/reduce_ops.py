"""Reduction & broadcast-axis ops.

Reference: src/operator/tensor/broadcast_reduce_op_value.cc and
broadcast_reduce-inl.h — sum/mean/prod/max/min/norm/argmax/argmin + the
broadcast_axis/broadcast_to pair used by the gradient of reductions.
"""
import jax.numpy as jnp

from .registry import register, register_alias


def _axis(attrs):
    ax = attrs.get('axis', None)
    if ax is None or ax == ():
        return None
    if isinstance(ax, (list, tuple)):
        return tuple(ax) if len(ax) else None
    return int(ax)


def _scalar1(out):
    """Full reductions yield a (1,) scalar array, not 0-d
    (broadcast_reduce_op.h:148 Shape1(1)); scripts index reduce(x)[0]."""
    return out.reshape(1) if out.ndim == 0 else out


def _r(name, f, differentiable=True, aliases=()):
    @register(name, param_defaults={'axis': None, 'keepdims': False,
                                    'exclude': False},
              differentiable=differentiable)
    def op(attrs, x, _f=f):
        ax = _axis(attrs)
        if attrs.get('exclude', False) and ax is not None:
            axes = (ax,) if isinstance(ax, int) else ax
            ax = tuple(i for i in range(x.ndim) if i not in
                       tuple(a % x.ndim for a in axes))
        return _scalar1(
            _f(x, axis=ax, keepdims=bool(attrs.get('keepdims', False))))
    for a in aliases:
        register_alias(a, name)
    return op


_r('sum', jnp.sum, aliases=('sum_axis',))
_r('mean', jnp.mean)
_r('prod', jnp.prod)
_r('nansum', jnp.nansum)
_r('nanprod', jnp.nanprod)
_r('max', jnp.max, aliases=('max_axis',))
_r('min', jnp.min, aliases=('min_axis',))


@register('norm', param_defaults={'axis': None, 'keepdims': False, 'ord': 2})
def _norm(attrs, x):
    ax = _axis(attrs)
    ordv = attrs.get('ord', 2)
    if ordv == 1:
        out = jnp.sum(jnp.abs(x), axis=ax,
                      keepdims=bool(attrs.get('keepdims', False)))
    else:
        out = jnp.sqrt(jnp.sum(jnp.square(x), axis=ax,
                               keepdims=bool(attrs.get('keepdims', False))))
    return _scalar1(out)


def _arg(name, f):
    @register(name, param_defaults={'axis': None, 'keepdims': False},
              differentiable=False)
    def op(attrs, x, _f=f):
        ax = attrs.get('axis', None)
        if ax is None:
            res = _f(x.ravel(), axis=0)
            if attrs.get('keepdims', False):
                res = res.reshape((1,) * x.ndim)
            # ReduceAxisShapeImpl: global argmax/argmin is Shape1(1)
            return _scalar1(res.astype(jnp.float32))
        res = _f(x, axis=int(ax))
        if attrs.get('keepdims', False):
            res = jnp.expand_dims(res, int(ax))
        return _scalar1(res.astype(jnp.float32))
    return op


_arg('argmax', jnp.argmax)
_arg('argmin', jnp.argmin)


@register('argmax_channel', differentiable=False)
def _argmax_channel(attrs, x):
    return jnp.argmax(x, axis=1).astype(jnp.float32)


@register('broadcast_axis', param_defaults={'axis': (), 'size': ()})
def _broadcast_axis(attrs, x):
    axes = attrs.get('axis', ())
    sizes = attrs.get('size', ())
    if isinstance(axes, int):
        axes, sizes = (axes,), (sizes,)
    shape = list(x.shape)
    for a, s in zip(axes, sizes):
        shape[a] = s
    return jnp.broadcast_to(x, tuple(shape))


register_alias('broadcast_axes', 'broadcast_axis')


@register('broadcast_to', param_defaults={'shape': ()})
def _broadcast_to(attrs, x):
    tgt = list(attrs['shape'])
    for i, s in enumerate(tgt):
        if s == 0:
            tgt[i] = x.shape[i]
    return jnp.broadcast_to(x, tuple(tgt))


@register('broadcast_like', input_names=['lhs', 'rhs'])
def _broadcast_like(attrs, lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)
