"""Random sampling ops.

Reference: src/operator/random/sample_op.cc (uniform/normal/gamma/
exponential/poisson/negative_binomial/generalized_negative_binomial) and
multisample_op.cc / sample_multinomial_op.cc.

Each op takes the PRNG key as its trailing array argument (needs_rng=True),
so the op body is pure and jittable — the TPU-native replacement for the
per-device mshadow::Random resource (src/resource.cc:84).
"""
import jax
import jax.numpy as jnp

from ..base import np_dtype
from .registry import register, register_alias


def _shape(attrs):
    s = attrs.get('shape', ())
    if isinstance(s, int):
        return (s,)
    # omitted shape draws ONE sample as a (1,) array, not 0-d
    # (sample_op.h: TShape() default -> Shape1(1)); scripts index [0]
    return tuple(s) if s else (1,)


def _dt(attrs):
    d = attrs.get('dtype', 'float32')
    if d in (None, 'None'):
        d = 'float32'
    return np_dtype(d)


@register('_random_uniform', input_names=[], needs_rng=True,
          differentiable=False,
          param_defaults={'low': 0.0, 'high': 1.0, 'shape': (), 'dtype': 'float32'})
def _uniform(attrs, key):
    return jax.random.uniform(key, _shape(attrs), dtype=_dt(attrs),
                              minval=attrs.get('low', 0.0),
                              maxval=attrs.get('high', 1.0))


register_alias('uniform', '_random_uniform')
register_alias('random_uniform', '_random_uniform')


@register('_random_normal', input_names=[], needs_rng=True,
          differentiable=False,
          param_defaults={'loc': 0.0, 'scale': 1.0, 'shape': (), 'dtype': 'float32'})
def _normal(attrs, key):
    return attrs.get('loc', 0.0) + attrs.get('scale', 1.0) * \
        jax.random.normal(key, _shape(attrs), dtype=_dt(attrs))


register_alias('normal', '_random_normal')
register_alias('random_normal', '_random_normal')


@register('_random_gamma', input_names=[], needs_rng=True, differentiable=False,
          param_defaults={'alpha': 1.0, 'beta': 1.0, 'shape': (), 'dtype': 'float32'})
def _gamma(attrs, key):
    return jax.random.gamma(key, attrs.get('alpha', 1.0), _shape(attrs),
                            dtype=_dt(attrs)) * attrs.get('beta', 1.0)


register_alias('random_gamma', '_random_gamma')


@register('_random_exponential', input_names=[], needs_rng=True,
          differentiable=False,
          param_defaults={'lam': 1.0, 'shape': (), 'dtype': 'float32'})
def _exponential(attrs, key):
    return jax.random.exponential(key, _shape(attrs), dtype=_dt(attrs)) / \
        attrs.get('lam', 1.0)


register_alias('random_exponential', '_random_exponential')


@register('_random_poisson', input_names=[], needs_rng=True,
          differentiable=False,
          param_defaults={'lam': 1.0, 'shape': (), 'dtype': 'float32'})
def _poisson(attrs, key):
    return jax.random.poisson(key, attrs.get('lam', 1.0), _shape(attrs)).astype(_dt(attrs))


register_alias('random_poisson', '_random_poisson')


@register('_random_negative_binomial', input_names=[], needs_rng=True,
          differentiable=False,
          param_defaults={'k': 1, 'p': 1.0, 'shape': (), 'dtype': 'float32'})
def _negbinomial(attrs, key):
    k, p = attrs.get('k', 1), attrs.get('p', 1.0)
    # NB(k,p) = Poisson(Gamma(k, (1-p)/p))
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k, _shape(attrs)) * ((1 - p) / max(p, 1e-6))
    return jax.random.poisson(kp, lam, _shape(attrs)).astype(_dt(attrs))


register_alias('random_negative_binomial', '_random_negative_binomial')


@register('_random_generalized_negative_binomial', input_names=[],
          needs_rng=True, differentiable=False,
          param_defaults={'mu': 1.0, 'alpha': 1.0, 'shape': (), 'dtype': 'float32'})
def _gen_negbinomial(attrs, key):
    mu, alpha = attrs.get('mu', 1.0), attrs.get('alpha', 1.0)
    kg, kp = jax.random.split(key)
    shape_k = 1.0 / max(alpha, 1e-6)
    lam = jax.random.gamma(kg, shape_k, _shape(attrs)) * (mu * alpha)
    return jax.random.poisson(kp, lam, _shape(attrs)).astype(_dt(attrs))


register_alias('random_generalized_negative_binomial',
               '_random_generalized_negative_binomial')


@register('_sample_multinomial', input_names=['data'], needs_rng=True,
          differentiable=False,
          param_defaults={'shape': (), 'get_prob': False, 'dtype': 'int32'})
def _sample_multinomial(attrs, data, key):
    """Reference sample_multinomial_op.cc — categorical draw per row."""
    n = attrs.get('shape', ()) or ()
    if isinstance(n, int):
        n = (n,)
    logits = jnp.log(jnp.maximum(data, 1e-20))
    out_shape = data.shape[:-1] + tuple(n)
    draws = jax.random.categorical(
        key, logits[..., None, :] if n else logits,
        axis=-1, shape=out_shape if n else data.shape[:-1])
    return draws.astype(_dt({'dtype': attrs.get('dtype', 'int32')}))


register_alias('sample_multinomial', '_sample_multinomial')


@register('_shuffle', needs_rng=True, differentiable=False)
def _shuffle(attrs, data, key):
    return jax.random.permutation(key, data, axis=0)


register_alias('shuffle', '_shuffle')


def _elemwise_sample(name, sampler, in_names):
    """sample_uniform etc: per-element distribution params (multisample_op.cc)."""
    @register(name, input_names=in_names, needs_rng=True, differentiable=False,
              param_defaults={'shape': (), 'dtype': 'float32'})
    def op(attrs, *args):
        key = args[-1]
        params = args[:-1]
        # unlike the zero-input _random_* family, an omitted shape here
        # means NO extra trailing dims (multisample_op.h concatenates an
        # empty sshape): sample_uniform((3,) low, (3,) high) -> (3,)
        s = attrs.get('shape', ())
        extra = ((s,) if isinstance(s, int) else tuple(s)) if s else ()
        out_shape = params[0].shape + extra
        bparams = [jnp.reshape(p, p.shape + (1,) * len(extra)) for p in params]
        return sampler(key, bparams, out_shape).astype(_dt(attrs))
    return op


_elemwise_sample('_sample_uniform',
                 lambda key, p, s: p[0] + (p[1] - p[0]) * jax.random.uniform(key, s),
                 ['low', 'high'])
register_alias('sample_uniform', '_sample_uniform')
_elemwise_sample('_sample_normal',
                 lambda key, p, s: p[0] + p[1] * jax.random.normal(key, s),
                 ['mu', 'sigma'])
register_alias('sample_normal', '_sample_normal')
_elemwise_sample('_sample_gamma',
                 lambda key, p, s: jax.random.gamma(key, jnp.broadcast_to(p[0], s)) * p[1],
                 ['alpha', 'beta'])
register_alias('sample_gamma', '_sample_gamma')
_elemwise_sample('_sample_exponential',
                 lambda key, p, s: jax.random.exponential(key, s) / p[0],
                 ['lam'])
register_alias('sample_exponential', '_sample_exponential')
_elemwise_sample('_sample_poisson',
                 lambda key, p, s: jax.random.poisson(key, jnp.broadcast_to(p[0], s)).astype(jnp.float32),
                 ['lam'])
register_alias('sample_poisson', '_sample_poisson')
