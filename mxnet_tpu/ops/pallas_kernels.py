"""Pallas TPU kernels for the hot ops.

The reference hand-writes CUDA for its hot paths (softmax.cu, im2col,
cudnn wrappers — SURVEY.md N6); on TPU, XLA's fusion already covers
most of that, and these kernels target what XLA does NOT schedule
optimally on the MXU/VMEM hierarchy:

- :func:`flash_attention` — O(T) VMEM attention: online-softmax over
  K/V tiles streamed through VMEM; no [Tq, Tk] score matrix in HBM.
- :func:`fused_rmsnorm` / :func:`fused_layernorm` — one pass over the
  feature dim in VMEM (XLA emits separate reduce+scale passes).
- :func:`softmax_xent` — fused logsumexp + gather loss for LM heads,
  avoiding the [N, V] softmax materialization.

Every kernel runs `interpret=True` off-TPU, so the same code path is
exercised by the CPU test mesh (tests/unittest/test_pallas.py) and
compiled for real on TPU. Backward passes use jax.custom_vjp with a
recompute strategy (jax.checkpoint-style), keeping kernels forward-only.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ['flash_attention', 'flash_attention_lse', 'fused_rmsnorm',
           'fused_layernorm', 'fused_softmax', 'softmax_xent']


def use_fused():
    """Dispatch policy for the registry ops: real kernels on TPU; on CPU
    the jnp formulations are faster than interpret-mode pallas, so the
    fused path is opt-in there (MXTPU_FORCE_PALLAS=1, used in tests)."""
    from ..config import flags as _flags
    _flags.reload('MXTPU_FORCE_PALLAS')  # tests toggle it per-case
    return (jax.default_backend() == 'tpu'
            or _flags.get('MXTPU_FORCE_PALLAS'))

_NEG = -1e30


def _interpret():
    return jax.default_backend() != 'tpu'


def _block_ok(blk, dim):
    """Mosaic's second-to-minor block rule (jax pallas/mosaic/lowering.py
    _check_block_mappings): a second-to-minor block dim is legal iff it
    equals the array dim or is a multiple of 8. (Minor dims and rank-1
    blocks need %128 or equality instead — here every minor dim and
    every rank-1 block equals its array dim: full feature rows, full
    (D,) params, and the [.., blk, 1] columns that carry per-row
    outputs.) Interpret mode (the CPU test mesh) does NOT enforce any
    of this, so every block-size choice goes through these helpers to
    keep CPU-green == TPU-lowerable."""
    return blk == dim or blk % 8 == 0


def _pick_block(want, n):
    """Largest Mosaic-legal divisor of ``n`` that is <= want. Falls back
    to the whole axis (always legal, but only sensible when the full
    block fits VMEM — the row kernels pre-pad ``n`` to a multiple of 8
    via :func:`_pad_and_block` so they never take the fallback on awkward
    sizes; flash q tiles share the fallback with the by-design
    full-axis K/V blocks)."""
    for b in range(min(want, n), 0, -1):
        if n % b == 0 and _block_ok(b, n):
            return b
    return n


def _pad_and_block(want, n):
    """(pad, blk) for tiling ``n`` rows at ~``want``: pad rows up to the
    next multiple of 8 when ``n`` has no Mosaic-legal divisor <= want,
    then pick the largest legal divisor of ``n + pad``. Keeps wide row
    kernels (e.g. a [N, vocab] xent) from falling back to a whole-array
    block that cannot fit VMEM when N has no small legal divisor
    (N = 2 * prime, ...). ``want`` is clamped to >= 8 internally so
    that once padded to a multiple of 8, blk=8 always qualifies — the
    fallback is only reachable for n <= want (small full blocks)."""
    want = max(want, 8)
    pad = (-n) % 8 if (n > want and _pick_block(want, n) == n) else 0
    return pad, _pick_block(want, n + pad)


# ---------------------------------------------------------------------------
# Flash attention
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, causal, scale, blk_q,
                  blk_k, offset):
    """Grid: (batch*heads, Tq/blk_q). K/V streamed in blk_k tiles.

    `offset` = Tk - Tq aligns the causal mask bottom-right (decode
    convention): query row i may see key cols <= i + offset — identical
    to the oracle's tril(ones(Tq, Tk), Tk - Tq) in _flash_ref.
    """
    q = q_ref[0].astype(jnp.float32) * scale          # [blk_q, D]
    Tk = k_ref.shape[1]
    qi = pl.program_id(1)

    def body(start, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(start * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(start * blk_k, blk_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = (qi * blk_q + rows + offset) >= (start * blk_k + cols)
            s = jnp.where(mask, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.dot(p, v,
                                       preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    total = Tk // blk_k
    if causal:
        # K blocks strictly after this q block's last visible col are
        # fully masked: last visible col = (qi+1)*blk_q - 1 + offset
        n_blocks = jnp.clip(pl.cdiv((qi + 1) * blk_q + offset, blk_k),
                            0, total)
    else:
        n_blocks = total
    acc = jnp.zeros((blk_q, v_ref.shape[2]), jnp.float32)
    m = jnp.full((blk_q, 1), _NEG, jnp.float32)
    l = jnp.zeros((blk_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # log-sum-exp of the scaled scores per query row — lets callers (ring
    # attention) merge normalized per-chunk outputs exactly. Kept as a
    # [blk_q, 1] column: a (1, blk_q, 1) block is Mosaic-legal (minor dim
    # equals the array's), a (1, blk_q) one is not (second-to-minor 1).
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30)))


def _flash_fwd_impl(q, k, v, causal, scale, blk_q, blk_k):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if causal and Tq > Tk:
        # bottom-right alignment gives the first Tq-Tk query rows zero
        # visible keys (softmax over empty set — NaN in the oracle);
        # reject rather than return silently-wrong finite values
        raise ValueError('causal attention requires Tq <= Tk '
                         '(got Tq=%d, Tk=%d)' % (Tq, Tk))
    if Tk == 0:
        # softmax over an empty key set is undefined (NaN in the
        # oracle); fail loudly instead of tracing a 0-size block
        raise ValueError('attention requires at least one key (Tk=0)')
    if B * H == 0 or Tq == 0:        # empty batch/seq: nothing to launch
        return (jnp.zeros((B, Tq, H, D), q.dtype),
                jnp.zeros((B, H, Tq), jnp.float32))
    # block_q/block_k are advisory: coerced to the largest Mosaic-legal
    # divisor of the axis (<= requested). The q axis is PADDED (zeros,
    # sliced off below) when it has no small legal divisor — a
    # whole-axis blk_q would put an O(Tq x blk_k) score tile in VMEM.
    # blk_k may fall back to Tk: the K/V blocks are full-axis by design,
    # and the score tile stays bounded by blk_q rows.
    pad_q, blk_q = _pad_and_block(min(blk_q, Tq), Tq)
    blk_k = _pick_block(blk_k, Tk)
    # [B, T, H, D] -> [B*H, T, D] for a clean 2-d grid
    qh = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kh = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vh = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    if pad_q:
        # zero q rows appended past Tq: their scores are 0 -> a uniform
        # finite softmax; the causal offset keys off the ORIGINAL Tq and
        # the rows are sliced off below, so real rows are untouched
        qh = jnp.concatenate(
            [qh, jnp.zeros((B * H, pad_q, D), qh.dtype)], axis=1)
    Tq_p = Tq + pad_q

    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale,
                               blk_q=blk_q, blk_k=blk_k, offset=Tk - Tq)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Tq_p // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, blk_q, 1), lambda b, i: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
                   jax.ShapeDtypeStruct((B * H, Tq_p, 1), jnp.float32)],
        interpret=_interpret(),
    )(qh, kh, vh)
    out = out[:, :Tq].reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    lse = lse[:, :Tq].reshape(B, H, Tq)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128):
    """Memory-efficient attention; shapes [B, T, H, D] like
    ring_attention.attention_reference (its numeric oracle).

    ``block_q``/``block_k`` are advisory tile sizes: they are coerced to
    the largest Mosaic-legal divisor of the respective sequence axis
    (so non-dividing or non-8-multiple requests silently shrink/grow
    rather than erroring)."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_fwd_impl(q, k, v, causal, s, block_q, block_k)[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_lse(q, k, v, causal=False, scale=None, block_q=128,
                        block_k=128):
    """flash_attention that also returns the per-row log-sum-exp
    [B, H, Tq] — the merge statistic ring attention needs to combine
    normalized chunk outputs exactly. Backward recomputes via the
    reference formulation (flash-paper strategy), with the lse cotangent
    folded in (ring attention's merge weights depend on lse)."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_fwd_impl(q, k, v, causal, s, block_q, block_k)


def _flash_lse_ref(q, k, v, causal, scale):
    """(out, lse) in plain jnp — the differentiable oracle for the
    kernel's backward."""
    s = jnp.einsum('bqhd,bkhd->bhqk', q * scale, k)
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        s = jnp.where(mask, s, _NEG)
    lse = jax.nn.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    return jnp.einsum('bhqk,bkhd->bqhd', p, v), lse


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k):
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_fwd_impl(q, k, v, causal, s, block_q, block_k), (q, k, v)


def _flash_lse_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    _, vjp = jax.vjp(lambda q, k, v: _flash_lse_ref(q, k, v, causal, s),
                     q, k, v)
    return vjp(g)


flash_attention_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _flash_ref(q, k, v, causal, scale):
    s = jnp.einsum('bqhd,bkhd->bhqk', q * scale, k)
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    s = scale if scale is not None else q.shape[-1] ** -0.5
    out, _ = _flash_fwd_impl(q, k, v, causal, s, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, res, g):
    # recompute-based backward (the flash paper's strategy; here via jax
    # autodiff of the reference formulation — XLA fuses it blockwise)
    q, k, v = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    _, vjp = jax.vjp(lambda q, k, v: _flash_ref(q, k, v, causal, s), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Fused normalization
# ---------------------------------------------------------------------------

def _rmsnorm_kernel(x_ref, g_ref, o_ref, eps):
    x = x_ref[:].astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[:] = (x * inv * g_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _layernorm_kernel(x_ref, g_ref, b_ref, o_ref, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * g_ref[:].astype(jnp.float32) +
                b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _norm_call(kernel, arrs, x, block_rows=256):
    lead = x.shape[:-1]
    D = x.shape[-1]
    x2 = x.reshape(-1, D)
    N = x2.shape[0]
    if N == 0:                       # empty batch: nothing to launch
        return x2.reshape(lead + (D,))
    pad, blk = _pad_and_block(block_rows, N)
    if pad:
        x2 = jnp.concatenate([x2, jnp.zeros((pad, D), x2.dtype)])
    out = pl.pallas_call(
        kernel,
        grid=((N + pad) // blk,),
        in_specs=[pl.BlockSpec((blk, D), lambda i: (i, 0))] +
                 [pl.BlockSpec((D,), lambda i: (0,))] * len(arrs),
        out_specs=pl.BlockSpec((blk, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N + pad, D), x.dtype),
        interpret=_interpret(),
    )(x2, *arrs)
    return out[:N].reshape(lead + (D,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_rmsnorm(x, gamma, eps=1e-6):
    """RMSNorm in one VMEM pass over the feature dim."""
    def kern(x_ref, g_ref, o_ref):
        _rmsnorm_kernel(x_ref, g_ref, o_ref, eps)
    return _norm_call(kern, (gamma,), x)


def _rms_ref(x, gamma, eps):
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (x32 * inv * gamma.astype(jnp.float32)).astype(x.dtype)


def _rms_fwd(x, gamma, eps):
    return fused_rmsnorm(x, gamma, eps), (x, gamma)


def _rms_bwd(eps, res, g):
    x, gamma = res
    _, vjp = jax.vjp(lambda x, gm: _rms_ref(x, gm, eps), x, gamma)
    return vjp(g)


fused_rmsnorm.defvjp(_rms_fwd, _rms_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layernorm(x, gamma, beta, eps=1e-5):
    """LayerNorm in one VMEM pass over the feature dim."""
    def kern(x_ref, g_ref, b_ref, o_ref):
        _layernorm_kernel(x_ref, g_ref, b_ref, o_ref, eps)
    return _norm_call(kern, (gamma, beta), x)


def _ln_ref(x, gamma, beta, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), -1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32) +
            beta.astype(jnp.float32)).astype(x.dtype)


def _ln_fwd(x, gamma, beta, eps):
    return fused_layernorm(x, gamma, beta, eps), (x, gamma, beta)


def _ln_bwd(eps, res, g):
    x, gamma, beta = res
    _, vjp = jax.vjp(lambda x, gm, b: _ln_ref(x, gm, b, eps), x, gamma, beta)
    return vjp(g)


fused_layernorm.defvjp(_ln_fwd, _ln_bwd)


# ---------------------------------------------------------------------------
# Fused row softmax
# ---------------------------------------------------------------------------

def _softmax_kernel(x_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    m = x.max(axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[:] = (e / e.sum(axis=-1, keepdims=True)).astype(o_ref.dtype)


@jax.custom_vjp
def fused_softmax(x):
    """Last-axis softmax in one VMEM pass (max+exp+sum+div fused)."""
    return _norm_call(_softmax_kernel, (), x)


def _softmax_fwd(x):
    y = fused_softmax(x)
    return y, y


def _softmax_bwd(y, g):
    # d/dx softmax = y * (g - sum(g*y)) along the row
    return (y * (g - jnp.sum(g * y, axis=-1, keepdims=True)),)


fused_softmax.defvjp(_softmax_fwd, _softmax_bwd)


# ---------------------------------------------------------------------------
# Fused softmax cross-entropy
# ---------------------------------------------------------------------------

def _xent_kernel(logits_ref, labels_ref, loss_ref):
    # labels/loss ride as [blk, 1] columns: rank-1 blocks would need
    # blk % 128 == 0 on real TPU (Mosaic's rank-1 rule); a [blk, 1]
    # block only needs blk % 8 with its minor dim equal to the array's
    x = logits_ref[:].astype(jnp.float32)          # [blk, V]
    m = x.max(axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1)) + m[:, 0]
    n = x.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = cols == labels_ref[:].reshape(n, 1)
    gold = jnp.sum(jnp.where(onehot, x, 0.0), axis=-1)
    loss_ref[:] = (lse - gold).astype(loss_ref.dtype)[:, None]


@jax.custom_vjp
def softmax_xent(logits, labels):
    """Per-example CE loss [N] from logits [N, V] + int labels [N],
    without materializing softmax in HBM."""
    N, V = logits.shape
    if N == 0:                       # empty batch: nothing to launch
        return jnp.zeros((0,), jnp.float32)
    pad, blk = _pad_and_block(128, N)
    if pad:
        logits = jnp.concatenate([logits, jnp.zeros((pad, V), logits.dtype)])
        labels = jnp.concatenate([labels, jnp.zeros((pad,), labels.dtype)])
    return pl.pallas_call(
        _xent_kernel,
        grid=((N + pad) // blk,),
        in_specs=[pl.BlockSpec((blk, V), lambda i: (i, 0)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((blk, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N + pad, 1), jnp.float32),
        interpret=_interpret(),
    )(logits, labels[:, None])[:N, 0]


def _xent_fwd(logits, labels):
    return softmax_xent(logits, labels), (logits, labels)


def _xent_bwd(res, g):
    logits, labels = res
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=p.dtype)
    return ((p - onehot) * g[:, None]).astype(logits.dtype), None


softmax_xent.defvjp(_xent_fwd, _xent_bwd)
