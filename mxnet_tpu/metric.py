"""Evaluation metrics.

Reference: python/mxnet/metric.py (1,132 LoC): EvalMetric base + registry
(Accuracy, TopKAccuracy, F1, Perplexity, MAE/MSE/RMSE, CrossEntropy,
NegativeLogLikelihood, PearsonCorrelation, Loss, Torch, Caffe, CustomMetric,
np adapter, CompositeEvalMetric).
"""
import math

import numpy

from . import ndarray

__all__ = ['EvalMetric', 'CompositeEvalMetric', 'Accuracy', 'TopKAccuracy',
           'Torch', 'Caffe',
           'F1', 'Perplexity', 'MAE', 'MSE', 'RMSE', 'CrossEntropy', 'Loss',
           'PearsonCorrelation', 'CustomMetric', 'np', 'create', 'check_label_shapes']

_REGISTRY = {}


def register(name=None):
    def deco(klass):
        _REGISTRY[(name or klass.__name__).lower()] = klass
        return klass
    return deco


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        return _REGISTRY[metric.lower()](*args, **kwargs)
    raise TypeError('metric should be string, callable, or EvalMetric')


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError('Shape of labels {} does not match shape of '
                         'predictions {}'.format(label_shape, pred_shape))


class EvalMetric:
    """Reference metric.py:34."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return 'EvalMetric: {}'.format(dict(self.get_name_value()))

    def get_config(self):
        config = self._kwargs.copy()
        config.update({'metric': self.__class__.__name__, 'name': self.name,
                       'output_names': self.output_names,
                       'label_names': self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name='composite', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, 'metrics', []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name) if not isinstance(name, list) else names.extend(name)
            values.append(value) if not isinstance(value, list) else values.extend(value)
        return (names, values)


@register()
@register('acc')
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name='accuracy', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            p = pred_label.asnumpy()
            if p.ndim > 1 and p.shape[-1 if self.axis == 1 and p.ndim == 2 else self.axis] > 1:
                p = numpy.argmax(p, axis=self.axis if p.ndim > self.axis else -1)
            lab = label.asnumpy().astype('int32').ravel()
            p = p.astype('int32').ravel()
            check_label_shapes(lab, p, shape=1)
            self.sum_metric += (p == lab).sum()
            self.num_inst += len(p)


@register('top_k_accuracy')
@register('top_k_acc')
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name='top_k_accuracy', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert self.top_k > 1, 'Please use Accuracy if top_k is no more than 1'
        self.name += '_%d' % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) <= 2, 'Predictions should be no more than 2 dims'
            pred = numpy.argsort(pred_label.asnumpy().astype('float32'), axis=1)
            lab = label.asnumpy().astype('int32')
            num_samples = pred.shape[0]
            num_dims = len(pred.shape)
            if num_dims == 1:
                self.sum_metric += (pred.ravel() == lab.ravel()).sum()
            elif num_dims == 2:
                num_classes = pred.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (pred[:, num_classes - 1 - j].ravel() ==
                                        lab.ravel()).sum()
            self.num_inst += num_samples


@register()
class F1(EvalMetric):
    def __init__(self, name='f1', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = pred.asnumpy()
            label = label.asnumpy().astype('int32')
            pred_label = numpy.argmax(pred, axis=1)
            check_label_shapes(label, pred_label, shape=1)
            if len(numpy.unique(label)) > 2:
                raise ValueError('F1 currently only supports binary classification.')
            tp = ((pred_label == 1) & (label == 1)).sum()
            fp = ((pred_label == 1) & (label == 0)).sum()
            fn = ((pred_label == 0) & (label == 1)).sum()
            precision = tp / (tp + fp) if tp + fp > 0 else 0.
            recall = tp / (tp + fn) if tp + fn > 0 else 0.
            f1 = 2 * precision * recall / (precision + recall) \
                if precision + recall > 0 else 0.
            self.sum_metric += f1
            self.num_inst += 1


@register()
class Perplexity(EvalMetric):
    """Reference metric.py Perplexity (ignore_label support)."""

    def __init__(self, ignore_label=None, axis=-1, name='perplexity',
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.
        num = 0
        for label, pred in zip(labels, preds):
            assert label.size == pred.size / pred.shape[-1], \
                'shape mismatch: %s vs. %s' % (label.shape, pred.shape)
            label = label.as_in_context(pred.context).reshape((label.size,))
            pred = ndarray.pick(pred, label.astype(dtype='int32'), axis=self.axis)
            lab_np = label.asnumpy()
            pred_np = pred.asnumpy()
            if self.ignore_label is not None:
                ignore = (lab_np == self.ignore_label)
                num -= int(ignore.sum())
                pred_np = pred_np * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, pred_np)))
            num += pred_np.size
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register()
class MAE(EvalMetric):
    def __init__(self, name='mae', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.abs(label - pred).mean()
            self.num_inst += 1


@register()
class MSE(EvalMetric):
    def __init__(self, name='mse', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register()
class RMSE(EvalMetric):
    def __init__(self, name='rmse', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@register('ce')
@register()
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name='cross-entropy', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register('nll_loss')
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name='nll-loss', output_names=None,
                 label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


@register('pearsonr')
class PearsonCorrelation(EvalMetric):
    def __init__(self, name='pearsonr', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            check_label_shapes(label, pred, 1)
            label = label.asnumpy()
            pred = pred.asnumpy()
            self.sum_metric += numpy.corrcoef(pred.ravel(), label.ravel())[0, 1]
            self.num_inst += 1


@register()
class Loss(EvalMetric):
    """Dummy metric for directly printing loss (reference metric.py:930)."""

    def __init__(self, name='loss', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += pred.asnumpy().sum()
            self.num_inst += pred.size


@register()
class Torch(Loss):
    """Dummy metric for torch criterions (reference metric.py:1002)."""

    def __init__(self, name='torch', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register()
class Caffe(Loss):
    """Dummy metric for caffe criterions (reference metric.py:1011)."""

    def __init__(self, name='caffe', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find('<') != -1:
                name = 'custom(%s)' % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = label.asnumpy()
            pred = pred.asnumpy()
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Adapter from a numpy feval to CustomMetric (reference metric.py:1100)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
