"""Global PRNG state — stateful seed API over JAX's stateless keys.

Reference: python/mxnet/random.py (mx.random.seed) + src/resource.cc:84
(per-device seedable mshadow PRNG pools). TPU-native: a process-global
counter-split key; every random op consumes one fresh subkey, passed to the
op as a trailing array argument so the op itself stays pure/jittable.
"""
import os as _os
import random as _pyrandom
import threading

import jax
import numpy as _np

__all__ = ['seed', 'next_key', 'get_state', 'set_state',
           'host_rng', 'host_pyrng',
           'uniform', 'normal', 'gamma', 'exponential', 'poisson',
           'negative_binomial', 'generalized_negative_binomial']

_lock = threading.Lock()
# lazy: creating a key initializes the jax backend, which must not happen
# at import time (slow/fragile through the TPU tunnel)
_key = None
# MXTPU_SEED: seed every framework stream at import, exactly as if the
# process's first statement were mx.random.seed(N) — lets unmodified
# scripts (which never call seed) run hermetically, e.g. in CI. The
# device key stream honors it too (next_key's lazy init uses PRNGKey(N)
# directly, with no extra host draw).
_env_seed = None
_env_raw = _os.environ.get('MXTPU_SEED', '').strip()
if _env_raw:
    try:
        _env_seed = int(_env_raw)
    except ValueError:
        import warnings as _warnings
        _warnings.warn('MXTPU_SEED=%r is not an integer; ignoring it'
                       % _env_raw)
# framework-private host-side stream for initializers / iterator shuffles.
# Private so mx.random.seed is hermetic WITHOUT clobbering the user's
# process-global numpy state (the reference's mx.random.seed doesn't
# touch numpy either).
_host_rng = _np.random.RandomState(
    _env_seed % (2 ** 32) if _env_seed is not None else None)
_host_pyrng = _pyrandom.Random(_env_seed)


def host_rng():
    """The framework's host-side numpy stream (initializers, shuffles)."""
    return _host_rng


def host_pyrng():
    """The framework's host-side stdlib stream (augmenter gates etc.)."""
    return _host_pyrng


def seed(seed_state):
    """Seed all framework RNG streams (reference random.py:30
    mx.random.seed): the device key stream AND the framework's host-side
    stream that initializers / iterator shuffles draw from — without
    the latter, suite ordering leaks into init and `seed` is not
    hermetic."""
    global _key
    with _lock:
        _key = jax.random.PRNGKey(int(seed_state))
        _host_rng.seed(int(seed_state) % (2 ** 32))
        _host_pyrng.seed(int(seed_state))


def get_state():
    """Snapshot every framework RNG stream for checkpointing
    (module/checkpointing.py): the device key (numpy uint32 array, or
    None while the stream is still lazily uninitialized), the host
    numpy stream and the host stdlib stream. The host states come back
    as JSON-serializable nested lists so they can ride a checkpoint's
    metadata record."""
    with _lock:
        key = None if _key is None else _np.asarray(_key).copy()
    st = _host_rng.get_state()
    np_state = [st[0], _np.asarray(st[1]).tolist(), int(st[2]),
                int(st[3]), float(st[4])]

    def _listify(obj):
        if isinstance(obj, tuple):
            return [_listify(x) for x in obj]
        return obj

    return {'key': key, 'numpy': np_state,
            'python': _listify(_host_pyrng.getstate())}


def set_state(state):
    """Restore a :func:`get_state` snapshot — the checkpoint-resume
    path: after this, the key/shuffle/augment streams continue exactly
    where the saved run left them."""
    global _key
    import jax.numpy as jnp

    def _tupleize(obj):
        if isinstance(obj, list):
            return tuple(_tupleize(x) for x in obj)
        return obj

    with _lock:
        key = state.get('key')
        _key = None if key is None else jnp.asarray(_np.asarray(key))
    np_state = state.get('numpy')
    if np_state is not None:
        _host_rng.set_state((np_state[0],
                             _np.asarray(np_state[1], _np.uint32),
                             int(np_state[2]), int(np_state[3]),
                             float(np_state[4])))
    py_state = state.get('python')
    if py_state is not None:
        _host_pyrng.setstate(_tupleize(py_state))


def next_key():
    """Split one subkey off the global stream."""
    global _key
    with _lock:
        if _key is None:
            # MXTPU_SEED path: PRNGKey(N) directly, exactly what
            # mx.random.seed(N) would have set — and no host draw, so
            # host-stream consumers stay aligned with the seed() path
            _key = jax.random.PRNGKey(
                _env_seed if _env_seed is not None
                else _host_rng.randint(0, 2**31 - 1))
        _key, sub = jax.random.split(_key)
        return sub


def _sampler(op_name):
    # reference random.py:25-31 re-exports the sampling ops at module
    # level (uniform/normal/... — in 0.11 these are the scalar-param
    # SampleUniformParam family); resolved lazily so importing
    # mx.random never forces the op registry/backend up
    def fn(*args, **kwargs):
        from . import ndarray as _nd
        return getattr(_nd, op_name)(*args, **kwargs)
    fn.__name__ = op_name
    fn.__doc__ = ('mx.random.%s — alias of nd.%s (reference '
                  'random.py:25-31)' % (op_name, op_name))
    return fn


uniform = _sampler('uniform')
normal = _sampler('normal')
gamma = _sampler('random_gamma')
exponential = _sampler('random_exponential')
poisson = _sampler('random_poisson')
negative_binomial = _sampler('random_negative_binomial')
generalized_negative_binomial = _sampler(
    'random_generalized_negative_binomial')
