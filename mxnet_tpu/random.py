"""Global PRNG state — stateful seed API over JAX's stateless keys.

Reference: python/mxnet/random.py (mx.random.seed) + src/resource.cc:84
(per-device seedable mshadow PRNG pools). TPU-native: a process-global
counter-split key; every random op consumes one fresh subkey, passed to the
op as a trailing array argument so the op itself stays pure/jittable.
"""
import threading

import jax
import numpy as _np

__all__ = ['seed', 'next_key']

_lock = threading.Lock()
# lazy: creating a key initializes the jax backend, which must not happen
# at import time (slow/fragile through the TPU tunnel)
_key = None


def seed(seed_state):
    """Seed all device RNG streams (reference random.py:30 mx.random.seed)."""
    global _key
    with _lock:
        _key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Split one subkey off the global stream."""
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.PRNGKey(_np.random.randint(0, 2**31 - 1))
        _key, sub = jax.random.split(_key)
        return sub
