"""Torch plugin — run pytorch modules/criteria inside mxnet_tpu graphs.

Reference: plugin/torch (torch_module-inl.h TorchModuleOp wraps a Lua
torch module built from ``lua_string``; torch_criterion-inl.h wraps a
criterion as a loss op). The modern analog wraps a **pytorch**
``nn.Module`` as a CustomOp: forward/backward run on host CPU through
torch autograd, the rest of the graph stays on TPU — the same
host-callback execution contract as the reference plugin
(ExecType::kLocal) and our CustomOp bridge.

Usage::

    bridge = TorchModule(torch.nn.Linear(4, 2))
    y = bridge(mx.nd.ones((3, 4)))            # imperative
    loss = TorchCriterion(torch.nn.MSELoss())
    l = loss(pred, target)

Both are differentiable under ``mx.autograd.record()`` — gradients
flow back into the mxnet_tpu graph (and into the torch parameters via
torch autograd, mirroring the reference's lua-held parameter update).
"""
import numpy as np

from ..ndarray.ndarray import array as nd_array
from ..operator import CustomOp, invoke_custom

try:
    import torch as _torch
except ImportError:  # pragma: no cover - torch is baked into this image
    _torch = None


def _require_torch():
    if _torch is None:
        raise ImportError('the torch plugin needs pytorch installed')


def _to_torch(x):
    """NDArray → torch tensor. Copies: jax buffers are read-only and
    torch assumes writable memory."""
    return _torch.from_numpy(np.array(x.asnumpy()))


class _TorchOp(CustomOp):
    """CustomOp running a pytorch callable on host CPU."""

    def __init__(self, fn, module=None, grad_input_mask=None):
        self._fn = fn
        self._module = module  # for train/eval mode switching
        self._mask = grad_input_mask  # None = grads for all inputs
        self._saved = None

    def forward(self, is_train, req, in_data, out_data, aux):
        from .. import autograd as _ag

        tins = [_to_torch(x) for x in in_data]
        if self._module is not None:
            self._module.train(bool(is_train))
        # build the torch graph iff the mxnet tape is recording (covers
        # record(train_mode=False) saliency-style gradients too); plain
        # inference takes the cheap no_grad path
        if _ag.is_recording():
            for i, t in enumerate(tins):
                if (self._mask is None or self._mask[i]) \
                        and t.is_floating_point():
                    t.requires_grad_(True)
            out = self._fn(*tins)
            self._saved = (tins, out)
        else:
            with _torch.no_grad():
                out = self._fn(*tins)
        self.assign(out_data[0], req[0], nd_array(out.detach().numpy()))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        tins, out = self._saved
        gout = _to_torch(out_grad[0]).reshape(out.shape)
        # .backward (not autograd.grad) so module parameters accumulate
        # their .grad too — the torch side stays trainable, like the
        # reference's lua-held parameters
        out.backward(gout)
        for i, t in enumerate(tins):
            g = t.grad
            z = np.zeros(in_data[i].shape, np.float32) if g is None \
                else g.numpy()
            self.assign(in_grad[i], req[i], nd_array(z))


class TorchModule:
    """Wrap a pytorch ``nn.Module`` (or a source string evaluating to
    one, mirroring the reference's ``lua_string``) as a differentiable
    mxnet_tpu operator."""

    def __init__(self, module):
        _require_torch()
        if isinstance(module, str):
            # the reference's lua_string contract: source evaluating to
            # a module, e.g. "nn.Linear(4, 2)"
            module = eval(module, {'torch': _torch, 'nn': _torch.nn})  # noqa: S307
        self.module = module.to('cpu')
        self._shape_cache = {}

    def _out_spec(self, inputs):
        """(shape, dtype) of the output for these input shapes,
        memoized. The one probe run per new shape happens in eval()
        mode so stateful modules (BatchNorm running stats) are not
        double-updated."""
        key = tuple(tuple(x.shape) for x in inputs)
        if key not in self._shape_cache:
            was_training = self.module.training
            self.module.eval()
            try:
                with _torch.no_grad():
                    probe = self.module(*[_to_torch(x) for x in inputs])
            finally:
                if was_training:
                    self.module.train()
            self._shape_cache[key] = (tuple(probe.shape),
                                      str(probe.numpy().dtype))
        return self._shape_cache[key]

    def __call__(self, *inputs):
        op = _TorchOp(lambda *t: self.module(*t), module=self.module)
        shape, dtype = self._out_spec(inputs)
        return invoke_custom(op, list(inputs), [shape],
                             out_dtypes=[dtype])

    def parameters(self):
        """Snapshot of the torch-held parameters as NDArrays (the torch
        side owns them, like the reference's lua-held params)."""
        return [nd_array(p.detach().numpy())
                for p in self.module.parameters()]

    def torch_parameters(self):
        return list(self.module.parameters())


class TorchCriterion(TorchModule):
    """Wrap a pytorch loss (criterion): ``crit(pred, target)`` →
    loss NDArray; grads flow to ``pred`` only (the reference
    TorchCriterionOp contract)."""

    def __call__(self, pred, target):
        op = _TorchOp(lambda p, t: self.module(p, t), module=self.module,
                      grad_input_mask=[True, False])
        shape, dtype = self._out_spec([pred, target])
        return invoke_custom(op, [pred, target], [shape],
                             out_dtypes=[dtype])
