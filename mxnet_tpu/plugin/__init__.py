"""Optional plugins (reference plugin/ — caffe/torch/warpctc bridges).

The reference compiles these in behind build flags; here each plugin is
an import-gated python module. Only bridges whose host library exists in
the environment load; everything degrades to an ImportError with a
clear message, never a crash at package import.
"""
from . import torch_bridge  # noqa: F401  (guards its own torch import)

__all__ = ['torch_bridge']
