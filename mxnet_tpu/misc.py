"""Legacy compatibility shims (reference python/mxnet/misc.py): the
pre-lr_scheduler learning-rate classes some old scripts import."""
from .lr_scheduler import FactorScheduler, LRScheduler

__all__ = ['FactorScheduler', 'LearningRateScheduler']

# the ancient name for the scheduler base class
LearningRateScheduler = LRScheduler
