"""Distributed KVStore worker tier.

Reference: src/kvstore/kvstore_dist.h:52 (KVStoreDist : KVStoreLocal).
Semantics preserved: `dist_sync` / `dist_device_sync` defer push acks on the
server until every worker has contributed (the synchronous-SGD barrier);
`dist_async` applies per push. Key sharding follows EncodeKey
(kvstore_dist.h:430-468): arrays smaller than MXTPU_KVSTORE_BIGARRAY_BOUND
(default 1 MB, reference MXNET_KVSTORE_BIGARRAY_BOUND) go whole to one
hashed server (key*9973 % n); larger arrays are striped evenly over *all*
servers so aggregate bandwidth scales with the server count.

Asynchrony: the reference makes ZPush/ZPull engine ops; here each server
connection gets a dedicated comm thread with a FIFO queue, so `push` returns
immediately and `pull` rides the same queue (per-server ordering ≙ the
engine's per-var ordering). `priority` is accepted for API compatibility.

Transient-fault tier: with ``MXTPU_KVSTORE_TIMEOUT`` set, each pull
shard reply is bounded and a socket error or expiry enters a
reconnect-and-retry path (``MXTPU_KVSTORE_RETRIES`` attempts,
exponential backoff) before surfacing as ConnectionError — the
retryable family the resilient-training drivers restart on. Push stays
fire-and-forget; a connection that dies with un-applied pushes in
flight is NOT silently retried past (the server is missing a
gradient): the next op raises ConnectionError so the restart drivers
restore from the last-good checkpoint instead.

Standalone mode: without the DMLC_* cluster env (no launcher), a scheduler
and one server are spun up as in-process threads so `mx.kv.create
('dist_sync')` works as a 1-worker cluster — handy for tests and parity with
the reference's single-machine `dist` fallback.

SECURITY — trusted clusters only: like the reference's ps-lite transport
(and its pickled server-side optimizer, python/mxnet/kvstore.py:349-393),
the wire protocol carries pickled python objects with no authentication or
encryption. Anyone who can connect to the scheduler/server ports can execute
arbitrary code in the job. Run only on private cluster networks; for
untrusted environments use the SPMD tier (jax.distributed + XLA collectives)
whose transport carries tensors, not code.
"""
import atexit
import os
import pickle
import threading
import time

import numpy as np

import jax

from . import telemetry as _tele
from .base import MXNetError
from .kvstore import KVStore, _key_value, _tele_bytes
from .ndarray import NDArray
from ._dist_proto import (send_msg, recv_msg, pack_array, unpack_array,
                          connect)

__all__ = ['KVStoreDist', 'LostPushError']


class LostPushError(ConnectionError):
    """A connection died with un-applied fire-and-forget push(es) in
    flight: the server is missing a gradient, so the retry tier must
    NOT silently reconnect past it. A dedicated subclass because
    socket-level ConnectionResetError/ConnectionRefusedError are ALSO
    ConnectionErrors and those are exactly the transients the retry
    path exists for — only this one must escape it."""

from .config import flags as _flags
_BIGARRAY_BOUND = _flags.get('MXTPU_KVSTORE_BIGARRAY_BOUND')


class _Future:
    def __init__(self):
        self._ev = threading.Event()
        self.value = None

    def set(self, value):
        self.value = value
        self._ev.set()

    def wait(self, timeout=None):
        """Reply, or raise. ``timeout`` (MXTPU_KVSTORE_TIMEOUT) bounds
        the wait: an expiry raises TimeoutError so the retry path can
        reconnect instead of hanging into the watchdog."""
        if not self._ev.wait(timeout):
            raise TimeoutError(
                'kvstore reply not received within %.1fs' % timeout)
        if isinstance(self.value, Exception):
            raise self.value
        return self.value


class _ServerConn:
    """One comm thread + socket per server; FIFO request/reply."""

    def __init__(self, addr):
        self.sock = connect(*addr)
        self._q = []
        self._err = None
        # a fire-and-forget push that died with the socket (send/recv
        # failed, or still queued when the conn was torn down) was
        # never applied by the server: the retry tier must NOT silently
        # reconnect past it — sync training would continue on weights
        # missing one worker's gradient
        self.lost_push = False
        self._closed = False
        self._cv = threading.Condition()
        self._th = threading.Thread(target=self._loop, daemon=True)
        self._th.start()

    def submit(self, msg):
        if self._closed:
            # the comm thread exited at the close sentinel: a message
            # queued now would never be processed and its future never
            # set — under an unbounded wait that is a silent hang, the
            # exact failure this tier exists to prevent. Fail fast so
            # the retry path reconnects (or surfaces the error).
            raise OSError('kvstore connection to this server is closed')
        if self._err is not None:
            raise RuntimeError('kvstore server error: %s' % self._err)
        fut = _Future()
        with self._cv:
            self._q.append((msg, fut))
            self._cv.notify()
        return fut

    def _loop(self):
        while True:
            with self._cv:
                while not self._q:
                    self._cv.wait()
                msg, fut = self._q.pop(0)
            if msg is None:
                return
            is_push = isinstance(msg, tuple) and msg \
                and str(msg[0]).startswith('push')
            try:
                send_msg(self.sock, msg)
                reply = recv_msg(self.sock)
                # fire-and-forget pushes never await their future; a
                # server-side failure must still surface on the next op
                if (isinstance(reply, tuple) and reply
                        and reply[0] == 'error'):
                    self._err = reply[1]
                    if is_push:
                        # the server REFUSED this gradient: as lost as
                        # a dead socket — the reconnect gate must not
                        # silently retry past it either
                        self.lost_push = True
                fut.set(reply)
            except OSError as e:
                if is_push:
                    self.lost_push = True
                fut.set(e)

    def close(self):
        with self._cv:
            # anything still queued will never be sent: queued pushes
            # count as lost for the reconnect-retry gate
            if any(isinstance(m, tuple) and m
                   and str(m[0]).startswith('push')
                   for m, _ in self._q):
                self.lost_push = True
            self._closed = True
            self._q.append((None, _Future()))
            self._cv.notify()
        try:
            self.sock.close()
        except OSError:
            pass


class KVStoreDist(KVStore):
    """Reference kvstore_dist.h:52 — worker side of the parameter server."""

    def __init__(self, kv_type='dist_sync'):
        super().__init__(kv_type)
        self._standalone = None
        if 'DMLC_PS_ROOT_URI' in os.environ:
            root = (os.environ['DMLC_PS_ROOT_URI'],
                    os.environ['DMLC_PS_ROOT_PORT'])
            self._num_workers = int(os.environ.get('DMLC_NUM_WORKER', 1))
            self._num_servers = int(os.environ.get('DMLC_NUM_SERVER', 1))
        else:
            root = self._start_standalone()
            self._num_workers = self._num_servers = 1
        host = os.environ.get('DMLC_NODE_HOST', '127.0.0.1')
        self._sched = connect(*root)
        self._sched_lock = threading.Lock()
        send_msg(self._sched, ('register', 'worker', (host, 0)))
        topo = recv_msg(self._sched)
        assert topo and topo[0] == 'topology', topo
        self._rank = topo[1]
        self._server_addrs = list(topo[2])   # kept for reconnect-retry
        self._conns = [_ServerConn(a) for a in topo[2]]
        self._sync = '_async' not in kv_type
        self._key_meta = {}  # key -> (shape, dtype)
        # MXTPU_GRAD_COMPRESS wire state: per-shard-key error-feedback
        # residual (this worker's accumulated quantization error, numpy
        # host-side) and per-key (compressed, uncompressed) byte counts
        # feeding the MEASURED comm.* gauges — these are real bytes
        # crossing the TCP wire, unlike the SPMD window's modeled twin
        self._push_ef = {}
        self._wire_stats = {}
        self._aux = None     # heartbeat / dead-node channel
        self._aux_lock = threading.Lock()
        self._start_heartbeat(root, 'worker')
        if self._rank == 0:
            self._command_all('set_sync_mode', self._sync)
        self.barrier()
        atexit.register(self._finalize)

    # -- failure detection (kvstore.h:321-330) ----------------------------
    def _start_heartbeat(self, root, role, interval=2.0):
        try:
            self._aux = connect(*root)
            send_msg(self._aux, ('aux', role, self._rank))
        except OSError:
            self._aux = None
            return

        def beat():
            while True:
                time.sleep(interval)
                try:
                    with self._aux_lock:
                        send_msg(self._aux, ('heartbeat',))
                except OSError:
                    return

        threading.Thread(target=beat, daemon=True).start()

    def num_dead_node(self, node_id=6, timeout=60):
        """Number of dead nodes in the masked groups (1=scheduler,
        2=servers, 4=workers — reference kvstore.h get_num_dead_node)."""
        if self._aux is None:
            return 0
        with self._aux_lock:
            send_msg(self._aux, ('num_dead', int(node_id), float(timeout)))
            reply = recv_msg(self._aux)
        assert reply and reply[0] == 'num_dead', reply
        return int(reply[1])

    def _start_standalone(self):
        """In-process 1-worker cluster (no launcher present)."""
        from .kvstore_server import Scheduler, KVStoreServer
        sched = Scheduler(1, 1)
        addr = ('127.0.0.1', sched.port)
        threading.Thread(target=sched.run, daemon=True).start()
        server = KVStoreServer()
        server.num_workers = 1
        threading.Thread(target=server.run, args=(addr,),
                         daemon=True).start()
        self._standalone = (sched, server)
        return addr

    # -- topology --------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def barrier(self):
        """Global worker barrier via the scheduler (ps::Postoffice)."""
        with self._sched_lock:
            send_msg(self._sched, ('barrier', 'worker'))
            reply = recv_msg(self._sched)
        assert reply and reply[0] == 'barrier_done', reply

    def _finalize(self):
        try:
            with self._sched_lock:
                send_msg(self._sched, ('finalize',))
        except OSError:
            pass
        for c in self._conns:
            c.close()

    # -- transient-error retry (timeout + reconnect + backoff) -----------
    def _retry_cfg(self):
        """(timeout_or_None, retries) from MXTPU_KVSTORE_TIMEOUT /
        MXTPU_KVSTORE_RETRIES, read ONCE per store (the hot pull loop
        calls this per key — no env parsing there, matching the
        decide-once contract every other flag gate keeps). timeout 0 =
        unbounded (the pre-retry behavior: a dead server hangs into
        the watchdog instead)."""
        cfg = getattr(self, '_retry_cfg_cached', None)
        if cfg is None:
            _flags.reload('MXTPU_KVSTORE_TIMEOUT')
            _flags.reload('MXTPU_KVSTORE_RETRIES')
            t = float(_flags.get('MXTPU_KVSTORE_TIMEOUT'))
            cfg = self._retry_cfg_cached = (
                (t if t > 0 else None),
                int(_flags.get('MXTPU_KVSTORE_RETRIES')))
        return cfg

    def _reconnect(self, sid):
        old = self._conns[sid]
        try:
            old.close()
        except Exception:  # noqa: BLE001 — the socket is already dead
            pass
        # the comm thread may be mid-failure on an in-flight push
        # (blocked in recv when the socket died): close() above unblocks
        # it, but its lost_push store lands ASYNCHRONOUSLY — join before
        # reading the flag, or the race silently retries past a lost
        # gradient. The thread exits via the close sentinel right after.
        old._th.join(timeout=10)
        # the fresh connection is installed EITHER way: the lost-push
        # gate below fires once for the event, and the in-process
        # restore-and-retry it triggers (resilient_fit restores from
        # checkpoint and re-enters fit with the SAME store) must find a
        # clean slot — a raise over the dead conn would poison every
        # retry into the same error until the budget burned
        self._conns[sid] = _ServerConn(self._server_addrs[sid])
        if old.lost_push:
            # a gradient push died with this connection and was never
            # applied: silently retrying the PULL would hand back
            # weights missing one worker's contribution. Surface it as
            # the retryable family instead — resilient_fit/the
            # supervisor restore from the last-good checkpoint, which
            # is the only state known to include every push
            raise LostPushError(
                'kvstore server %d connection died with un-applied '
                'push(es) in flight — state on the server may be '
                'stale; restore from checkpoint instead of retrying'
                % sid)

    def _request(self, sid, msg):
        """Submit ``msg`` to server ``sid`` and wait for the reply,
        retrying transient connection errors (socket error, bounded-
        timeout expiry) with an exponential-backoff reconnect. NOT
        transient: a server-side 'error' reply to a push marks the
        gradient lost (the reconnect gate raises
        :class:`LostPushError`), an 'error' reply to THIS request comes
        back as the reply tuple for the caller's assert to surface.
        Past the retry budget the failure surfaces as ConnectionError —
        the retryable family resilient_fit / the supervisor act on."""
        timeout, retries = self._retry_cfg()
        delay = 0.05
        last = None
        for attempt in range(retries + 1):
            try:
                if self._conns[sid]._err is not None:
                    # poisoned by an earlier failure: a fresh socket or
                    # nothing — submit() on it only re-raises the past
                    self._reconnect(sid)
                return self._conns[sid].submit(msg).wait(timeout)
            except LostPushError:
                raise           # never burned as a transient retry
            except (OSError, TimeoutError) as e:
                last = e
                if attempt >= retries:
                    break
                import logging
                logging.warning(
                    'kvstore: server %d request failed (%s: %s) — '
                    'reconnecting and retrying in %.2fs (%d/%d)',
                    sid, type(e).__name__, e, delay, attempt + 1, retries)
                time.sleep(delay)
                delay = min(2.0, delay * 2.0)
                try:
                    self._reconnect(sid)
                except LostPushError:
                    raise
                except OSError as re_err:
                    last = re_err   # server still down: burn the retry
        raise ConnectionError(
            'kvstore server %d unreachable after %d attempt(s): %s'
            % (sid, retries + 1, last)) from last

    # -- key sharding (EncodeKey, kvstore_dist.h:430-468) ----------------
    def _shards(self, key, shape, dtype):
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        n = len(self._conns)
        size = int(np.prod(shape))
        if nbytes < _BIGARRAY_BOUND or n == 1 or size < n:
            sid = (_hash_key(key) * 9973) % n
            return [(sid, str(key), slice(0, size))]
        out = []
        chunk = (size + n - 1) // n
        for s in range(n):
            lo, hi = s * chunk, min(size, (s + 1) * chunk)
            if lo >= hi:
                break
            out.append((s, '%s#%d' % (key, s), slice(lo, hi)))
        return out

    # -- init/push/pull --------------------------------------------------
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            vv = v[0] if isinstance(v, (list, tuple)) else v
            arr = vv.asnumpy() if isinstance(vv, NDArray) else np.asarray(vv)
            self._key_meta[k] = (arr.shape, arr.dtype)
            if self._rank == 0:
                flat = arr.reshape(-1)
                futs = [self._conns[sid].submit(
                            ('init', skey, pack_array(flat[sl])))
                        for sid, skey, sl in self._shards(
                            k, arr.shape, arr.dtype)]
                for f in futs:
                    f.wait()
        self.barrier()

    def push(self, key, value, priority=0):
        from .ndarray.sparse import RowSparseNDArray
        with _tele.span('kvstore.push', 'kvstore'):
            keys, values = _key_value(key, value)
            nbytes, t0 = 0, 0.0
            if _tele.enabled():
                nbytes = _tele_bytes('kvstore.push_bytes', values)
                t0 = time.time()
            for k, vlist in zip(keys, values):
                if not isinstance(vlist, (list, tuple)):
                    vlist = [vlist]
                if isinstance(vlist[0], RowSparseNDArray):
                    self._push_row_sparse(k, vlist)
                    continue
                merged = self._reduce(vlist).asnumpy()
                if k not in self._key_meta:
                    self._key_meta[k] = (merged.shape, merged.dtype)
                flat = merged.reshape(-1)
                cmode = self._compress_mode() \
                    if merged.dtype.kind == 'f' else 'off'
                comp = unc = 0
                for sid, skey, sl in self._shards(k, merged.shape,
                                                  merged.dtype):
                    seg = flat[sl]
                    if cmode != 'off':
                        msg = self._encode_push(skey, seg, cmode)
                        self._conns[sid].submit(('push_c', skey, msg))
                        from .parallel import compression
                        comp += compression.wire_message_bytes(msg)
                    else:
                        self._conns[sid].submit(
                            ('push', skey, pack_array(seg)))
                        comp += seg.nbytes
                    unc += seg.nbytes
                self._wire_stats[k] = (comp, unc)
            self._publish_wire_gauges()
            if nbytes:
                # host-observed push rate (reduce + serialize + submit;
                # the server ack is async). /metrics labels it with
                # this process's host id, so a slow DCN link shows up
                # attributed to its machine
                dt = time.time() - t0
                if dt > 0:
                    _tele.gauge('kvstore.push_mb_s').set(
                        round(nbytes / 2.0**20 / dt, 2))
            _tele.watchdog.note_progress('kvstore.push')

    # -- compressed wire format (MXTPU_GRAD_COMPRESS) ----------------------
    @staticmethod
    def _compress_mode():
        from .parallel import compression
        return compression.resolved_mode()

    def _encode_push(self, skey, seg, cmode):
        """Error-feedback encode of one shard segment: this worker's
        residual for the key re-enters the carry before quantization,
        and what the quantizer drops becomes the next residual —
        host-side numpy, mirroring the in-window EF math."""
        from .parallel import compression
        carry = seg.astype(np.float32, copy=True)
        resid = self._push_ef.get(skey)
        if resid is not None and resid.shape == carry.shape:
            carry += resid
        msg = compression.encode_wire(carry, cmode)
        nr = carry - compression.decode_wire(msg).astype(np.float32)
        self._push_ef[skey] = np.where(np.isfinite(nr), nr, 0.0)
        return msg

    def _publish_wire_gauges(self):
        """MEASURED comm.* gauges: actual payload bytes submitted to
        the server sockets this push round, summed over keys — the
        kvstore path counts real wire traffic where the SPMD window
        can only model it (comm.bytes_src says which one you read)."""
        if not _tele.enabled() or not self._wire_stats:
            return
        comp = sum(c for c, _ in self._wire_stats.values())
        unc = sum(u for _, u in self._wire_stats.values())
        _tele.gauge('comm.bytes_on_wire_per_step').set(int(comp))
        _tele.gauge('comm.compression_ratio').set(
            round(unc / max(comp, 1), 3))
        _tele.gauge('comm.mode').set(self._compress_mode())
        _tele.gauge('comm.bytes_src').set('measured')

    def _push_row_sparse(self, k, vlist):
        """Row-sparse grads go whole to the key's home server (the
        reference stripes per-row key ranges; one home server preserves
        the API semantics — see module docstring)."""
        idx, vals = _merge_row_sparse(vlist)
        if k in self._key_meta:
            shape, dtype = self._key_meta[k]
            if len(self._shards(k, shape, dtype)) > 1:
                raise MXNetError(
                    'row_sparse key %r exceeds the big-array bound and was '
                    'striped at init; raise MXTPU_KVSTORE_BIGARRAY_BOUND '
                    'for sparse keys' % (k,))
        sid = (_hash_key(k) * 9973) % len(self._conns)
        self._conns[sid].submit(
            ('push_rsp', str(k), pack_array(idx), pack_array(vals)))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        assert out is not None
        with _tele.span('kvstore.pull', 'kvstore'):
            keys, outs = _key_value(key, out)
            nbytes, t0 = 0, 0.0
            if _tele.enabled():
                nbytes = _tele_bytes('kvstore.pull_bytes', outs)
                t0 = time.time()
            for k, olist in zip(keys, outs):
                if not isinstance(olist, (list, tuple)):
                    olist = [olist]
                shape, dtype = self._key_meta.get(
                    k, (olist[0].shape, olist[0].dtype))
                shards = self._shards(k, shape, dtype)
                timeout, _ = self._retry_cfg()
                # bf16 mode compresses the pull wire too (a half-width
                # value cast is loss-bounded for weights); int8 pulls
                # stay full-precision — the blockwise-EF recipe is a
                # GRADIENT transform, weights get no residual stream
                pkind = 'pull'
                if np.dtype(dtype).kind == 'f' \
                        and self._compress_mode() == 'bf16':
                    pkind = 'pull_c'
                # first attempt stays parallel across servers; a shard
                # whose reply errors or times out drops into the
                # serial reconnect-retry path (_request)
                futs = []
                for sid, skey, sl in shards:
                    try:
                        fut = self._conns[sid].submit((pkind, skey))
                    except (RuntimeError, OSError):
                        fut = None   # conn poisoned/closed: retry path
                    futs.append((sid, skey, sl, fut))
                flat = np.empty(int(np.prod(shape)), dtype)
                for sid, skey, sl, f in futs:
                    try:
                        if f is None:
                            raise OSError('connection already failed')
                        reply = f.wait(timeout)
                    except (OSError, TimeoutError):
                        reply = self._request(sid, (pkind, skey))
                    if pkind == 'pull_c':
                        assert reply and reply[0] == 'arr_c', reply
                        from .parallel import compression
                        flat[sl] = compression.decode_wire(
                            reply[1]).reshape(-1)
                    else:
                        assert reply and reply[0] == 'arr', reply
                        flat[sl] = unpack_array(reply[1]).reshape(-1)
                arr = flat.reshape(shape)
                for o in olist:
                    o._data = jax.device_put(
                        arr.astype(o.dtype), o.context.jax_device())
            if nbytes:
                # pull waits for every shard, so this is real end-to-end
                # server->host throughput for this host
                dt = time.time() - t0
                if dt > 0:
                    _tele.gauge('kvstore.pull_mb_s').set(
                        round(nbytes / 2.0**20 / dt, 2))
            _tele.watchdog.note_progress('kvstore.pull')

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        from .ndarray.sparse import RowSparseNDArray, row_sparse_array
        assert out is not None and row_ids is not None
        keys, outs = _key_value(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, olist, rids in zip(
                keys, outs,
                row_ids if isinstance(row_ids, list) else [row_ids]):
            if not isinstance(olist, (list, tuple)):
                olist = [olist]
            rows = np.unique(rids.asnumpy().astype(np.int64))
            sid = (_hash_key(k) * 9973) % len(self._conns)
            row_shape = tuple(self._key_meta[k][0][1:])
            reply = self._conns[sid].submit(
                ('pull_rsp', str(k), pack_array(rows), row_shape)).wait()
            assert reply and reply[0] == 'arr', reply
            vals = unpack_array(reply[1])
            shape, _ = self._key_meta[k]
            res = row_sparse_array((vals, rows), shape=shape)
            for o in olist:
                if isinstance(o, RowSparseNDArray):
                    o.data, o.indices = res.data, res.indices
                else:
                    res.copyto(o)

    # -- server commands (reference kvstore.py:349-393) ------------------
    def set_optimizer(self, optimizer):
        """Ship the pickled optimizer to the servers; updates then run
        server-side (update_on_kvstore)."""
        if self._rank == 0:
            self._command_all('set_optimizer', pickle.dumps(optimizer))
        self.barrier()
        self._optimizer = optimizer
        self._updater = None

    def _send_command_to_servers(self, head, body):
        self._command_all(head, body)

    def _command_all(self, head, body):
        futs = [c.submit(('cmd', head, body)) for c in self._conns]
        for f in futs:
            f.wait()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise RuntimeError('Cannot save states for distributed training '
                           '(they live on the servers)')

    def load_optimizer_states(self, fname):
        raise RuntimeError('Cannot load states for distributed training')


def _hash_key(key):
    try:
        return int(key)
    except (TypeError, ValueError):
        return abs(hash(str(key)))


def _merge_row_sparse(vlist):
    """Sum a list of row-sparse shards into one (indices, values) pair."""
    all_idx = np.concatenate([v.indices.asnumpy().astype(np.int64)
                              for v in vlist])
    uniq = np.unique(all_idx)
    pos = {r: i for i, r in enumerate(uniq)}
    width = vlist[0].data.shape[1:]
    vals = np.zeros((len(uniq),) + tuple(width),
                    vlist[0].data.asnumpy().dtype)
    for v in vlist:
        vi = v.indices.asnumpy().astype(np.int64)
        vd = v.data.asnumpy()
        for j, r in enumerate(vi):
            vals[pos[r]] += vd[j]
    return uniq, vals
