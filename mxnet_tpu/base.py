"""Shared plumbing: dtype tables, error type, attr normalization.

Reference: python/mxnet/base.py (check_call/handles/string_types) — here there
is no C-API ctypes boundary for the compute path (XLA is the backend), so this
module only keeps the shared tables and helpers.
"""
import numpy as np

__all__ = ['MXNetError', 'string_types', 'numeric_types']

string_types = (str,)
numeric_types = (float, int, np.generic)


class MXNetError(Exception):
    """Error raised by the framework (reference: base.py MXNetError)."""


# dtype <-> string tables (reference: ndarray/ndarray.py _DTYPE_NP_TO_MX/_DTYPE_MX_TO_NP)
_DTYPE_STR = {
    np.dtype('float32'): 'float32',
    np.dtype('float64'): 'float64',
    np.dtype('float16'): 'float16',
    np.dtype('uint8'): 'uint8',
    np.dtype('int8'): 'int8',
    np.dtype('int32'): 'int32',
    np.dtype('int64'): 'int64',
    np.dtype('bool'): 'bool',
}


def np_dtype(dtype):
    """Normalize any dtype spec (str, np.dtype, jnp dtype, type).
    'bfloat16' maps to the jnp scalar class (the convention jnp.zeros
    etc. accept); everything else goes through np.dtype, which also
    resolves the bf16 scalar class itself via ml_dtypes — so the
    function is idempotent. NOTE: str() of the bf16 CLASS is not a
    parseable dtype name; pass dtype objects around, not str(dtype)."""
    if dtype is None:
        return np.dtype('float32')
    if isinstance(dtype, str) and dtype == 'bfloat16':
        import jax.numpy as jnp
        return jnp.bfloat16
    d = np.dtype(dtype)
    if d == np.float16 and _f16_as_bf16():
        import jax.numpy as jnp
        return jnp.bfloat16
    return d


def _f16_as_bf16():
    """MXTPU_F16_AS_BF16: requests for float16 resolve to bfloat16 —
    the TPU's native half type (the MXU has no fp16 datapath; XLA
    emulates f16 through f32). Off by default so CPU-mesh tests keep
    reference fp16 numerics; the TPU benchmark artifacts enable it so
    reference --dtype float16 recipes run at the hardware's rate."""
    from .config import flags
    return flags.get('MXTPU_F16_AS_BF16')


def dtype_str(dtype):
    d = np.dtype(dtype) if not isinstance(dtype, str) else dtype
    return str(d) if not isinstance(d, str) else d


def normalize_attrs(attrs):
    """Make an attr dict hashable & canonical (lists/shapes -> tuples)."""
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (list, tuple)):
            out[k] = tuple(normalize_value(x) for x in v)
        else:
            out[k] = normalize_value(v)
    return out


def normalize_value(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (list, tuple)):
        return tuple(normalize_value(x) for x in v)
    return v


def attr_key(attrs):
    return tuple(sorted(attrs.items(), key=lambda kv: kv[0]))


# ---------------------------------------------------------------------------
# ctypes-era helpers kept for source compatibility (reference base.py:
# check_call, c_array, ctypes2buffer, ctypes2numpy_shared, c_str,
# build_param_doc, add_fileline_to_docstring, MXCallbackList and the
# Symbol/Sparse capability exceptions). Third-party reference code
# imports these from mxnet.base; they operate on the real C ABI types
# when the native library is loaded.
# ---------------------------------------------------------------------------

class NotImplementedForSymbol(MXNetError):
    """Reference base.py: op available for NDArray but not Symbol."""

    def __init__(self, function, alias, *args):
        super().__init__()
        self.function = function.__name__
        self.alias = alias
        self.args = [str(type(a)) for a in args]

    def __str__(self):
        msg = 'Function %s' % self.function
        if self.alias:
            msg += ' (namely operator "%s")' % self.alias
        if self.args:
            msg += ' with arguments (%s)' % ', '.join(self.args)
        return msg + ' is not supported for Symbol and only available ' \
                     'in NDArray.'


class NotSupportedForSparseNDArray(MXNetError):
    """Reference base.py: op not available for sparse storage types."""

    def __init__(self, function, alias, *args):
        super().__init__()
        self.function = function.__name__
        self.alias = alias
        self.args = [str(type(a)) for a in args]

    def __str__(self):
        msg = 'Function %s' % self.function
        if self.alias:
            msg += ' (namely operator "%s")' % self.alias
        if self.args:
            msg += ' with arguments (%s)' % ', '.join(self.args)
        return msg + ' is not supported for SparseNDArray and only ' \
                     'available in NDArray.'


import ctypes as _ctypes  # noqa: E402  (compat helpers below)


class MXCallbackList(_ctypes.Structure):
    """Reference base.py: the C callback-list struct (num_callbacks,
    callbacks, contexts) used by the custom-op/custom-function
    protocols; layout matches include/mxnet_tpu/c_api.h."""
    _fields_ = [('num_callbacks', _ctypes.c_int),
                ('callbacks', _ctypes.POINTER(_ctypes.CFUNCTYPE(_ctypes.c_int))),
                ('contexts', _ctypes.POINTER(_ctypes.c_void_p))]


def check_call(ret):
    """Reference base.py:108: raise MXNetError on a nonzero C return.
    With the native library loaded, MXTGetLastError (the engine's
    last-error slot, src/engine.cc) carries the detail."""
    if ret != 0:
        msg = None
        try:
            from ._native import get_lib
            lib = get_lib()
            if lib is not None:
                msg = lib.MXTGetLastError().decode('utf-8') or None
        except Exception:
            msg = None
        raise MXNetError(msg or 'C API call failed with status %d' % ret)


def c_str(string):
    """Create a ctypes char* from a python string."""
    return _ctypes.c_char_p(string.encode('utf-8'))


def c_array(ctype, values):
    """Create a ctypes array from a python list (reference base.py:135)."""
    return (ctype * len(values))(*values)


def ctypes2buffer(cptr, length):
    """Convert a ctypes pointer to a python bytearray."""
    if not isinstance(cptr, _ctypes.POINTER(_ctypes.c_char)):
        raise TypeError('expected char pointer')
    res = bytearray(length)
    rptr = (_ctypes.c_char * length).from_buffer(res)
    if not _ctypes.memmove(rptr, cptr, length):
        raise RuntimeError('memmove failed')
    return res


def ctypes2numpy_shared(cptr, shape):
    """Wrap a ctypes float pointer as a shared-memory numpy array."""
    import numpy as _np
    if not isinstance(cptr, _ctypes.POINTER(_ctypes.c_float)):
        raise RuntimeError('expected float pointer')
    size = 1
    for s in shape:
        size *= s
    dbuffer = (_ctypes.c_float * size).from_address(
        _ctypes.addressof(cptr.contents))
    return _np.frombuffer(dbuffer, dtype=_np.float32).reshape(shape)


def build_param_doc(arg_names, arg_types, arg_descs, remove_dup=True):
    """Build an operator parameter docstring block (reference
    base.py:186)."""
    param_keys = set()
    param_str = []
    for key, type_info, desc in zip(arg_names, arg_types, arg_descs):
        if key in param_keys and remove_dup:
            continue
        if key == 'num_args':
            continue
        param_keys.add(key)
        ret = '%s : %s' % (key, type_info)
        if len(desc) != 0:
            ret += '\n    ' + desc
        param_str.append(ret)
    return 'Parameters\n----------\n%s\n' % str.join('\n', param_str)


def add_fileline_to_docstring(module, incursive=True):
    """Append the definition position to every function docstring in a
    module (reference base.py:214) — a doc-tooling hook."""
    import inspect
    import sys as _sys

    def _add(obj):
        try:
            fname = inspect.getsourcefile(obj)
            line = inspect.getsourcelines(obj)[-1]
        except Exception:
            return
        if obj.__doc__ and 'From:' not in obj.__doc__:
            obj.__doc__ += '\n\nFrom:%s:%d' % (fname, line)

    if isinstance(module, str):
        module = _sys.modules[module]
    for _, obj in module.__dict__.items():
        if inspect.isfunction(obj):
            _add(obj)
        elif inspect.isclass(obj) and incursive:
            for _, meth in obj.__dict__.items():
                if inspect.isfunction(meth):
                    _add(meth)
