"""Shared plumbing: dtype tables, error type, attr normalization.

Reference: python/mxnet/base.py (check_call/handles/string_types) — here there
is no C-API ctypes boundary for the compute path (XLA is the backend), so this
module only keeps the shared tables and helpers.
"""
import numpy as np

__all__ = ['MXNetError', 'string_types', 'numeric_types']

string_types = (str,)
numeric_types = (float, int, np.generic)


class MXNetError(Exception):
    """Error raised by the framework (reference: base.py MXNetError)."""


# dtype <-> string tables (reference: ndarray/ndarray.py _DTYPE_NP_TO_MX/_DTYPE_MX_TO_NP)
_DTYPE_STR = {
    np.dtype('float32'): 'float32',
    np.dtype('float64'): 'float64',
    np.dtype('float16'): 'float16',
    np.dtype('uint8'): 'uint8',
    np.dtype('int8'): 'int8',
    np.dtype('int32'): 'int32',
    np.dtype('int64'): 'int64',
    np.dtype('bool'): 'bool',
}


def np_dtype(dtype):
    """Normalize any dtype spec (str, np.dtype, jnp dtype, type).
    'bfloat16' maps to the jnp scalar class (the convention jnp.zeros
    etc. accept); everything else goes through np.dtype, which also
    resolves the bf16 scalar class itself via ml_dtypes — so the
    function is idempotent. NOTE: str() of the bf16 CLASS is not a
    parseable dtype name; pass dtype objects around, not str(dtype)."""
    if dtype is None:
        return np.dtype('float32')
    if isinstance(dtype, str) and dtype == 'bfloat16':
        import jax.numpy as jnp
        return jnp.bfloat16
    return np.dtype(dtype)


def dtype_str(dtype):
    d = np.dtype(dtype) if not isinstance(dtype, str) else dtype
    return str(d) if not isinstance(d, str) else d


def normalize_attrs(attrs):
    """Make an attr dict hashable & canonical (lists/shapes -> tuples)."""
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (list, tuple)):
            out[k] = tuple(normalize_value(x) for x in v)
        else:
            out[k] = normalize_value(v)
    return out


def normalize_value(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (list, tuple)):
        return tuple(normalize_value(x) for x in v)
    return v


def attr_key(attrs):
    return tuple(sorted(attrs.items(), key=lambda kv: kv[0]))
