"""Runtime kernel compilation — the TPU answer to mx.rtc.

Reference: python/mxnet/rtc.py (Rtc — user writes a CUDA kernel body in
a python string, NVRTC compiles it at runtime, the kernel runs on
NDArrays) over src/common/mxrtc.cc.

On TPU the runtime-compilation engine is XLA itself, and the
user-facing kernel language is Pallas. :class:`Rtc` keeps the
reference's shape — (name, inputs, outputs, kernel-source) in,
callable-on-NDArrays out — but the source is a python/Pallas kernel
body instead of CUDA C. Two source forms are accepted:

- a *jnp expression body*: python statements that read the input names
  and assign each output name, traced and jit-compiled by XLA
  (replaces the common "elementwise CUDA one-liner" use of mx.rtc);
- a *pallas kernel*: a ``def kernel(in_ref, ..., out_ref, ...)`` body
  using ``pl.load/pl.store``-style Ref ops, lowered by pallas_call
  (interpret mode off-TPU).

Security note: like the reference, this executes user-supplied source
in-process. It is a developer tool, not an untrusted-input boundary.
"""
import textwrap

import jax
import jax.numpy as jnp

from .ndarray.ndarray import NDArray

__all__ = ['Rtc']


class Rtc:
    """Compile a kernel from source at runtime and run it on NDArrays.

    Mirrors reference rtc.py:24 — ``name``/``inputs``/``outputs`` have
    the same meaning; ``kernel`` is python (jnp or pallas) source.
    """

    def __init__(self, name, inputs, outputs, kernel, mode='jnp'):
        if mode not in ('jnp', 'pallas'):
            raise ValueError("mode must be 'jnp' or 'pallas'")
        self.name = name
        self._in_names = [i[0] for i in inputs]
        self._out_names = [o[0] for o in outputs]
        self._out_shapes = [tuple(o[1].shape) for o in outputs]
        self._out_dtypes = [o[1].dtype for o in outputs]
        self._mode = mode
        self._source = kernel
        self._fn = self._compile(kernel)

    def _compile(self, kernel):
        src = textwrap.dedent(kernel)
        if self._mode == 'jnp':
            # wrap the body into a function of the declared inputs that
            # returns the declared outputs (the XLA analog of NVRTC
            # decorating the CUDA body with the kernel signature)
            body = textwrap.indent(src, '    ')
            fn_src = 'def %s(%s):\n%s\n    return (%s,)' % (
                self.name, ', '.join(self._in_names), body,
                ', '.join(self._out_names))
            env = {'jnp': jnp, 'jax': jax}
            exec(compile(fn_src, '<rtc:%s>' % self.name, 'exec'), env)
            return jax.jit(env[self.name])
        # pallas mode: source must define `def kernel(*refs)` over
        # input refs then output refs
        from jax.experimental import pallas as pl
        env = {'jnp': jnp, 'jax': jax, 'pl': pl}
        exec(compile(src, '<rtc:%s>' % self.name, 'exec'), env)
        if 'kernel' not in env:
            raise ValueError("pallas-mode source must define "
                             "'def kernel(...)'")
        kern = env['kernel']
        out_spec = [jax.ShapeDtypeStruct(s, d)
                    for s, d in zip(self._out_shapes, self._out_dtypes)]
        interpret = jax.default_backend() != 'tpu'

        def run(*arrays):
            outs = pl.pallas_call(kern, out_shape=out_spec,
                                  interpret=interpret)(*arrays)
            return outs if isinstance(outs, (tuple, list)) else (outs,)
        return jax.jit(run)

    def push(self, inputs, outputs, grid_dims=None, block_dims=None):
        """Run the kernel (reference rtc.py push; grid/block dims are
        accepted for API compatibility — XLA/pallas choose the real
        launch geometry)."""
        if len(inputs) != len(self._in_names):
            raise ValueError('expected %d inputs' % len(self._in_names))
        if len(outputs) != len(self._out_names):
            raise ValueError('expected %d outputs' % len(self._out_names))
        arrays = [x._data if isinstance(x, NDArray) else jnp.asarray(x)
                  for x in inputs]
        res = self._fn(*arrays)
        for out, r in zip(outputs, res):
            out._data = r.astype(out._data.dtype).reshape(out.shape)
        return outputs
