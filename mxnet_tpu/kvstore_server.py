"""KVStore server + scheduler roles for distributed training.

Reference: src/kvstore/kvstore_dist_server.h:109 (KVStoreDistServer —
sync-barrier merge in MergeBuf/ApplyUpdates:144-209, server-side optimizer
re-instantiated from a pickled command, python/mxnet/kvstore_server.py:28-75)
and ps-lite's Postoffice scheduler (rank assignment, barriers).

The server applies updates with numpy-backed NDArrays on the host — gradient
aggregation across *machines* is bandwidth-bound host work in the reference
too (pinned-CPU merge); the TPU stays dedicated to the worker's compute.

Roles are selected by DMLC_ROLE at import time of mxnet_tpu (reference
kvstore_server.py:75 _init_kvstore_server_module): 'server' and 'scheduler'
processes block in their loop and exit with the job.
"""
import os
import pickle
import socket
import sys
import threading
import time

import numpy as np


def _dbg(msg):
    if os.environ.get('MXTPU_KVSTORE_DEBUG'):
        print('[kvserver pid=%d] %s' % (os.getpid(), msg),
              file=sys.stderr, flush=True)

from ._dist_proto import (send_msg, recv_msg, pack_array, unpack_array,
                          connect, listener)

__all__ = ['KVStoreServer', 'Scheduler', 'run_scheduler', 'run_server',
           'init_server_module_if_needed']


class Scheduler:
    """Rendezvous + barrier service (ps-lite Postoffice role).

    Protocol: every node connects and sends ('register', role); once
    DMLC_NUM_WORKER workers and DMLC_NUM_SERVER servers are in, each gets
    ('topology', rank, [server addresses]). The connection then serves
    ('barrier', group) requests — replies ('barrier_done',) to all members
    once the whole group has entered — and ('finalize',) notifications;
    when every worker finalizes, servers get ('stop',) and the scheduler
    exits.
    """

    #: ps-lite node-group masks (ps.h kScheduler/kServerGroup/kWorkerGroup)
    SCHEDULER_GROUP = 1
    SERVER_GROUP = 2
    WORKER_GROUP = 4

    def __init__(self, num_workers, num_servers, port=None):
        self.num_workers = num_workers
        self.num_servers = num_servers
        port = port if port is not None else int(
            os.environ.get('DMLC_PS_ROOT_PORT', 0))
        self.sock, self.port = listener(port=port)
        self._lock = threading.Lock()
        self._registered = {'worker': [], 'server': []}
        self._ready = threading.Event()
        self._barrier = {}  # group -> list of waiting conns
        self._finalized = 0
        self._threads = []
        self._beats = {}    # (role, rank) -> last heartbeat time
        self._start_time = time.time()
        self._done = threading.Event()

    def run(self):
        total = self.num_workers + self.num_servers
        conns = []
        while len(conns) < total:
            conn, _ = self.sock.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conns.append(conn)
            th = threading.Thread(target=self._serve, args=(conn,),
                                  daemon=True)
            th.start()
            self._threads.append(th)
        self._ready.wait()
        # keep accepting aux channels (heartbeat / dead-node queries —
        # reference ps-lite keeps its scheduler port open for control
        # messages throughout the job)
        aux_th = threading.Thread(target=self._accept_aux, daemon=True)
        aux_th.start()
        for th in self._threads:
            th.join()
        self._done.set()
        try:
            self.sock.close()
        except OSError:
            pass

    # -- failure detection (kvstore.h:321-330 get_num_dead_node) ---------
    def _accept_aux(self):
        self.sock.settimeout(0.5)
        while not self._done.is_set():
            try:
                conn, _ = self.sock.accept()
            except (socket.timeout, OSError):
                continue
            threading.Thread(target=self._serve_aux, args=(conn,),
                             daemon=True).start()

    def _serve_aux(self, conn):
        hello = recv_msg(conn)
        if not hello or hello[0] != 'aux':
            conn.close()
            return
        role, rank = hello[1], hello[2]
        with self._lock:
            self._beats[(role, rank)] = time.time()
        while not self._done.is_set():
            msg = recv_msg(conn)
            if msg is None:
                return
            if msg[0] == 'heartbeat':
                with self._lock:
                    self._beats[(role, rank)] = time.time()
            elif msg[0] == 'num_dead':
                send_msg(conn, ('num_dead', self._num_dead(msg[1], msg[2])))
            else:
                send_msg(conn, ('error', 'unknown aux message %r' % (msg[0],)))

    def _num_dead(self, node_id, timeout):
        """Count nodes in the masked groups whose heartbeat is stale.

        Heartbeats are seeded at node registration; a node that never
        arrived at all is measured from scheduler start. Either way a
        node becomes dead after ``timeout`` seconds of silence, never
        instantly (a query racing cluster startup must not report
        phantom dead nodes)."""
        now = time.time()
        dead = 0
        with self._lock:
            groups = []
            if node_id & self.WORKER_GROUP:
                groups.append(('worker', self.num_workers))
            if node_id & self.SERVER_GROUP:
                groups.append(('server', self.num_servers))
            for role, count in groups:
                for rank in range(count):
                    beat = self._beats.get((role, rank), self._start_time)
                    if now - beat > timeout:
                        dead += 1
        return dead

    def _serve(self, conn):
        msg = recv_msg(conn)
        if not msg or msg[0] != 'register':
            conn.close()
            return
        role = msg[1]
        addr = msg[2] if len(msg) > 2 else None
        with self._lock:
            rank = len(self._registered[role])
            self._registered[role].append((conn, addr))
            # registration seeds the heartbeat: the grace period for
            # failure detection starts per node when it arrives, so a
            # slow rendezvous never yields phantom dead nodes
            self._beats[(role, rank)] = time.time()
            done = (len(self._registered['worker']) == self.num_workers and
                    len(self._registered['server']) == self.num_servers)
        if done:
            with self._lock:
                servers = [a for _, a in self._registered['server']]
                for r, (c, _) in enumerate(self._registered['server']):
                    send_msg(c, ('topology', r, servers))
                for r, (c, _) in enumerate(self._registered['worker']):
                    send_msg(c, ('topology', r, servers))
            self._ready.set()
        self._ready.wait()
        while True:
            msg = recv_msg(conn)
            if msg is None:
                return
            kind = msg[0]
            if kind == 'barrier':
                self._enter_barrier(msg[1], conn)
            elif kind == 'finalize':
                if self._worker_finalized():
                    return
            else:
                send_msg(conn, ('error', 'unknown message %r' % (kind,)))

    def _enter_barrier(self, group, conn):
        sizes = {'worker': self.num_workers, 'server': self.num_servers,
                 'all': self.num_workers + self.num_servers}
        with self._lock:
            waiters = self._barrier.setdefault(group, [])
            waiters.append(conn)
            if len(waiters) < sizes[group]:
                return
            self._barrier[group] = []
            release = list(waiters)
        for c in release:
            send_msg(c, ('barrier_done',))

    def _worker_finalized(self):
        with self._lock:
            self._finalized += 1
            if self._finalized < self.num_workers:
                return False
            servers = [c for c, _ in self._registered['server']]
        for c in servers:
            try:
                send_msg(c, ('stop',))
            except OSError:
                pass
        return True


class KVStoreServer:
    """One parameter-server shard (kvstore_dist_server.h:109).

    dist_sync: pushes for a key accumulate in a merge buffer and the push
    *replies are deferred* until all DMLC_NUM_WORKER workers have pushed —
    that deferred ack is the synchronous-SGD barrier (ApplyUpdates:175).
    With an optimizer installed (pickled via a 'set_optimizer' command,
    reference kvstore.py:349-393) the merged gradient updates the stored
    weight; without one the merged sum *becomes* the stored value.

    dist_async: each push applies immediately and acks immediately
    (kvstore_dist_server.h:389-401).
    """

    def __init__(self):
        self.store = {}            # key -> np.ndarray
        self.sync_mode = False
        self.updater = None
        self._lock = threading.Lock()
        self._merge = {}           # key -> (buf, [conns awaiting ack])
        self.num_workers = int(os.environ.get('DMLC_NUM_WORKER', 1))
        self._stop = threading.Event()

    # -- role entry ------------------------------------------------------
    def run(self, sched_addr=None):
        sock, port = listener()
        host = os.environ.get('DMLC_NODE_HOST', _local_host())
        if sched_addr is None:
            sched_addr = (os.environ['DMLC_PS_ROOT_URI'],
                          os.environ['DMLC_PS_ROOT_PORT'])
        sched = connect(*sched_addr)
        send_msg(sched, ('register', 'server', (host, port)))
        topo = recv_msg(sched)
        assert topo and topo[0] == 'topology', topo
        self.rank = topo[1]
        threading.Thread(target=self._watch_scheduler, args=(sched,),
                         daemon=True).start()
        self._start_heartbeat(sched_addr)
        sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = sock.accept()
            except socket.timeout:
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()
        sock.close()

    def _watch_scheduler(self, sched):
        while True:
            msg = recv_msg(sched)
            if msg is None or msg[0] == 'stop':
                self._stop.set()
                return

    def _start_heartbeat(self, sched_addr, interval=2.0):
        try:
            aux = connect(*sched_addr)
            send_msg(aux, ('aux', 'server', self.rank))
        except OSError:
            return

        def beat():
            while not self._stop.is_set():
                time.sleep(interval)
                try:
                    send_msg(aux, ('heartbeat',))
                except OSError:
                    return

        threading.Thread(target=beat, daemon=True).start()

    # -- request handling ------------------------------------------------
    def _serve(self, conn):
        while not self._stop.is_set():
            msg = recv_msg(conn)
            if msg is None:
                return
            _dbg('recv %s %s' % (msg[0], msg[1] if len(msg) > 1 else ''))
            try:
                self.handle(msg, conn)
            except Exception as e:  # noqa: BLE001 — must not kill the conn
                _dbg('handler error: %r' % e)
                try:
                    send_msg(conn, ('error', repr(e)))
                except OSError:
                    return
            _dbg('done %s' % msg[0])

    def handle(self, msg, conn):
        kind = msg[0]
        if kind == 'init':
            _, key, triple = msg
            with self._lock:
                if key not in self.store:
                    self.store[key] = unpack_array(triple)
            send_msg(conn, ('ok',))
        elif kind == 'push':
            self._handle_push(msg[1], unpack_array(msg[2]), conn)
        elif kind == 'push_rsp':
            indices = unpack_array(msg[2])
            values = unpack_array(msg[3])
            self._handle_push(msg[1], (indices, values), conn, sparse=True)
        elif kind == 'push_c':
            # compressed push (MXTPU_GRAD_COMPRESS): version-tagged
            # payload. decode_wire raises on version/mode skew and the
            # serve loop turns that into an ('error', ...) reply — a
            # mixed-version gang fails loudly on its first compressed
            # push, never merges a misparsed gradient. An OLD server
            # hits the unknown-message branch below with the same
            # loud outcome.
            from .parallel import compression
            self._handle_push(msg[1], compression.decode_wire(msg[2]),
                              conn)
        elif kind == 'pull':
            with self._lock:
                arr = self.store[msg[1]]
            send_msg(conn, ('arr', pack_array(arr)))
        elif kind == 'pull_c':
            # bf16-compressed pull: the stored value goes back at half
            # width (value cast, no residual — weights are not a
            # gradient stream)
            from .parallel import compression
            with self._lock:
                arr = self.store[msg[1]]
            send_msg(conn, ('arr_c', compression.encode_wire(
                np.asarray(arr).reshape(-1), 'bf16')))
        elif kind == 'pull_rsp':
            # stored values are flat (init ships flattened stripes); view
            # them as rows of the requested width before gathering
            rows = unpack_array(msg[2]).astype(np.int64)
            row_shape = tuple(msg[3])
            with self._lock:
                vals = self.store[msg[1]].reshape(
                    (-1,) + row_shape)[rows]
            send_msg(conn, ('arr', pack_array(vals)))
        elif kind == 'cmd':
            self._handle_command(msg[1], msg[2])
            send_msg(conn, ('ok',))
        else:
            send_msg(conn, ('error', 'unknown message %r' % (kind,)))

    def _handle_push(self, key, grad, conn, sparse=False):
        if not self.sync_mode:
            with self._lock:
                self._apply(key, self._densify(key, grad, sparse))
            send_msg(conn, ('ok',))
            return
        with self._lock:
            dense = self._densify(key, grad, sparse)
            buf, waiters = self._merge.get(key, (None, []))
            buf = dense if buf is None else buf + dense
            waiters.append(conn)
            if len(waiters) < self.num_workers:
                self._merge[key] = (buf, waiters)
                return
            self._merge.pop(key, None)
            self._apply(key, buf)
            release = list(waiters)
        for c in release:
            send_msg(c, ('ok',))

    def _densify(self, key, grad, sparse):
        if not sparse:
            return grad
        indices, values = grad
        dense = np.zeros_like(self.store[key])
        # scatter through a row-shaped view — the store itself is flat
        view = dense.reshape((-1,) + values.shape[1:])
        np.add.at(view, indices.astype(np.int64), values)
        return dense

    def _apply(self, key, merged):
        """ApplyUpdates (kvstore_dist_server.h:175): optimizer if set,
        else the merged sum replaces the stored value."""
        if self.updater is None:
            self.store[key] = merged
            return
        from .ndarray import NDArray
        from .context import cpu
        import jax.numpy as jnp
        w = NDArray(jnp.asarray(self.store[key]), cpu())
        g = NDArray(jnp.asarray(merged), cpu())
        self.updater(_int_key(key), g, w)
        self.store[key] = np.asarray(w.asnumpy())

    def _handle_command(self, head, body):
        if head == 'set_optimizer':
            from . import optimizer as opt
            optimizer = pickle.loads(body)
            self.updater = opt.get_updater(optimizer)
        elif head == 'set_sync_mode':
            self.sync_mode = bool(body)
        elif head == 'stop':
            self._stop.set()
        else:
            raise ValueError('unknown server command %r' % (head,))


def _int_key(key):
    base = key.split('#', 1)[0] if isinstance(key, str) else key
    try:
        return int(base)
    except (TypeError, ValueError):
        return base


def _local_host():
    return os.environ.get('DMLC_LOCAL_HOST', '127.0.0.1')


def run_scheduler():
    sched = Scheduler(int(os.environ['DMLC_NUM_WORKER']),
                      int(os.environ['DMLC_NUM_SERVER']))
    sched.run()


def run_server():
    KVStoreServer().run()


def init_server_module_if_needed():
    """Reference kvstore_server.py:75 — server/scheduler processes take over
    when mxnet is imported, and the process exits with the role loop.

    The loop runs on a NON-daemon thread that first re-imports mxnet_tpu:
    that import blocks until the interpreter's in-progress import of the
    package (we are called from __init__.py) completes. Blocking the import
    itself would deadlock the server: handling 'set_optimizer' unpickles an
    optimizer, and pickle's __import__ of mxnet_tpu.optimizer waits on the
    parent package's import lock.
    """
    role = os.environ.get('DMLC_ROLE', '')
    if role not in ('server', 'scheduler'):
        return
    # Server/scheduler are host-side roles (reference: CPU processes next
    # to ps-lite) — never let them grab the accelerator; in particular a
    # single-chip TPU must stay dedicated to the workers.
    try:
        import jax
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass

    def role_main():
        import mxnet_tpu  # noqa: F401 — wait for the package import to finish
        if role == 'server':
            run_server()
        else:
            run_scheduler()
        os._exit(0)

    threading.Thread(target=role_main, name='kvstore-' + role,
                     daemon=False).start()
