"""Checkpointing + kvstore training helpers.

Reference: python/mxnet/model.py (967 LoC) — save_checkpoint:340 /
load_checkpoint:370 ({prefix}-symbol.json + {prefix}-{epoch:04d}.params with
arg:/aux: key prefixes), and the kvstore helpers Module/Gluon build on:
_create_kvstore:57, _initialize_kvstore:96, _update_params(_on_kvstore):105.
"""
import logging
from collections import namedtuple

from . import io
from . import ndarray as nd
from . import symbol as sym
from . import optimizer as opt
from . import metric
from . import kvstore as kvs

BatchEndParam = namedtuple('BatchEndParams',
                           ['epoch', 'nbatch', 'eval_metric', 'locals'])


def _create_kvstore(kvstore, num_device, arg_params):
    """Reference model.py:57 — returns (kvstore, update_on_kvstore)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and 'dist' not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == 'local':
                max_size = max(np.prod(param.shape) for param in arg_params.values()) \
                    if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError('kvstore must be KVStore, string or None')
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


import numpy as np  # noqa: E402 (used above lazily)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Reference model.py:96."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """Reference model.py:105 — push grads, pull updated weights."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """Reference model.py:117 — aggregate on kvstore, update locally."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            if kvstore is not None and w._data.sharding != \
                    g._data.sharding:
                # the pull above re-materialized the summed gradient on
                # its own context's single device, but on an SPMD group
                # the weight is a mesh-sharded (or differently placed)
                # global array — the updater would then mix placements
                # and jax either raises or silently gathers. Restore
                # the invariant the executor group established: the
                # gradient lives exactly where its weight lives.
                import jax
                g._data = jax.device_put(g._data, w._data.sharding)
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Reference model.py:340."""
    if symbol is not None:
        symbol.save('%s-symbol.json' % prefix)
    save_dict = {('arg:%s' % k): v for k, v in arg_params.items()}
    save_dict.update({('aux:%s' % k): v for k, v in aux_params.items()})
    param_name = '%s-%04d.params' % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """Reference model.py:370. Returns (symbol, arg_params, aux_params)."""
    symbol = sym.load('%s-symbol.json' % prefix)
    save_dict = nd.load('%s-%04d.params' % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(':', 1)
        if tp == 'arg':
            arg_params[name] = v
        if tp == 'aux':
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


def _num_samples(X):
    """Sample count of an NDArrayIter-style source: array, dict of
    arrays, or list of arrays (batch axis 0)."""
    if isinstance(X, dict):
        X = next(iter(X.values()))
    elif isinstance(X, (list, tuple)):
        X = X[0]
    return len(X)


class FeedForward:
    """Deprecated legacy API (reference model.py FeedForward) — kept as a
    thin shim over Module for API completeness."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer='sgd', initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .module import Module
        from .initializer import Uniform
        if isinstance(symbol, sym.Symbol) or not callable(symbol):
            self.symbol = symbol
            self._sym_gen = None
        else:
            # reference model.py:460-464: a callable symbol is a
            # sym_gen(bucket_key) for bucketing iterators; kept so every
            # fit() re-lowers through BucketingModule
            self.symbol = None
            self._sym_gen = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.numpy_batch_size = numpy_batch_size
        self.kwargs = kwargs
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric='acc', kvstore='local',
            batch_end_callback=None, epoch_end_callback=None, logger=None,
            work_load_list=None, monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        from .module import Module, BucketingModule
        if not isinstance(X, io.DataIter):
            X = io.NDArrayIter(X, y, batch_size=min(self.numpy_batch_size,
                                                    _num_samples(X)),
                               shuffle=True)
        data_names = [d[0] for d in X.provide_data]
        label_names = [l[0] for l in X.provide_label]
        if self._sym_gen is not None:
            # reference model.py:797-798: the resolved default-bucket
            # symbol is kept for save()/checkpointing (widest bucket,
            # rnn/rnn.py's convention); the cache both dedups the
            # resolve here with BucketingModule.bind's and speeds
            # per-bucket switches
            gen, cache = self._sym_gen, {}

            def _gen(key):
                if key not in cache:
                    cache[key] = gen(key)
                return cache[key], data_names, label_names

            self._module = BucketingModule(
                _gen, default_bucket_key=X.default_bucket_key,
                context=self.ctx)
            self.symbol = _gen(X.default_bucket_key)[0]
        else:
            self._module = Module(self.symbol,
                                  data_names=data_names,
                                  label_names=label_names,
                                  context=self.ctx)
        self._module.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                         kvstore=kvstore, initializer=self.initializer,
                         arg_params=self.arg_params, aux_params=self.aux_params,
                         optimizer=self.optimizer, optimizer_params=self.kwargs,
                         begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
                         monitor=monitor,
                         eval_end_callback=eval_end_callback,
                         eval_batch_end_callback=eval_batch_end_callback,
                         batch_end_callback=batch_end_callback,
                         epoch_end_callback=epoch_end_callback)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    def predict(self, X, num_batch=None):
        if not isinstance(X, io.DataIter):
            X = io.NDArrayIter(X, batch_size=min(self.numpy_batch_size,
                                                 _num_samples(X)))
        return self._module.predict(X, num_batch=num_batch).asnumpy()

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch or self.num_epoch, self.symbol,
                        self.arg_params, self.aux_params or {})
