"""Python side of the C ABI bridge.

Reference: include/mxnet/c_api.h (146 MXNET_DLL entry points over opaque
handles) and src/c_api/c_api.cc / c_api_symbolic.cc / c_api_executor.cc.

Design (TPU-native): the reference's C API fronts a C++ core; here the
core is the JAX/XLA runtime hosted by CPython, so the C ABI
(src/c_api.cc) embeds the interpreter and delegates each entry point to
one helper in this module. Handles crossing the ABI are CPython object
pointers (ref-counted by the C layer); device compute still runs through
XLA, so nothing is lost relative to the reference's dispatch path — the
C frontier is control-plane only, exactly like the reference's (its data
plane is cudnn/mshadow kernels; ours is XLA executables).

Helpers accept/return only simple types (int/float/str/bytes/lists/
tuples and handle objects) so the C marshalling layer stays mechanical.
"""
import pickle

import numpy as np

# Lazy imports: embedding apps call MXPredCreate before anything else and
# must not pay package-import cost twice.
from . import ndarray as _nd_mod
from .ndarray import NDArray
from .ndarray.ndarray import invoke as _nd_invoke, waitall as _nd_waitall
from .ndarray import utils as _nd_utils
from .context import Context
from .ops import registry as _op_reg
from .symbol import Symbol, Variable as _sym_var
from .symbol.symbol import (_invoke_sym, _parse_attr,
                            load_json as _sym_load_json)
from . import autograd as _autograd
from . import kvstore as _kvstore_mod
from . import random as _random_mod
from . import profiler as _profiler_mod

_DTYPE_TO_CODE = {'float32': 0, 'float64': 1, 'float16': 2, 'uint8': 3,
                  'int32': 4, 'int8': 5, 'int64': 6, 'bfloat16': 7}
_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_CODE.items()}
_DEVTYPE = {1: 'cpu', 2: 'gpu', 3: 'cpu_pinned', 6: 'tpu'}
_DEVTYPE_R = {'cpu': 1, 'gpu': 2, 'cpu_pinned': 3, 'tpu': 6}
_STYPE = {'default': 0, 'row_sparse': 1, 'csr': 2}


def _ctx(dev_type, dev_id):
    name = _DEVTYPE.get(int(dev_type), 'cpu')
    if name == 'cpu_pinned':
        name = 'cpu'
    return Context(name, int(dev_id))


# ---------------------------------------------------------------- misc --

def random_seed(seed):
    _random_mod.seed(int(seed))
    return 0


def notify_shutdown():
    _nd_waitall()
    return 0


def profiler_set_config(mode, filename):
    _profiler_mod.profiler_set_config(mode=mode, filename=filename)
    return 0


def profiler_set_state(state):
    _profiler_mod.profiler_set_state('run' if int(state) else 'stop')
    return 0


def profiler_dump():
    _profiler_mod.dump_profile()
    return 0


# ------------------------------------------------------------- ndarray --

def nd_create_none():
    return NDArray(np.zeros((), dtype=np.float32))


def nd_create(shape, dev_type, dev_id, delay_alloc, dtype_code):
    dtype = _CODE_TO_DTYPE[int(dtype_code)]
    if dtype == 'bfloat16':
        import jax.numpy as jnp
        import jax
        data = jnp.zeros(tuple(shape), dtype=jnp.bfloat16)
        return NDArray(data, ctx=_ctx(dev_type, dev_id))
    return _nd_mod.zeros(tuple(shape), ctx=_ctx(dev_type, dev_id),
                         dtype=dtype)


def nd_sync_copy_from_bytes(handle, buf, dtype_code):
    """Raw bytes in the array's wire dtype (bf16 = 2 B/elt via ml_dtypes,
    exactly the dtype MXNDArrayGetDType reports)."""
    dtype = _CODE_TO_DTYPE[int(dtype_code)]
    np_dtype = np.dtype(dtype)  # ml_dtypes registers 'bfloat16'
    expect = int(np.prod(handle.shape)) * np_dtype.itemsize
    if len(buf) != expect:
        raise ValueError('SyncCopyFromCPU: got %d bytes, array needs %d'
                         % (len(buf), expect))
    arr = np.frombuffer(buf, dtype=np_dtype).reshape(handle.shape)
    if dtype == 'bfloat16':
        import jax.numpy as jnp
        handle._set_data(jnp.asarray(arr))
        return 0
    handle[:] = arr if handle.ndim else _nd_mod.array(arr.reshape(()))
    return 0


def nd_sync_copy_to_bytes(handle):
    """Raw bytes in the array's own dtype — byte count always equals
    size * itemsize of the dtype MXNDArrayGetDType reports (asnumpy()
    upcasts bf16 for python users, so read the device buffer directly)."""
    return np.ascontiguousarray(np.asarray(handle._data)).tobytes()


def nd_wait_to_read(handle):
    handle.wait_to_read()
    return 0


def nd_wait_all():
    _nd_waitall()
    return 0


def nd_shape(handle):
    return tuple(int(d) for d in handle.shape)


def nd_dtype(handle):
    return _DTYPE_TO_CODE.get(str(handle.dtype), 0)


def nd_stype(handle):
    return _STYPE.get(handle.stype, 0)


def nd_context(handle):
    c = handle.context
    return (_DEVTYPE_R.get(c.device_type, 1), c.device_id)


def nd_slice(handle, begin, end):
    return handle[int(begin):int(end)]


def nd_at(handle, idx):
    return handle[int(idx)]


def nd_reshape(handle, shape):
    return handle.reshape(tuple(shape))


def nd_save(fname, handles, keys):
    if keys:
        _nd_utils.save(fname, dict(zip(keys, handles)))
    else:
        _nd_utils.save(fname, list(handles))
    return 0


def nd_load(fname):
    data = _nd_utils.load(fname)
    if isinstance(data, dict):
        keys = list(data.keys())
        return keys, [data[k] for k in keys]
    return [], list(data)


def nd_save_raw_bytes(handle):
    npy = handle.asnumpy()
    if npy.dtype.name == 'bfloat16':
        npy = npy.astype(np.float32)
    header = pickle.dumps((npy.shape, npy.dtype.str))
    return len(header).to_bytes(8, 'little') + header + npy.tobytes()


def nd_load_from_raw_bytes(buf):
    hlen = int.from_bytes(buf[:8], 'little')
    shape, dtype = pickle.loads(buf[8:8 + hlen])
    npy = np.frombuffer(buf[8 + hlen:], dtype=np.dtype(dtype)).reshape(shape)
    return _nd_mod.array(npy)


# Host mirror buffers for MXNDArrayGetData: NDArray is __slots__'d, so
# pinned numpy views live here, keyed by handle id, until MXNDArrayFree.
_HOST_MIRRORS = {}


def nd_data_ptr(handle):
    npy = handle.asnumpy()
    if npy.dtype.name == 'bfloat16':
        npy = npy.astype(np.float32)
    npy = np.ascontiguousarray(npy)
    _HOST_MIRRORS[id(handle)] = npy
    return npy.ctypes.data


def nd_free(handle):
    _HOST_MIRRORS.pop(id(handle), None)
    return 0


def nd_get_grad(handle):
    return handle.grad


def nd_detach(handle):
    return handle.detach()


# ----------------------------------------------------------- operators --

def list_all_op_names():
    return sorted(_op_reg.list_ops())


def op_info(name):
    op = _op_reg.get(name)
    arg_names = list(op.input_names) + list(op.param_defaults)
    arg_types = (['NDArray-or-Symbol'] * len(op.input_names)
                 + ['string'] * len(op.param_defaults))
    arg_descs = [''] * len(arg_names)
    return (name, op.doc or '', arg_names, arg_types, arg_descs,
            op.key_var_num_args or '', '')


def imperative_invoke(name, inputs, keys, vals, num_out_provided, outputs):
    # C callers send every param as a string; recover typed attrs the same
    # way symbol JSON loading does (tuples, bools, numbers)
    attrs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
    out = None
    if num_out_provided:
        out = outputs if len(outputs) > 1 else outputs[0]
    res = _nd_invoke(name, list(inputs), attrs, out)
    if isinstance(res, (list, tuple)):
        return list(res)
    return [res]


# ------------------------------------------------------------ autograd --

def autograd_set_recording(flag):
    prev = _autograd.is_recording()
    _autograd.set_recording(bool(flag))
    return int(prev)


def autograd_set_training(flag):
    prev = _autograd.is_training()
    _autograd.set_training(bool(flag))
    return int(prev)


def autograd_is_recording():
    return int(_autograd.is_recording())


def autograd_is_training():
    return int(_autograd.is_training())


def autograd_mark_variables(arrays, grad_reqs, grads):
    # OpReqType codes: 0=null, 1=write, 2=inplace, 3=add (ndarray.h)
    req_map = {0: 'null', 1: 'write', 2: 'write', 3: 'add'}
    for arr, req, grad in zip(arrays, grad_reqs, grads):
        req_name = req_map.get(int(req), 'write')
        if grad is not None:
            # bind the caller's buffer: backward rebinds grad._data in
            # place, so the C handle observes the gradients directly
            _autograd.mark_variables([arr], [grad], req_name)
        else:
            arr.attach_grad(grad_req=req_name)
    return 0


def autograd_backward(outputs, head_grads, retain_graph, train_mode):
    _autograd.backward(list(outputs),
                       head_grads=None if not head_grads else list(head_grads),
                       retain_graph=bool(retain_graph),
                       train_mode=bool(train_mode))
    return 0


# ------------------------------------------------------------- symbols --

class _AtomicSymbol:
    """An op + attrs awaiting composition (MXSymbolCreateAtomicSymbol
    result before MXSymbolCompose — reference nnvm Symbol::CreateFunctor)."""

    __slots__ = ('op', 'attrs')

    def __init__(self, op, attrs):
        self.op = op
        self.attrs = attrs


def symbol_create_atomic(op_name, keys, vals):
    if not _op_reg.exists(op_name):
        raise ValueError('unknown operator %s' % op_name)
    return _AtomicSymbol(op_name,
                         {k: _parse_attr(v) for k, v in zip(keys, vals)})


# MXSymbolCompose mutates in place in the reference (nnvm symbols are
# mutable); ours are immutable, so composed results live here, keyed by
# handle id, purged by symbol_free (called from MXSymbolFree).
_COMPOSED = {}


def symbol_compose(handle, name, keys, args):
    """Compose an atomic symbol with its inputs → real Symbol."""
    if isinstance(handle, _AtomicSymbol):
        attrs = dict(handle.attrs)
        if name:
            attrs['name'] = name
        if keys:
            # keyword symbol args map onto the op's declared input names,
            # in declaration order; leftovers are attrs
            op = _op_reg.get(handle.op)
            kw = {k: _as_symbol(a) for k, a in zip(keys, args)}
            inputs = [kw.pop(n) for n in op.input_names if n in kw]
            attrs.update(kw)
            return _invoke_sym(handle.op, inputs, attrs)
        return _invoke_sym(handle.op, [_as_symbol(a) for a in args], attrs)
    sym = _as_symbol(handle)
    if keys:
        return sym(**{k: _as_symbol(a) for k, a in zip(keys, args)})
    return sym(*[_as_symbol(a) for a in args])


def symbol_compose_inplace(handle, name, keys, args):
    _COMPOSED[id(handle)] = symbol_compose(handle, name, keys, args)
    return 0


def symbol_free(handle):
    _COMPOSED.pop(id(handle), None)
    return 0


def _as_symbol(handle):
    composed = _COMPOSED.get(id(handle))
    if composed is not None:
        return composed
    if isinstance(handle, _AtomicSymbol):
        return _invoke_sym(handle.op, [], dict(handle.attrs))
    return handle


def symbol_create_variable(name):
    return _sym_var(name)


def symbol_create_group(handles):
    from .symbol import Group
    return Group([_as_symbol(h) for h in handles])


def symbol_from_json(json_str):
    return _sym_load_json(json_str)


def symbol_from_file(fname):
    from .symbol import load as _sym_load
    return _sym_load(fname)


def symbol_to_json(handle):
    return _as_symbol(handle).tojson()


def symbol_save_file(handle, fname):
    _as_symbol(handle).save(fname)
    return 0


def symbol_copy(handle):
    import copy
    return copy.copy(_as_symbol(handle))


def symbol_print(handle):
    return repr(_as_symbol(handle))


def symbol_get_name(handle):
    name = _as_symbol(handle).name
    return name if name is not None else ''


def symbol_get_attr(handle, key):
    v = _as_symbol(handle).attr(key)
    return v if v is not None else None


def symbol_set_attr(handle, key, value):
    _as_symbol(handle)._set_attr(**{key: value})
    return 0


def symbol_list_attr(handle):
    d = _as_symbol(handle).attr_dict()
    flat = []
    for node_name, attrs in d.items():
        for k, v in attrs.items():
            flat.append('%s$%s' % (node_name, k))
            flat.append(str(v))
    return flat


def symbol_list_arguments(handle):
    return _as_symbol(handle).list_arguments()


def symbol_list_outputs(handle):
    return _as_symbol(handle).list_outputs()


def symbol_list_aux(handle):
    return _as_symbol(handle).list_auxiliary_states()


def symbol_get_internals(handle):
    return _as_symbol(handle).get_internals()


def symbol_get_children(handle):
    return _as_symbol(handle).get_children()


def symbol_get_output(handle, index):
    return _as_symbol(handle)[int(index)]


def symbol_grad(handle, wrt):
    return _as_symbol(handle).gradient(list(wrt))


def _shape_kwargs(keys, arg_ind, arg_data):
    kwargs = {}
    for i, k in enumerate(keys):
        kwargs[k] = tuple(arg_data[arg_ind[i]:arg_ind[i + 1]])
    return kwargs


def symbol_infer_shape(handle, keys, arg_ind, arg_data, partial):
    sym = _as_symbol(handle)
    kwargs = _shape_kwargs(keys, arg_ind, arg_data)
    if partial:
        arg_shapes, out_shapes, aux_shapes = sym.infer_shape_partial(**kwargs)
    else:
        arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**kwargs)
    def pack(shapes):
        return [tuple(int(d) for d in s) if s is not None else ()
                for s in (shapes or [])]
    return pack(arg_shapes), pack(out_shapes), pack(aux_shapes)


def symbol_infer_type(handle, keys, dtype_codes):
    sym = _as_symbol(handle)
    kwargs = {k: _CODE_TO_DTYPE[int(c)] for k, c in zip(keys, dtype_codes)}
    arg_t, out_t, aux_t = sym.infer_type(**kwargs)
    def pack(ts):
        return [_DTYPE_TO_CODE.get(str(np.dtype(t).name) if t is not None
                                   else '', -1) if t is not None else -1
                for t in (ts or [])]
    return pack(arg_t), pack(out_t), pack(aux_t)


# ----------------------------------------------------------- executors --

def executor_bind(sym_handle, dev_type, dev_id, args, arg_grads, grad_reqs,
                  aux_states):
    sym = _as_symbol(sym_handle)
    ctx = _ctx(dev_type, dev_id)
    req_names = {0: 'null', 1: 'write', 3: 'add'}
    arg_names = sym.list_arguments()
    args_map = dict(zip(arg_names, args))
    grads_map = {n: g for n, g in zip(arg_names, arg_grads or [])
                 if g is not None}
    reqs = {n: req_names.get(int(r), 'write')
            for n, r in zip(arg_names, grad_reqs or [])} or 'write'
    aux_map = dict(zip(sym.list_auxiliary_states(), aux_states or []))
    return sym.bind(ctx, args_map, args_grad=grads_map or None,
                    grad_req=reqs, aux_states=aux_map or None)


def executor_forward(handle, is_train):
    handle.forward(is_train=bool(is_train))
    return 0


def executor_backward(handle, out_grads):
    handle.backward(out_grads=list(out_grads) if out_grads else None)
    return 0


def executor_outputs(handle):
    return list(handle.outputs)


def executor_print(handle):
    return repr(handle)


# ------------------------------------------------------------ cachedop --

class _CachedOp:
    """MXCreateCachedOp: a symbol specialized for repeated imperative calls
    (reference src/imperative/cached_op.cc). Here: bind-once + jit reuse
    keyed on input shapes, via Symbol.eval machinery."""

    def __init__(self, sym):
        self.sym = _as_symbol(sym)
        self._cache = {}

    def __call__(self, inputs):
        names = self.sym.list_arguments()
        key = tuple((a.shape, str(a.dtype)) for a in inputs)
        ex = self._cache.get(key)
        if ex is None:
            ctx = inputs[0].context if inputs else Context('cpu', 0)
            ex = self.sym.bind(ctx, dict(zip(names, inputs)),
                               grad_req='null')
            self._cache[key] = ex
        else:
            ex.copy_params_from(dict(zip(names, inputs)),
                                allow_extra_params=True)
        ex.forward(is_train=False)
        return list(ex.outputs)


def cached_op_create(sym_handle):
    return _CachedOp(sym_handle)


def cached_op_invoke(handle, inputs):
    return handle(list(inputs))


# ------------------------------------------------------------- kvstore --

def kv_create(type_name):
    return _kvstore_mod.create(type_name)


def kv_init(handle, keys, values):
    handle.init(list(keys), list(values))
    return 0


def kv_push(handle, keys, values, priority):
    handle.push(list(keys), list(values), priority=int(priority))
    return 0


def kv_pull(handle, keys, outs, priority):
    handle.pull(list(keys), out=list(outs), priority=int(priority))
    return 0


def kv_type(handle):
    return handle.type


def kv_rank(handle):
    return handle.rank


def kv_group_size(handle):
    return handle.num_workers


def kv_barrier(handle):
    if hasattr(handle, '_barrier'):
        handle._barrier()
    return 0


def kv_num_dead_node(handle, node_id):
    if hasattr(handle, 'num_dead_node'):
        return handle.num_dead_node(int(node_id))
    return 0


def kv_run_server(handle):
    """MXKVStoreRunServer — blocks in the server role loop."""
    from . import kvstore_server
    kvstore_server.run_server()
    return 0


def kv_send_command(handle, cmd_id, cmd_body):
    if hasattr(handle, '_send_command_to_servers'):
        handle._send_command_to_servers(int(cmd_id), cmd_body)
    return 0


# ------------------------------------------------------------- dataio --

_ITER_CLASSES = None


def _iter_classes():
    global _ITER_CLASSES
    if _ITER_CLASSES is None:
        from . import io as _io
        _ITER_CLASSES = {
            'MNISTIter': _io.MNISTIter,
            'CSVIter': _io.CSVIter,
            'ImageRecordIter': _io.ImageRecordIter,
            'ImageDetRecordIter': _io.ImageDetRecordIter,
            'LibSVMIter': _io.LibSVMIter,
        }
    return _ITER_CLASSES


def list_data_iters():
    return sorted(_iter_classes().keys())


def data_iter_create(name, keys, vals):
    cls = _iter_classes()[name]
    kwargs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
    return iter(cls(**kwargs))


class _IterState:
    __slots__ = ('it', 'batch')

    def __init__(self, it):
        self.it = it
        self.batch = None


def iter_state_new(it):
    return _IterState(it)


def data_iter_next(handle):
    try:
        handle.batch = next(handle.it)
        return 1
    except StopIteration:
        return 0


def data_iter_before_first(handle):
    handle.it.reset()
    return 0


def data_iter_get_data(handle):
    return handle.batch.data[0]


def data_iter_get_label(handle):
    return handle.batch.label[0]


def data_iter_get_pad(handle):
    return int(handle.batch.pad or 0)


# ------------------------------------------------------------- predict --

class _Predictor:
    """MXPredCreate state (reference src/c_api/c_predict_api.cc:57-177):
    symbol json + param blob → bound inference executor."""

    def __init__(self, symbol_json, param_bytes, dev_type, dev_id,
                 input_keys, input_shapes, output_keys=None):
        import io as _pyio
        sym = _sym_load_json(symbol_json)
        if output_keys:
            outs = sym.list_outputs()
            picked = []
            for k in output_keys:
                name = k if k.endswith('_output') else k + '_output'
                idx = outs.index(name) if name in outs else outs.index(k)
                picked.append(sym[idx])
            from .symbol import Group
            sym = Group(picked) if len(picked) > 1 else picked[0]
        self.sym = sym
        # param blob: NDArray save format (arg:/aux: prefixed dict)
        params = {}
        if param_bytes:
            import tempfile, os
            with tempfile.NamedTemporaryFile(delete=False) as f:
                f.write(param_bytes)
                tmp = f.name
            try:
                loaded = _nd_utils.load(tmp)
            finally:
                os.unlink(tmp)
            for k, v in (loaded.items() if isinstance(loaded, dict) else []):
                params[k.split(':', 1)[-1]] = v
        ctx = _ctx(dev_type, dev_id)
        shapes = dict(zip(input_keys, [tuple(s) for s in input_shapes]))
        arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        self.input_keys = list(input_keys)
        args = {}
        for name, shp in zip(arg_names, arg_shapes):
            if name in params:
                args[name] = params[name].as_in_context(ctx)
            else:
                args[name] = _nd_mod.zeros(shp, ctx=ctx)
        aux = {}
        for name, shp in zip(aux_names, aux_shapes or []):
            if name in params:
                aux[name] = params[name].as_in_context(ctx)
            else:
                aux[name] = _nd_mod.zeros(shp, ctx=ctx)
        self.executor = sym.bind(ctx, args, grad_req='null',
                                 aux_states=aux or None)
        self.args = args

    def set_input(self, key, buf, shape):
        arr = np.frombuffer(buf, dtype=np.float32).reshape(shape)
        self.args[key][:] = arr
        return 0

    def forward(self):
        self.executor.forward(is_train=False)
        return 0

    def get_output_shape(self, index):
        out = self.executor.outputs[int(index)]
        return tuple(int(d) for d in out.shape)

    def get_output(self, index):
        out = self.executor.outputs[int(index)]
        npy = out.asnumpy()
        if npy.dtype != np.float32:
            npy = npy.astype(np.float32)
        return npy.tobytes()


def pred_create(symbol_json, param_bytes, dev_type, dev_id, input_keys,
                input_shapes, output_keys=None):
    return _Predictor(symbol_json, param_bytes, dev_type, dev_id,
                      input_keys, input_shapes, output_keys)


def nd_list_create(buf):
    """MXNDListCreate: load an NDArray-save blob → (keys, arrays)."""
    import tempfile, os
    with tempfile.NamedTemporaryFile(delete=False) as f:
        f.write(buf)
        tmp = f.name
    try:
        loaded = _nd_utils.load(tmp)
    finally:
        os.unlink(tmp)
    if isinstance(loaded, dict):
        keys = list(loaded.keys())
        return keys, [loaded[k] for k in keys]
    return [''] * len(loaded), list(loaded)


def nd_list_get(keys, arrays, index):
    i = int(index)
    arr = arrays[i]
    npy = arr.asnumpy().astype(np.float32)
    return keys[i], npy.tobytes(), tuple(int(d) for d in npy.shape)
