"""Python side of the C ABI bridge.

Reference: include/mxnet/c_api.h (146 MXNET_DLL entry points over opaque
handles) and src/c_api/c_api.cc / c_api_symbolic.cc / c_api_executor.cc.

Design (TPU-native): the reference's C API fronts a C++ core; here the
core is the JAX/XLA runtime hosted by CPython, so the C ABI
(src/c_api.cc) embeds the interpreter and delegates each entry point to
one helper in this module. Handles crossing the ABI are CPython object
pointers (ref-counted by the C layer); device compute still runs through
XLA, so nothing is lost relative to the reference's dispatch path — the
C frontier is control-plane only, exactly like the reference's (its data
plane is cudnn/mshadow kernels; ours is XLA executables).

Helpers accept/return only simple types (int/float/str/bytes/lists/
tuples and handle objects) so the C marshalling layer stays mechanical.
"""
import pickle

import numpy as np

# Lazy imports: embedding apps call MXPredCreate before anything else and
# must not pay package-import cost twice.
from . import ndarray as _nd_mod
from .ndarray import NDArray
from .ndarray.ndarray import invoke as _nd_invoke, waitall as _nd_waitall
from .ndarray import utils as _nd_utils
from .context import Context
from .ops import registry as _op_reg
from .symbol import Symbol, Variable as _sym_var
from .symbol.symbol import (_invoke_sym, _parse_attr,
                            load_json as _sym_load_json)
from . import autograd as _autograd
from . import kvstore as _kvstore_mod
from . import random as _random_mod
from . import profiler as _profiler_mod

_DTYPE_TO_CODE = {'float32': 0, 'float64': 1, 'float16': 2, 'uint8': 3,
                  'int32': 4, 'int8': 5, 'int64': 6, 'bfloat16': 7}
_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_CODE.items()}
_DEVTYPE = {1: 'cpu', 2: 'gpu', 3: 'cpu_pinned', 6: 'tpu'}
_DEVTYPE_R = {'cpu': 1, 'gpu': 2, 'cpu_pinned': 3, 'tpu': 6}
_STYPE = {'default': 0, 'row_sparse': 1, 'csr': 2}


def _ctx(dev_type, dev_id):
    name = _DEVTYPE.get(int(dev_type), 'cpu')
    if name == 'cpu_pinned':
        name = 'cpu'
    return Context(name, int(dev_id))


# ---------------------------------------------------------------- misc --

def random_seed(seed):
    _random_mod.seed(int(seed))
    return 0


def notify_shutdown():
    _nd_waitall()
    return 0


def profiler_set_config(mode, filename):
    _profiler_mod.profiler_set_config(mode=mode, filename=filename)
    return 0


def profiler_set_state(state):
    _profiler_mod.profiler_set_state('run' if int(state) else 'stop')
    return 0


def profiler_dump():
    _profiler_mod.dump_profile()
    return 0


# ------------------------------------------------------------- ndarray --

def nd_create_none():
    return NDArray(np.zeros((), dtype=np.float32))


def nd_create(shape, dev_type, dev_id, delay_alloc, dtype_code):
    dtype = _CODE_TO_DTYPE[int(dtype_code)]
    if dtype == 'bfloat16':
        import jax.numpy as jnp
        import jax
        data = jnp.zeros(tuple(shape), dtype=jnp.bfloat16)
        return NDArray(data, ctx=_ctx(dev_type, dev_id))
    return _nd_mod.zeros(tuple(shape), ctx=_ctx(dev_type, dev_id),
                         dtype=dtype)


def nd_sync_copy_from_bytes(handle, buf, dtype_code):
    """Raw bytes in the array's wire dtype (bf16 = 2 B/elt via ml_dtypes,
    exactly the dtype MXNDArrayGetDType reports)."""
    dtype = _CODE_TO_DTYPE[int(dtype_code)]
    np_dtype = np.dtype(dtype)  # ml_dtypes registers 'bfloat16'
    expect = int(np.prod(handle.shape)) * np_dtype.itemsize
    if len(buf) != expect:
        raise ValueError('SyncCopyFromCPU: got %d bytes, array needs %d'
                         % (len(buf), expect))
    arr = np.frombuffer(buf, dtype=np_dtype).reshape(handle.shape)
    if dtype == 'bfloat16':
        import jax.numpy as jnp
        handle._set_data(jnp.asarray(arr))
        return 0
    handle[:] = arr if handle.ndim else _nd_mod.array(arr.reshape(()))
    return 0


def nd_sync_copy_to_bytes(handle):
    """Raw bytes in the array's own dtype — byte count always equals
    size * itemsize of the dtype MXNDArrayGetDType reports (asnumpy()
    upcasts bf16 for python users, so read the device buffer directly)."""
    return np.ascontiguousarray(np.asarray(handle._data)).tobytes()


def nd_wait_to_read(handle):
    handle.wait_to_read()
    return 0


def nd_wait_all():
    _nd_waitall()
    return 0


def nd_shape(handle):
    return tuple(int(d) for d in handle.shape)


def nd_dtype(handle):
    return _DTYPE_TO_CODE.get(str(handle.dtype), 0)


def nd_stype(handle):
    return _STYPE.get(handle.stype, 0)


def nd_context(handle):
    c = handle.context
    return (_DEVTYPE_R.get(c.device_type, 1), c.device_id)


def nd_slice(handle, begin, end):
    return handle[int(begin):int(end)]


def nd_at(handle, idx):
    return handle[int(idx)]


def nd_reshape(handle, shape):
    return handle.reshape(tuple(shape))


def nd_save(fname, handles, keys):
    if keys:
        _nd_utils.save(fname, dict(zip(keys, handles)))
    else:
        _nd_utils.save(fname, list(handles))
    return 0


def nd_load(fname):
    data = _nd_utils.load(fname)
    if isinstance(data, dict):
        keys = list(data.keys())
        return keys, [data[k] for k in keys]
    return [], list(data)


def nd_save_raw_bytes(handle):
    npy = handle.asnumpy()
    if npy.dtype.name == 'bfloat16':
        npy = npy.astype(np.float32)
    header = pickle.dumps((npy.shape, npy.dtype.str))
    return len(header).to_bytes(8, 'little') + header + npy.tobytes()


def nd_load_from_raw_bytes(buf):
    hlen = int.from_bytes(buf[:8], 'little')
    shape, dtype = pickle.loads(buf[8:8 + hlen])
    npy = np.frombuffer(buf[8 + hlen:], dtype=np.dtype(dtype)).reshape(shape)
    return _nd_mod.array(npy)


# Host mirror buffers for MXNDArrayGetData: NDArray is __slots__'d, so
# pinned numpy views live here, keyed by handle id, until MXNDArrayFree.
_HOST_MIRRORS = {}


def nd_data_ptr(handle):
    npy = handle.asnumpy()
    if npy.dtype.name == 'bfloat16':
        npy = npy.astype(np.float32)
    npy = np.ascontiguousarray(npy)
    _HOST_MIRRORS[id(handle)] = npy
    return npy.ctypes.data


def nd_free(handle):
    _HOST_MIRRORS.pop(id(handle), None)
    return 0


def nd_get_grad(handle):
    return handle.grad


def nd_detach(handle):
    return handle.detach()


# ----------------------------------------------------------- operators --

def list_all_op_names():
    return sorted(_op_reg.list_ops())


def op_info(name):
    op = _op_reg.get(name)
    arg_names = list(op.input_names) + list(op.param_defaults)
    arg_types = (['NDArray-or-Symbol'] * len(op.input_names)
                 + ['string'] * len(op.param_defaults))
    arg_descs = [''] * len(arg_names)
    return (name, op.doc or '', arg_names, arg_types, arg_descs,
            op.key_var_num_args or '', '')


def imperative_invoke(name, inputs, keys, vals, num_out_provided, outputs):
    # C callers send every param as a string; recover typed attrs the same
    # way symbol JSON loading does (tuples, bools, numbers)
    attrs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
    out = None
    if num_out_provided:
        out = outputs if len(outputs) > 1 else outputs[0]
    res = _nd_invoke(name, list(inputs), attrs, out)
    if isinstance(res, (list, tuple)):
        return list(res)
    return [res]


# ------------------------------------------------------------ autograd --

def autograd_set_recording(flag):
    prev = _autograd.is_recording()
    _autograd.set_recording(bool(flag))
    return int(prev)


def autograd_set_training(flag):
    prev = _autograd.is_training()
    _autograd.set_training(bool(flag))
    return int(prev)


def autograd_is_recording():
    return int(_autograd.is_recording())


def autograd_is_training():
    return int(_autograd.is_training())


def autograd_mark_variables(arrays, grad_reqs, grads):
    # OpReqType codes: 0=null, 1=write, 2=inplace, 3=add (ndarray.h)
    req_map = {0: 'null', 1: 'write', 2: 'write', 3: 'add'}
    for arr, req, grad in zip(arrays, grad_reqs, grads):
        req_name = req_map.get(int(req), 'write')
        if grad is not None:
            # bind the caller's buffer: backward rebinds grad._data in
            # place, so the C handle observes the gradients directly
            _autograd.mark_variables([arr], [grad], req_name)
        else:
            arr.attach_grad(grad_req=req_name)
    return 0


def autograd_backward(outputs, head_grads, retain_graph, train_mode):
    _autograd.backward(list(outputs),
                       head_grads=None if not head_grads else list(head_grads),
                       retain_graph=bool(retain_graph),
                       train_mode=bool(train_mode))
    return 0


# ------------------------------------------------------------- symbols --

class _AtomicSymbol:
    """An op + attrs awaiting composition (MXSymbolCreateAtomicSymbol
    result before MXSymbolCompose — reference nnvm Symbol::CreateFunctor)."""

    __slots__ = ('op', 'attrs')

    def __init__(self, op, attrs):
        self.op = op
        self.attrs = attrs


def symbol_create_atomic(op_name, keys, vals):
    if not _op_reg.exists(op_name):
        raise ValueError('unknown operator %s' % op_name)
    return _AtomicSymbol(op_name,
                         {k: _parse_attr(v) for k, v in zip(keys, vals)})


# MXSymbolCompose mutates in place in the reference (nnvm symbols are
# mutable); ours are immutable, so composed results live here, keyed by
# handle id, purged by symbol_free (called from MXSymbolFree).
_COMPOSED = {}


def symbol_compose(handle, name, keys, args):
    """Compose an atomic symbol with its inputs → real Symbol."""
    if isinstance(handle, _AtomicSymbol):
        attrs = dict(handle.attrs)
        if name:
            attrs['name'] = name
        if keys:
            # keyword symbol args map onto the op's declared input names,
            # in declaration order; leftovers are attrs
            op = _op_reg.get(handle.op)
            kw = {k: _as_symbol(a) for k, a in zip(keys, args)}
            inputs = [kw.pop(n) for n in op.input_names if n in kw]
            attrs.update(kw)
            return _invoke_sym(handle.op, inputs, attrs)
        return _invoke_sym(handle.op, [_as_symbol(a) for a in args], attrs)
    sym = _as_symbol(handle)
    if keys:
        return sym(**{k: _as_symbol(a) for k, a in zip(keys, args)})
    return sym(*[_as_symbol(a) for a in args])


def symbol_compose_inplace(handle, name, keys, args):
    _COMPOSED[id(handle)] = symbol_compose(handle, name, keys, args)
    return 0


def symbol_free(handle):
    _COMPOSED.pop(id(handle), None)
    return 0


def _as_symbol(handle):
    composed = _COMPOSED.get(id(handle))
    if composed is not None:
        return composed
    if isinstance(handle, _AtomicSymbol):
        return _invoke_sym(handle.op, [], dict(handle.attrs))
    return handle


def symbol_create_variable(name):
    return _sym_var(name)


def symbol_create_group(handles):
    from .symbol import Group
    return Group([_as_symbol(h) for h in handles])


def symbol_from_json(json_str):
    return _sym_load_json(json_str)


def symbol_from_file(fname):
    from .symbol import load as _sym_load
    return _sym_load(fname)


def symbol_to_json(handle):
    return _as_symbol(handle).tojson()


def symbol_save_file(handle, fname):
    _as_symbol(handle).save(fname)
    return 0


def symbol_copy(handle):
    import copy
    return copy.copy(_as_symbol(handle))


def symbol_print(handle):
    return repr(_as_symbol(handle))


def symbol_get_name(handle):
    name = _as_symbol(handle).name
    return name if name is not None else ''


def symbol_get_attr(handle, key):
    v = _as_symbol(handle).attr(key)
    return v if v is not None else None


def symbol_set_attr(handle, key, value):
    _as_symbol(handle)._set_attr(**{key: value})
    return 0


def symbol_list_attr(handle):
    d = _as_symbol(handle).attr_dict()
    flat = []
    for node_name, attrs in d.items():
        for k, v in attrs.items():
            flat.append('%s$%s' % (node_name, k))
            flat.append(str(v))
    return flat


def symbol_list_arguments(handle):
    return _as_symbol(handle).list_arguments()


def symbol_list_outputs(handle):
    return _as_symbol(handle).list_outputs()


def symbol_list_aux(handle):
    return _as_symbol(handle).list_auxiliary_states()


def symbol_get_internals(handle):
    return _as_symbol(handle).get_internals()


def symbol_get_children(handle):
    return _as_symbol(handle).get_children()


def symbol_get_output(handle, index):
    return _as_symbol(handle)[int(index)]


def symbol_grad(handle, wrt):
    return _as_symbol(handle).gradient(list(wrt))


def _shape_kwargs(keys, arg_ind, arg_data):
    kwargs = {}
    for i, k in enumerate(keys):
        kwargs[k] = tuple(arg_data[arg_ind[i]:arg_ind[i + 1]])
    return kwargs


def symbol_infer_shape(handle, keys, arg_ind, arg_data, partial):
    sym = _as_symbol(handle)
    kwargs = _shape_kwargs(keys, arg_ind, arg_data)
    if partial:
        arg_shapes, out_shapes, aux_shapes = sym.infer_shape_partial(**kwargs)
    else:
        arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**kwargs)
    def pack(shapes):
        return [tuple(int(d) for d in s) if s is not None else ()
                for s in (shapes or [])]
    return pack(arg_shapes), pack(out_shapes), pack(aux_shapes)


def symbol_infer_type(handle, keys, dtype_codes):
    sym = _as_symbol(handle)
    kwargs = {k: _CODE_TO_DTYPE[int(c)] for k, c in zip(keys, dtype_codes)}
    arg_t, out_t, aux_t = sym.infer_type(**kwargs)
    def pack(ts):
        return [_DTYPE_TO_CODE.get(str(np.dtype(t).name) if t is not None
                                   else '', -1) if t is not None else -1
                for t in (ts or [])]
    return pack(arg_t), pack(out_t), pack(aux_t)


# ----------------------------------------------------------- executors --

def executor_bind(sym_handle, dev_type, dev_id, args, arg_grads, grad_reqs,
                  aux_states):
    sym = _as_symbol(sym_handle)
    ctx = _ctx(dev_type, dev_id)
    req_names = {0: 'null', 1: 'write', 3: 'add'}
    arg_names = sym.list_arguments()
    args_map = dict(zip(arg_names, args))
    grads_map = {n: g for n, g in zip(arg_names, arg_grads or [])
                 if g is not None}
    reqs = {n: req_names.get(int(r), 'write')
            for n, r in zip(arg_names, grad_reqs or [])} or 'write'
    aux_map = dict(zip(sym.list_auxiliary_states(), aux_states or []))
    return sym.bind(ctx, args_map, args_grad=grads_map or None,
                    grad_req=reqs, aux_states=aux_map or None)


def executor_forward(handle, is_train):
    handle.forward(is_train=bool(is_train))
    return 0


def executor_backward(handle, out_grads):
    handle.backward(out_grads=list(out_grads) if out_grads else None)
    return 0


def executor_outputs(handle):
    return list(handle.outputs)


def executor_print(handle):
    return repr(handle)


# ------------------------------------------------------------ cachedop --

class _CachedOp:
    """MXCreateCachedOp: a symbol specialized for repeated imperative calls
    (reference src/imperative/cached_op.cc). Here: bind-once + jit reuse
    keyed on input shapes, via Symbol.eval machinery."""

    def __init__(self, sym):
        self.sym = _as_symbol(sym)
        self._cache = {}

    def __call__(self, inputs):
        names = self.sym.list_arguments()
        key = tuple((a.shape, str(a.dtype)) for a in inputs)
        ex = self._cache.get(key)
        if ex is None:
            ctx = inputs[0].context if inputs else Context('cpu', 0)
            ex = self.sym.bind(ctx, dict(zip(names, inputs)),
                               grad_req='null')
            self._cache[key] = ex
        else:
            ex.copy_params_from(dict(zip(names, inputs)),
                                allow_extra_params=True)
        ex.forward(is_train=False)
        return list(ex.outputs)


def cached_op_create(sym_handle):
    return _CachedOp(sym_handle)


def cached_op_invoke(handle, inputs):
    return handle(list(inputs))


# ------------------------------------------------------------- kvstore --

def kv_create(type_name):
    return _kvstore_mod.create(type_name)


def kv_init(handle, keys, values):
    handle.init(list(keys), list(values))
    return 0


def kv_push(handle, keys, values, priority):
    handle.push(list(keys), list(values), priority=int(priority))
    return 0


def kv_pull(handle, keys, outs, priority):
    handle.pull(list(keys), out=list(outs), priority=int(priority))
    return 0


def kv_type(handle):
    return handle.type


def kv_rank(handle):
    return handle.rank


def kv_group_size(handle):
    return handle.num_workers


def kv_barrier(handle):
    if hasattr(handle, '_barrier'):
        handle._barrier()
    return 0


def kv_num_dead_node(handle, node_id):
    if hasattr(handle, 'num_dead_node'):
        return handle.num_dead_node(int(node_id))
    return 0


def kv_run_server(handle):
    """MXKVStoreRunServer — blocks in the server role loop."""
    from . import kvstore_server
    kvstore_server.run_server()
    return 0


def kv_send_command(handle, cmd_id, cmd_body):
    if hasattr(handle, '_send_command_to_servers'):
        handle._send_command_to_servers(int(cmd_id), cmd_body)
    return 0


# ------------------------------------------------------------- dataio --

_ITER_CLASSES = None


def _iter_classes():
    global _ITER_CLASSES
    if _ITER_CLASSES is None:
        from . import io as _io
        _ITER_CLASSES = {
            'MNISTIter': _io.MNISTIter,
            'CSVIter': _io.CSVIter,
            'ImageRecordIter': _io.ImageRecordIter,
            'ImageDetRecordIter': _io.ImageDetRecordIter,
            'LibSVMIter': _io.LibSVMIter,
        }
    return _ITER_CLASSES


def list_data_iters():
    return sorted(_iter_classes().keys())


def data_iter_create(name, keys, vals):
    cls = _iter_classes()[name]
    kwargs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
    return iter(cls(**kwargs))


class _IterState:
    __slots__ = ('it', 'batch')

    def __init__(self, it):
        self.it = it
        self.batch = None


def iter_state_new(it):
    return _IterState(it)


def data_iter_next(handle):
    try:
        handle.batch = next(handle.it)
        return 1
    except StopIteration:
        return 0


def data_iter_before_first(handle):
    handle.it.reset()
    return 0


def data_iter_get_data(handle):
    return handle.batch.data[0]


def data_iter_get_label(handle):
    return handle.batch.label[0]


def data_iter_get_pad(handle):
    return int(handle.batch.pad or 0)


# ------------------------------------------------------------- predict --

class _Predictor:
    """MXPredCreate state (reference src/c_api/c_predict_api.cc:57-177):
    symbol json + param blob → bound inference executor."""

    def __init__(self, symbol_json, param_bytes, dev_type, dev_id,
                 input_keys, input_shapes, output_keys=None):
        import io as _pyio
        sym = _sym_load_json(symbol_json)
        if output_keys:
            outs = sym.list_outputs()
            picked = []
            for k in output_keys:
                name = k if k.endswith('_output') else k + '_output'
                idx = outs.index(name) if name in outs else outs.index(k)
                picked.append(sym[idx])
            from .symbol import Group
            sym = Group(picked) if len(picked) > 1 else picked[0]
        self.sym = sym
        # param blob: NDArray save format (arg:/aux: prefixed dict)
        params = {}
        if param_bytes:
            import tempfile, os
            with tempfile.NamedTemporaryFile(delete=False) as f:
                f.write(param_bytes)
                tmp = f.name
            try:
                loaded = _nd_utils.load(tmp)
            finally:
                os.unlink(tmp)
            for k, v in (loaded.items() if isinstance(loaded, dict) else []):
                params[k.split(':', 1)[-1]] = v
        ctx = _ctx(dev_type, dev_id)
        shapes = dict(zip(input_keys, [tuple(s) for s in input_shapes]))
        arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**shapes)
        # static output shapes: lets MXPredGetOutputShape size buffers
        # without forcing a forward (esp. mid partial_forward pass)
        self._out_shapes = [tuple(int(d) for d in s) for s in out_shapes]
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        self.input_keys = list(input_keys)
        args = {}
        for name, shp in zip(arg_names, arg_shapes):
            if name in params:
                args[name] = params[name].as_in_context(ctx)
            else:
                args[name] = _nd_mod.zeros(shp, ctx=ctx)
        aux = {}
        for name, shp in zip(aux_names, aux_shapes or []):
            if name in params:
                aux[name] = params[name].as_in_context(ctx)
            else:
                aux[name] = _nd_mod.zeros(shp, ctx=ctx)
        self.executor = sym.bind(ctx, args, grad_req='null',
                                 aux_states=aux or None)
        self.args = args

    def set_input(self, key, buf, shape):
        arr = np.frombuffer(buf, dtype=np.float32).reshape(shape)
        self.args[key][:] = arr
        return 0

    def forward(self):
        self.executor.forward(is_train=False)
        return 0

    def partial_forward(self, step):
        """MXPredPartialForward (reference include/mxnet/c_predict_api.h:169):
        one operator per call for progress display; returns steps left."""
        return self.executor.partial_forward(False, int(step))

    def get_output_shape(self, index):
        return self._out_shapes[int(index)]

    def get_output(self, index):
        out = self.executor.outputs[int(index)]
        npy = out.asnumpy()
        if npy.dtype != np.float32:
            npy = npy.astype(np.float32)
        return npy.tobytes()


def pred_create(symbol_json, param_bytes, dev_type, dev_id, input_keys,
                input_shapes, output_keys=None):
    return _Predictor(symbol_json, param_bytes, dev_type, dev_id,
                      input_keys, input_shapes, output_keys)


def nd_list_create(buf):
    """MXNDListCreate: load an NDArray-save blob → (keys, arrays)."""
    import tempfile, os
    with tempfile.NamedTemporaryFile(delete=False) as f:
        f.write(buf)
        tmp = f.name
    try:
        loaded = _nd_utils.load(tmp)
    finally:
        os.unlink(tmp)
    if isinstance(loaded, dict):
        keys = list(loaded.keys())
        return keys, [loaded[k] for k in keys]
    return [''] * len(loaded), list(loaded)


def nd_list_get(keys, arrays, index):
    i = int(index)
    arr = arrays[i]
    npy = arr.asnumpy().astype(np.float32)
    return keys[i], npy.tobytes(), tuple(int(d) for d in npy.shape)


# ---------------------------------------------------------------------------
# Round-3 additions: the 38 remaining reference entry points
# (reference include/mxnet/c_api.h; closes the C ABI to 146/146).
# ---------------------------------------------------------------------------

def _ctypes():
    import ctypes
    return ctypes


def _handle_ptr(obj):
    """The PyObject* of obj as an integer — what the C caller sees as an
    NDArrayHandle. The caller of the C callback must keep obj alive for
    the duration of the call (we do, via locals)."""
    return id(obj)


# -- imperative/cachedop Ex variants (storage types out) --

def nd_stype_code(arr):
    from .ndarray import sparse as _sp
    if isinstance(arr, _sp.RowSparseNDArray):
        return 1
    if isinstance(arr, _sp.CSRNDArray):
        return 2
    return 0


def imperative_invoke_ex(name, inputs, keys, vals, num_out_provided, outputs):
    outs = imperative_invoke(name, inputs, keys, vals, num_out_provided,
                             outputs)
    return outs, [nd_stype_code(o) for o in outs]


def cached_op_invoke_ex(handle, inputs):
    outs = handle(list(inputs))
    return outs, [nd_stype_code(o) for o in outs]


# -- sparse creation + accessors --

def nd_create_sparse(storage_type, shape, dev_type, dev_id, dtype_code,
                     aux_types, aux_shapes):
    from .ndarray import sparse as _sp
    from .ndarray import zeros as _zeros
    ctx = _ctx(dev_type, dev_id)
    dtype = _CODE_TO_DTYPE.get(int(dtype_code), 'float32')
    shape = tuple(int(d) for d in shape)
    if int(storage_type) == 1:      # row_sparse: aux [indices]
        nrows = int(aux_shapes[0][0]) if aux_shapes and aux_shapes[0] else 0
        return _sp.RowSparseNDArray(
            _zeros((nrows,) + shape[1:], dtype=dtype),
            _zeros((nrows,), dtype='int64'), shape, ctx=ctx)
    if int(storage_type) == 2:      # csr: aux [indptr, indices]
        nnz = int(aux_shapes[1][0]) if len(aux_shapes) > 1 and aux_shapes[1] else 0
        return _sp.CSRNDArray(
            _zeros((nnz,), dtype=dtype),
            _zeros((shape[0] + 1,), dtype='int64'),
            _zeros((nnz,), dtype='int64'), shape, ctx=ctx)
    return _zeros(shape, dtype=dtype, ctx=ctx)


def _aux_arrays(arr):
    from .ndarray import sparse as _sp
    if isinstance(arr, _sp.RowSparseNDArray):
        return [arr.indices]
    if isinstance(arr, _sp.CSRNDArray):
        return [arr.indptr, arr.indices]
    raise TypeError('dense NDArray has no aux arrays')


def nd_aux_type(handle, i):
    aux = _aux_arrays(handle)[int(i)]
    return _DTYPE_TO_CODE.get(str(aux.dtype), 6)


def nd_get_aux(handle, i):
    return _aux_arrays(handle)[int(i)]


def nd_get_data(handle):
    return handle.data


def nd_grad_state(handle):
    return 1 if getattr(handle, '_fresh_grad', False) else 0


def nd_set_grad_state(handle, state):
    handle._fresh_grad = bool(state)
    return 0


def nd_sync_copy_from_ndarray(dst, src, i):
    from .ndarray import sparse as _sp
    if int(i) >= 0:
        src = _aux_arrays(src)[int(i)]
    elif isinstance(src, _sp.BaseSparseNDArray):
        src = src.data
    dst[:] = src.astype(dst.dtype) if str(src.dtype) != str(dst.dtype) else src
    return 0


# -- autograd extras --

def autograd_get_symbol(handle):
    """Export the recorded imperative history of `handle` as a Symbol
    (reference MXAutogradGetSymbol / nnvm graph behind the tape).
    Leaves and unrecorded inputs become Variables."""
    from .symbol import Variable
    node = handle._node
    if node is None:
        name = 'var0'
        return Variable(name)
    memo = {}
    counter = [0]

    def build(entry):
        src, idx = entry
        if src is None or not hasattr(src, 'op_info') or \
                getattr(src, 'op_info', None) is None:
            key = id(src) if src is not None else ('anon', counter[0])
            if key not in memo:
                memo[key] = Variable('var%d' % counter[0])
                counter[0] += 1
            return memo[key]
        if id(src) in memo:
            sym = memo[id(src)]
        else:
            op_name, attrs = src.op_info
            parents = [build(p) for p in src.parents[:src.n_grad_inputs]]
            attrs = {k: v for k, v in attrs.items()
                     if not k.startswith('__')}
            sym = _invoke_sym(op_name, parents, attrs)
            memo[id(src)] = sym
        if src.n_outputs > 1:
            return sym[idx]
        return sym
    return build((node, handle._out_idx))


class _CCustomFunction:
    """MXCustomFunctionRecord: a python-side Function whose backward calls
    the C callback list (kCustomFunctionBackward)."""

    def __init__(self, callbacks_ptr, n_in, n_out):
        ct = _ctypes()
        self._cb = callbacks_ptr      # (fnptr_int, ctx_int) list
        self.n_in, self.n_out = int(n_in), int(n_out)
        fnptr, ctx = callbacks_ptr[0]
        proto = ct.CFUNCTYPE(ct.c_int, ct.c_int, ct.c_int,
                             ct.POINTER(ct.c_void_p), ct.POINTER(ct.c_int),
                             ct.c_int, ct.c_void_p)
        self._bwd = proto(fnptr) if fnptr else None
        self._bwd_ctx = ctx

    def backward_arrays(self, ograds):
        """Run the C backward: ograds (NDArrays) -> igrads (NDArrays)."""
        ct = _ctypes()
        from .ndarray import zeros as _zeros
        igrads = [_zeros(s) for s in self._igrad_shapes]
        all_arrays = list(ograds) + igrads
        n = len(all_arrays)
        ptrs = (ct.c_void_p * n)(*[_handle_ptr(a) for a in all_arrays])
        reqs = (ct.c_int * len(igrads))(*([1] * len(igrads)))
        rc = self._bwd(len(ograds), len(igrads), ptrs, reqs, 1,
                       ct.c_void_p(self._bwd_ctx))
        if rc == 0:
            raise RuntimeError('CustomFunction backward callback failed')
        return igrads


def custom_function_record(inputs, outputs, callbacks):
    """Attach a C-callback backward to the tape edge inputs->outputs."""
    from . import autograd as _ag
    import jax.numpy as jnp
    fn = _CCustomFunction(callbacks, len(inputs), len(outputs))
    fn._igrad_shapes = [tuple(a.shape) for a in inputs]

    def vjp_fn(cotangents):
        if not isinstance(cotangents, (tuple, list)):
            cotangents = (cotangents,)
        ograds = [NDArray(jnp.asarray(g)) for g in cotangents]
        igrads = fn.backward_arrays(ograds)
        return tuple(g._data for g in igrads)

    parents = []
    for a in inputs:
        if a._node is not None:
            parents.append((a._node, a._out_idx))
        elif a._leaf is not None:
            parents.append((a._leaf, 0))
        else:
            parents.append((None, 0))
    node = _ag.record_op(vjp_fn, parents, len(outputs), len(inputs),
                         op_info=('_CustomFunction', {}))
    node.head_ids = [(tuple(o.shape), o.dtype) for o in outputs]
    for i, o in enumerate(outputs):
        o._node = node
        o._out_idx = i
    return 0


# -- legacy NDArray-function registry (MXFunc*) --

class _LegacyFunction:
    __slots__ = ('name', 'op')

    def __init__(self, name):
        self.name = name
        self.op = _op_reg.get(name)


_FUNC_CACHE = {}


def list_functions():
    return [get_function(n) for n in _op_reg.list_ops()]


def get_function(name):
    f = _FUNC_CACHE.get(name)
    if f is None:
        f = _FUNC_CACHE[name] = _LegacyFunction(name)
    return f


def func_describe(fun):
    n_in = 0 if fun.op.variadic else len(fun.op.input_names)
    n_out = fun.op.num_outputs if isinstance(fun.op.num_outputs, int) else 1
    return n_in, 0, n_out, 0


def func_get_info(fun):
    op = fun.op
    args = list(op.param_defaults)
    return (fun.name, op.doc or '', args, ['string'] * len(args),
            [''] * len(args), 'NDArray')


def func_invoke(fun, use_vars, scalars, mutate_vars, keys, vals):
    attrs = {k: _parse_attr(v) for k, v in zip(keys, vals)}
    outs = mutate_vars if mutate_vars else None
    out = outs if outs and len(outs) > 1 else (outs[0] if outs else None)
    res = _nd_invoke(fun.name, list(use_vars), attrs, out)
    return 0


# -- kvstore Ex / row_sparse / updater --

def kv_init_ex(handle, keys, values):
    handle.init(list(keys), list(values))
    return 0


def kv_push_ex(handle, keys, values, priority):
    handle.push(list(keys), list(values), priority=int(priority))
    return 0


def kv_pull_ex(handle, keys, outs, priority):
    handle.pull(list(keys), out=list(outs), priority=int(priority))
    return 0


def kv_pull_row_sparse(handle, keys, outs, row_ids, priority):
    handle.row_sparse_pull(list(keys), out=list(outs),
                           priority=int(priority), row_ids=list(row_ids))
    return 0


def kv_set_barrier_before_exit(handle, flag):
    if hasattr(handle, 'set_barrier_before_exit'):
        handle.set_barrier_before_exit(bool(flag))
    return 0


def kv_set_updater(handle, fnptr, str_fnptr, ctx_ptr):
    """MXKVStoreSetUpdater(Ex): wrap the C function pointer in a python
    updater. NDArray handles passed to C are live PyObject pointers kept
    alive for the call duration."""
    ct = _ctypes()
    int_proto = ct.CFUNCTYPE(None, ct.c_int, ct.c_void_p, ct.c_void_p,
                             ct.c_void_p)
    str_proto = ct.CFUNCTYPE(None, ct.c_char_p, ct.c_void_p, ct.c_void_p,
                             ct.c_void_p)
    c_int_fn = int_proto(fnptr) if fnptr else None
    c_str_fn = str_proto(str_fnptr) if str_fnptr else None

    def updater(key, recv, local):
        if isinstance(key, str) and not key.isdigit():
            if c_str_fn is None:
                raise RuntimeError(
                    'string key %r needs MXKVStoreSetUpdaterEx with a '
                    'str_updater (reference kvstore.cc semantics)' % key)
            c_str_fn(key.encode(), _handle_ptr(recv), _handle_ptr(local),
                     ct.c_void_p(ctx_ptr))
        elif c_int_fn is not None:
            c_int_fn(int(key), _handle_ptr(recv), _handle_ptr(local),
                     ct.c_void_p(ctx_ptr))
        elif c_str_fn is not None:
            c_str_fn(str(key).encode(), _handle_ptr(recv),
                     _handle_ptr(local), ct.c_void_p(ctx_ptr))
    handle.set_updater(updater)
    return 0


def init_ps_env(keys, vals):
    import os as _os
    for k, v in zip(keys, vals):
        _os.environ[str(k)] = str(v)
    return 0


# -- executor extras --

def executor_backward_ex(handle, out_grads, is_train):
    handle.backward(out_grads=list(out_grads) if out_grads else None)
    return 0


def executor_bind_x(sym_handle, dev_type, dev_id, map_keys, map_dev_types,
                    map_dev_ids, args, arg_grads, grad_reqs, aux_states):
    sym = _as_symbol(sym_handle)
    ctx = _ctx(dev_type, dev_id)
    g2c = {k: _ctx(t, i) for k, t, i in
           zip(map_keys, map_dev_types, map_dev_ids)}
    req_names = {0: 'null', 1: 'write', 3: 'add'}
    arg_names = sym.list_arguments()
    args_map = dict(zip(arg_names, args))
    grads_map = {n: g for n, g in zip(arg_names, arg_grads or [])
                 if g is not None}
    reqs = {n: req_names.get(int(r), 'write')
            for n, r in zip(arg_names, grad_reqs or [])} or 'write'
    aux_map = dict(zip(sym.list_auxiliary_states(), aux_states or []))
    from .executor import Executor
    return Executor(sym, ctx, args_map, args_grad=grads_map or None,
                    grad_req=reqs, aux_states=aux_map or None,
                    group2ctx=g2c or None)


def executor_simple_bind(sym_handle, dev_type, dev_id, g2c_keys,
                         g2c_dev_types, g2c_dev_ids, grad_req_names,
                         grad_req_types, shape_names, shapes, dtype_names,
                         dtypes, stype_names, stypes,
                         shared_buffer_names, shared_buffer_arrays):
    """MXExecutorSimpleBind: allocate arg/grad/aux arrays from hints.
    Returns (executor, arg_names, in_args, arg_grads(list w/ None),
    aux_names, aux_states, updated_buffer_names, updated_buffer_arrays)."""
    sym = _as_symbol(sym_handle)
    ctx = _ctx(dev_type, dev_id)
    kwargs = {}
    for n, s in zip(shape_names, shapes):
        kwargs[n] = tuple(int(d) for d in s)
    grad_req = 'write'
    named = [(n, t) for n, t in zip(grad_req_names, grad_req_types) if n]
    if named:
        grad_req = dict(named)
    elif grad_req_types:
        grad_req = grad_req_types[0]
    type_dict = {n: _CODE_TO_DTYPE.get(int(t), 'float32')
                 for n, t in zip(dtype_names, dtypes)} or None
    g2c = {k: _ctx(t, i) for k, t, i in
           zip(g2c_keys, g2c_dev_types, g2c_dev_ids)}
    shared = dict(zip(shared_buffer_names or [],
                      shared_buffer_arrays or []))
    ex = sym.simple_bind(ctx, grad_req=grad_req, type_dict=type_dict,
                         group2ctx=g2c or None, **kwargs)
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    in_args = [ex.arg_dict[n] for n in arg_names]
    arg_grads = [ex.grad_dict.get(n) for n in arg_names]
    aux_states = [ex.aux_dict[n] for n in aux_names]
    # updated shared buffer: existing entries plus this bind's args
    # (memory identity is an XLA concern here; values are what matter)
    for n in arg_names:
        shared.setdefault(n, ex.arg_dict[n])
    upd_names = list(shared.keys())
    upd_arrays = [shared[n] for n in upd_names]
    return (ex, arg_names, in_args, arg_grads, aux_names, aux_states,
            upd_names, upd_arrays)


def executor_set_monitor_callback(handle, fnptr, ctx_ptr):
    ct = _ctypes()
    proto = ct.CFUNCTYPE(None, ct.c_char_p, ct.c_void_p, ct.c_void_p)
    c_fn = proto(fnptr)

    def monitor(name, arr):
        c_fn(str(name).encode(), _handle_ptr(arr), ct.c_void_p(ctx_ptr))
    handle.set_monitor_callback(monitor)
    return 0


# -- data iter index --

def data_iter_get_index(handle):
    batch = handle.batch
    idx = getattr(batch, 'index', None)
    if idx is None:
        n = int(batch.data[0].shape[0]) if batch.data else 0
        idx = np.arange(n, dtype=np.uint64)
    return np.asarray(idx, dtype=np.uint64).tobytes()


# -- custom op registration from C (MXCustomOpRegister) --

_C_CUSTOM_CREATORS = {}


def custom_op_register(op_type, creator_ptr):
    """Register a C CustomOpPropCreator under op_type. A python
    CustomOpProp proxy calls the C callback list for list_arguments/
    list_outputs/infer_shape/create_operator (+forward/backward),
    mirroring the reference's CustomOpProp-over-MXCallbackList protocol
    (src/operator/custom/custom.cc)."""
    ct = _ctypes()
    from . import operator as _op_mod

    creator_proto = ct.CFUNCTYPE(
        ct.c_int, ct.c_char_p, ct.c_int, ct.POINTER(ct.c_char_p),
        ct.POINTER(ct.c_char_p), ct.c_void_p)
    creator = creator_proto(creator_ptr)
    _C_CUSTOM_CREATORS[op_type] = creator

    class _CallbackList(ct.Structure):
        _fields_ = [('num_callbacks', ct.c_int),
                    ('callbacks', ct.POINTER(ct.CFUNCTYPE(ct.c_int))),
                    ('contexts', ct.POINTER(ct.c_void_p))]

    list_proto = ct.CFUNCTYPE(ct.c_int, ct.POINTER(ct.POINTER(ct.c_char_p)),
                              ct.c_void_p)
    shape_proto = ct.CFUNCTYPE(ct.c_int, ct.c_int, ct.POINTER(ct.c_int),
                               ct.POINTER(ct.POINTER(ct.c_uint)), ct.c_void_p)
    create_proto = ct.CFUNCTYPE(ct.c_int, ct.c_char_p, ct.c_int,
                                ct.POINTER(ct.POINTER(ct.c_uint)),
                                ct.POINTER(ct.c_int), ct.POINTER(ct.c_int),
                                ct.c_void_p, ct.c_void_p)
    fb_proto = ct.CFUNCTYPE(ct.c_int, ct.c_int, ct.POINTER(ct.c_void_p),
                            ct.POINTER(ct.c_int), ct.POINTER(ct.c_int),
                            ct.c_int, ct.c_void_p)

    def _read_strlist(fn_addr, context):
        fn = list_proto(fn_addr)
        arr = ct.POINTER(ct.c_char_p)()
        if not fn(ct.byref(arr), context):
            raise RuntimeError('%s: C list callback failed' % op_type)
        out, i = [], 0
        while arr[i]:
            out.append(arr[i].decode())
            i += 1
        return out

    class CProp(_op_mod.CustomOpProp):
        def __init__(self, **kwargs):
            super().__init__(need_top_grad=True)
            keys = [k.encode() for k in kwargs]
            vals = [str(v).encode() for v in kwargs.values()]
            karr = (ct.c_char_p * len(keys))(*keys)
            varr = (ct.c_char_p * len(vals))(*vals)
            cblist = _CallbackList()
            if not creator(op_type.encode(), len(keys), karr, varr,
                           ct.cast(ct.byref(cblist), ct.c_void_p)):
                raise RuntimeError('CustomOpPropCreator for %r failed'
                                   % op_type)
            # order: CustomOpPropCallbacks enum (c_api.h:137-146)
            self._cbs = [(ct.cast(cblist.callbacks[i], ct.c_void_p).value,
                          cblist.contexts[i])
                         for i in range(cblist.num_callbacks)]

        def _cb(self, idx):
            fnptr, context = self._cbs[idx]
            return fnptr, context

        def list_arguments(self):
            fnptr, context = self._cb(1)
            return _read_strlist(fnptr, context)

        def list_outputs(self):
            fnptr, context = self._cb(2)
            return _read_strlist(fnptr, context)

        def list_auxiliary_states(self):
            if len(self._cbs) > 3 and self._cbs[3][0]:
                return _read_strlist(*self._cb(3))
            return []

        def infer_shape(self, in_shape):
            fnptr, context = self._cb(4)
            fn = shape_proto(fnptr)
            n_in = len(self.list_arguments())
            n_out = len(self.list_outputs())
            n_aux = len(self.list_auxiliary_states())
            # total includes aux states (reference custom.cc:109)
            n = n_in + n_out + n_aux
            # protocol: in entries filled by caller, callback fills rest
            ndims = (ct.c_int * n)()
            shape_ptrs = (ct.POINTER(ct.c_uint) * n)()
            keep = []
            for i, s in enumerate(in_shape):
                ndims[i] = len(s)
                buf = (ct.c_uint * max(1, len(s)))(*[int(d) for d in s])
                keep.append(buf)
                shape_ptrs[i] = ct.cast(buf, ct.POINTER(ct.c_uint))
            if not fn(n, ndims, shape_ptrs, context):
                raise RuntimeError('%s: infer_shape callback failed'
                                   % op_type)
            shapes = []
            for i in range(n):
                shapes.append(tuple(int(shape_ptrs[i][j])
                                    for j in range(ndims[i])))
            return (shapes[:n_in], shapes[n_in:n_in + n_out],
                    shapes[n_in + n_out:])

        def create_operator(self, ctx_str, in_shapes, in_dtypes):
            fnptr, context = self._cb(6)
            fn = create_proto(fnptr)
            n = len(in_shapes)
            ndims = (ct.c_int * n)(*[len(s) for s in in_shapes])
            keep = []
            shape_ptrs = (ct.POINTER(ct.c_uint) * n)()
            for i, s in enumerate(in_shapes):
                buf = (ct.c_uint * max(1, len(s)))(*[int(d) for d in s])
                keep.append(buf)
                shape_ptrs[i] = ct.cast(buf, ct.POINTER(ct.c_uint))
            dts = (ct.c_int * n)(*[_DTYPE_TO_CODE.get(str(t), 0)
                                   for t in in_dtypes])
            op_cblist = _CallbackList()
            if not fn(b'cpu', n, shape_ptrs, ndims, dts,
                      ct.cast(ct.byref(op_cblist), ct.c_void_p), context):
                raise RuntimeError('%s: create_operator callback failed'
                                   % op_type)
            op_cbs = [(ct.cast(op_cblist.callbacks[i], ct.c_void_p).value,
                       op_cblist.contexts[i])
                      for i in range(op_cblist.num_callbacks)]
            prop = self

            class COp(_op_mod.CustomOp):
                def _run_fb(self, idx, arrays_tagged, is_train):
                    fnptr2, context2 = op_cbs[idx]
                    fn2 = fb_proto(fnptr2)
                    n2 = len(arrays_tagged)
                    ptrs = (ct.c_void_p * n2)(
                        *[_handle_ptr(a) for a, _ in arrays_tagged])
                    tags = (ct.c_int * n2)(*[t for _, t in arrays_tagged])
                    reqs = (ct.c_int * n2)(*([1] * n2))
                    if not fn2(n2, ptrs, tags, reqs, int(is_train),
                               context2):
                        raise RuntimeError('%s: forward/backward callback '
                                           'failed' % op_type)

                def forward(self, is_train, req, in_data, out_data, aux):
                    tagged = [(a, 0) for a in in_data] + \
                             [(a, 1) for a in out_data] + \
                             [(a, 4) for a in aux]
                    self._run_fb(1, tagged, is_train)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    tagged = [(a, 3) for a in out_grad] + \
                             [(a, 0) for a in in_data] + \
                             [(a, 1) for a in out_data] + \
                             [(a, 2) for a in in_grad] + \
                             [(a, 4) for a in aux]
                    self._run_fb(2, tagged, is_train=True)
            return COp()

    CProp.__name__ = 'CProp_%s' % op_type
    _op_mod.register(op_type)(CProp)
    return 0


# -- rtc --

def rtc_create(name, input_names, output_names, inputs, outputs, kernel):
    from . import rtc as _rtc
    ins = list(zip(input_names, inputs))
    outs = list(zip(output_names, outputs))
    return _rtc.Rtc(name, ins, outs, kernel)


def rtc_push(handle, inputs, outputs):
    handle.push(list(inputs), list(outputs))
    return 0


# -- symbol shallow attrs --

def symbol_list_attr_shallow(handle):
    sym = _as_symbol(handle)
    flat = []
    for node, _idx in sym._outputs:
        for k, v in node.attr_dict.items():
            flat.append(str(k))
            flat.append(str(v))
    return flat
