"""Sparse NDArray storage types — row_sparse and csr.

Reference: python/mxnet/ndarray/sparse.py (RowSparseNDArray:780,
CSRNDArray:998) + include/mxnet/ndarray.h:82-87 (kRowSparseStorage,
kCSRStorage, aux tensors).

TPU-native stance (SURVEY.md §7 hard-part 4): XLA has no native sparse
tensors, so these are *structured dense* containers — data + index aux
arrays, exactly the reference's aux-tensor layout — with gather/scatter
lowerings for the ops that matter (dot(csr, dense), sparse_retain,
row-sparse update in optimizers/kvstore) and explicit densification
(`tostype('default')`) elsewhere.
"""
import numpy as np

import jax.numpy as jnp

from ..context import current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ['RowSparseNDArray', 'CSRNDArray', 'row_sparse_array', 'csr_matrix',
           'BaseSparseNDArray']


class BaseSparseNDArray:
    def __init__(self, shape, ctx=None, dtype='float32'):
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()
        self._dtype = np.dtype(dtype) if dtype != 'bfloat16' else dtype

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def context(self):
        return self._ctx

    ctx = context

    def asnumpy(self):
        return self.tostype('default').asnumpy()

    def wait_to_read(self):
        pass

    def __repr__(self):
        return '<%s %s @%s>' % (type(self).__name__,
                                'x'.join(map(str, self._shape)), self._ctx)


class RowSparseNDArray(BaseSparseNDArray):
    """rows `indices` hold `data`; all other rows are zero."""

    stype = 'row_sparse'

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(shape, ctx, data.dtype)
        self.data = data          # NDArray (nnz_rows, *shape[1:])
        self.indices = indices    # NDArray int64 (nnz_rows,)

    def tostype(self, stype):
        if stype == 'row_sparse':
            return self
        if stype != 'default':
            raise ValueError(stype)
        dense = jnp.zeros(self._shape, dtype=self.data._data.dtype)
        dense = dense.at[self.indices._data.astype(jnp.int32)].set(self.data._data)
        return NDArray(dense, self._ctx)

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._data = self.tostype('default')._data
            return other
        return RowSparseNDArray(self.data.copy(), self.indices.copy(),
                                self._shape, other)

    def retain(self, row_ids):
        return retain(self, row_ids)

    def __add__(self, other):
        return self.tostype('default') + (
            other.tostype('default') if isinstance(other, BaseSparseNDArray) else other)


class CSRNDArray(BaseSparseNDArray):
    stype = 'csr'

    def __init__(self, data, indptr, indices, shape, ctx=None):
        super().__init__(shape, ctx, data.dtype)
        self.data = data
        self.indptr = indptr
        self.indices = indices

    def tostype(self, stype):
        if stype == 'csr':
            return self
        if stype != 'default':
            raise ValueError(stype)
        import scipy.sparse as sp  # scipy ships with jax
        m = sp.csr_matrix((self.data.asnumpy(), self.indices.asnumpy().astype(np.int64),
                           self.indptr.asnumpy().astype(np.int64)), shape=self._shape)
        return _dense_array(m.toarray(), self._ctx)

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._data = self.tostype('default')._data
            return other
        return CSRNDArray(self.data.copy(), self.indptr.copy(),
                          self.indices.copy(), self._shape, other)


def row_sparse_array(arg1, shape=None, ctx=None, dtype='float32'):
    """Reference sparse.py row_sparse_array: from (data, indices) or dense."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else _dense_array(np.asarray(data, dtype=dtype), ctx)
        indices = indices if isinstance(indices, NDArray) else \
            _dense_array(np.asarray(indices, dtype=np.int64), ctx, dtype='int64')
        if shape is None:
            nrows = int(indices.asnumpy().max()) + 1 if indices.size else 0
            shape = (nrows,) + data.shape[1:]
        return RowSparseNDArray(data, indices, shape, ctx)
    dense = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1, dtype=dtype)
    nz = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(_dense_array(dense[nz], ctx),
                            _dense_array(nz.astype(np.int64), ctx, dtype='int64'),
                            dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype='float32'):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = data if isinstance(data, NDArray) else _dense_array(np.asarray(data, dtype=dtype), ctx)
        indices = indices if isinstance(indices, NDArray) else \
            _dense_array(np.asarray(indices, dtype=np.int64), ctx, dtype='int64')
        indptr = indptr if isinstance(indptr, NDArray) else \
            _dense_array(np.asarray(indptr, dtype=np.int64), ctx, dtype='int64')
        return CSRNDArray(data, indptr, indices, shape, ctx)
    import scipy.sparse as sp
    dense = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1, dtype=dtype)
    m = sp.csr_matrix(dense)
    return CSRNDArray(_dense_array(m.data, ctx),
                      _dense_array(m.indptr.astype(np.int64), ctx, dtype='int64'),
                      _dense_array(m.indices.astype(np.int64), ctx, dtype='int64'),
                      dense.shape, ctx)


def retain(rsp, row_ids):
    """Reference sparse_retain op (tensor/sparse_retain.cc)."""
    want = row_ids.asnumpy().astype(np.int64)
    have = rsp.indices.asnumpy().astype(np.int64)
    pos = {r: i for i, r in enumerate(have)}
    keep = [r for r in want if r in pos]
    sel = np.array([pos[r] for r in keep], dtype=np.int64)
    data = rsp.data.asnumpy()[sel] if len(sel) else \
        np.zeros((0,) + rsp.shape[1:], dtype=rsp.data.asnumpy().dtype)
    return RowSparseNDArray(_dense_array(data, rsp._ctx),
                            _dense_array(np.asarray(keep, dtype=np.int64),
                                         rsp._ctx, dtype='int64'),
                            rsp.shape, rsp._ctx)
