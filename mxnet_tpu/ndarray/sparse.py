"""Sparse NDArray storage types — row_sparse and csr — and their ops.

Reference: python/mxnet/ndarray/sparse.py (RowSparseNDArray:780,
CSRNDArray:998), include/mxnet/ndarray.h:82-87 (kRowSparseStorage,
kCSRStorage, aux tensors), and the sparse op family in
src/operator/tensor/: cast_storage-inl.h, sparse_retain-inl.h,
square_sum-inl.h, dot-inl.h (csr×dense / csrᵀ×dense → row_sparse).

TPU-native stance (SURVEY.md §7 hard-part 4): XLA has no native sparse
tensors, so these are *structured dense* containers — data + index aux
arrays, exactly the reference's aux-tensor layout. The compute lowerings
are gather/segment-sum formulations that XLA schedules well (and that
keep the FLOPs proportional to nnz, not to the dense shape):

- ``dot(csr, dense)``       → one gather + segment_sum over nnz
- ``dot(csrᵀ, dense)``      → scatter-add keyed by column → row_sparse
- ``sparse_retain``         → membership mask + gather
- ``square_sum``            → row-sparse-aware reduction
- ``elemwise_add(rsp,rsp)`` → index-union merge

Storage-type inference follows the reference's FInferStorageType tables:
outputs carry the stype the reference's op would produce.
"""
import numpy as np

import jax.numpy as jnp

from ..context import current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ['RowSparseNDArray', 'CSRNDArray', 'row_sparse_array', 'csr_matrix',
           'BaseSparseNDArray', 'cast_storage', 'retain', 'sparse_retain',
           'dot', 'square_sum', 'add', 'zeros', 'empty', 'array']


class BaseSparseNDArray:
    def __init__(self, shape, ctx=None, dtype='float32'):
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()
        self._dtype = np.dtype(dtype) if dtype != 'bfloat16' else dtype

    @property
    def shape(self):
        return self._shape

    @property
    def size(self):
        return int(np.prod(self._shape))

    @property
    def dtype(self):
        return self._dtype

    @property
    def context(self):
        return self._ctx

    ctx = context

    def asnumpy(self):
        return self.tostype('default').asnumpy()

    def wait_to_read(self):
        pass

    def __repr__(self):
        return '<%s %s @%s>' % (type(self).__name__,
                                'x'.join(map(str, self._shape)), self._ctx)

    # dense-fallback arithmetic (reference elemwise ops accept
    # dense/sparse mixes and emit dense): subclasses override the cases
    # that stay sparse (scalar mul on row_sparse, rsp+rsp add)
    def _dense(self, other):
        return other.tostype('default') \
            if isinstance(other, BaseSparseNDArray) else other

    def __sub__(self, other):
        return self.tostype('default') - self._dense(other)

    def __rsub__(self, other):
        return self._dense(other) - self.tostype('default')

    def __truediv__(self, other):
        return self.tostype('default') / self._dense(other)

    def __rtruediv__(self, other):
        return self._dense(other) / self.tostype('default')

    def __neg__(self):
        return self * -1.0

    def __add__(self, other):
        return self.tostype('default') + self._dense(other)

    __radd__ = __add__

    def __mul__(self, other):
        return self.tostype('default') * self._dense(other)

    __rmul__ = __mul__


class RowSparseNDArray(BaseSparseNDArray):
    """rows `indices` hold `data`; all other rows are zero
    (reference sparse.py:780, aux layout ndarray.h:82-87)."""

    stype = 'row_sparse'

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(shape, ctx, data.dtype)
        self.data = data          # NDArray (nnz_rows, *shape[1:])
        self.indices = indices    # NDArray int64 (nnz_rows,)

    def tostype(self, stype):
        if stype == 'row_sparse':
            return self
        if stype != 'default':
            raise ValueError('cast from row_sparse to %s is not supported'
                             % stype)
        dense = jnp.zeros(self._shape, dtype=self.data._data.dtype)
        dense = dense.at[self.indices._data.astype(jnp.int32)].set(
            self.data._data)
        return NDArray(dense, self._ctx)

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._data = self.tostype('default')._data
            return other
        return RowSparseNDArray(self.data.copy(), self.indices.copy(),
                                self._shape, other)

    def copy(self):
        return RowSparseNDArray(self.data.copy(), self.indices.copy(),
                                self._shape, self._ctx)

    def retain(self, row_ids):
        return retain(self, row_ids)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return add(self, other)
        return self.tostype('default') + self._dense(other)

    __radd__ = __add__

    def __mul__(self, other):
        if np.isscalar(other):
            return RowSparseNDArray(self.data * other, self.indices,
                                    self._shape, self._ctx)
        return self.tostype('default') * self._dense(other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        if np.isscalar(other):
            return RowSparseNDArray(self.data / other, self.indices,
                                    self._shape, self._ctx)
        return self.tostype('default') / self._dense(other)

    def __sub__(self, other):
        if isinstance(other, RowSparseNDArray):
            return add(self, other * -1.0)
        return self.tostype('default') - self._dense(other)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference sparse.py:998)."""

    stype = 'csr'

    def __init__(self, data, indptr, indices, shape, ctx=None):
        super().__init__(shape, ctx, data.dtype)
        self.data = data
        self.indptr = indptr
        self.indices = indices

    def tostype(self, stype):
        if stype == 'csr':
            return self
        if stype == 'row_sparse':
            # reference cast_storage supports csr -> rsp via dense rows
            return row_sparse_array(self.tostype('default'), ctx=self._ctx,
                                    dtype=self.data.asnumpy().dtype)
        if stype != 'default':
            raise ValueError(stype)
        dense = jnp.zeros(self._shape, dtype=self.data._data.dtype)
        rows = self._row_ids()
        dense = dense.at[rows, self.indices._data.astype(jnp.int32)].set(
            self.data._data)
        return NDArray(dense, self._ctx)

    def _row_ids(self):
        """nnz-length row id per value, from indptr (host-side: aux
        indices are concrete metadata, exactly like the reference's
        aux_data on CPU)."""
        ptr = self.indptr.asnumpy().astype(np.int64)
        return jnp.asarray(np.repeat(np.arange(len(ptr) - 1),
                                     np.diff(ptr)), jnp.int32)

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._data = self.tostype('default')._data
            return other
        return CSRNDArray(self.data.copy(), self.indptr.copy(),
                          self.indices.copy(), self._shape, other)

    def copy(self):
        return CSRNDArray(self.data.copy(), self.indptr.copy(),
                          self.indices.copy(), self._shape, self._ctx)

    def __getitem__(self, key):
        """Row slicing (reference sparse.py CSRNDArray.__getitem__)."""
        if isinstance(key, int):
            key = slice(key, key + 1)
        start, stop, step = key.indices(self._shape[0])
        if step != 1:
            raise ValueError('CSR slicing requires step 1')
        ptr = self.indptr.asnumpy().astype(np.int64)
        lo, hi = int(ptr[start]), int(ptr[stop])
        return CSRNDArray(
            _dense_array(self.data.asnumpy()[lo:hi], self._ctx),
            _dense_array(ptr[start:stop + 1] - lo, self._ctx, dtype='int64'),
            _dense_array(self.indices.asnumpy()[lo:hi], self._ctx,
                         dtype='int64'),
            (stop - start, self._shape[1]), self._ctx)


# ---------------------------------------------------------------------------
# Constructors
# ---------------------------------------------------------------------------

def row_sparse_array(arg1, shape=None, ctx=None, dtype='float32'):
    """Reference sparse.py row_sparse_array: from (data, indices) or dense."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else \
            _dense_array(np.asarray(data, dtype=dtype), ctx)
        indices = indices if isinstance(indices, NDArray) else \
            _dense_array(np.asarray(indices, dtype=np.int64), ctx,
                         dtype='int64')
        if shape is None:
            nrows = int(indices.asnumpy().max()) + 1 if indices.size else 0
            shape = (nrows,) + data.shape[1:]
        return RowSparseNDArray(data, indices, shape, ctx)
    dense = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1,
                       dtype=dtype)
    nz = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(
        _dense_array(dense[nz], ctx),
        _dense_array(nz.astype(np.int64), ctx, dtype='int64'),
        dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype='float32'):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = data if isinstance(data, NDArray) else \
            _dense_array(np.asarray(data, dtype=dtype), ctx)
        indices = indices if isinstance(indices, NDArray) else \
            _dense_array(np.asarray(indices, dtype=np.int64), ctx,
                         dtype='int64')
        indptr = indptr if isinstance(indptr, NDArray) else \
            _dense_array(np.asarray(indptr, dtype=np.int64), ctx,
                         dtype='int64')
        return CSRNDArray(data, indptr, indices, shape, ctx)
    import scipy.sparse as sp
    dense = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1,
                       dtype=dtype)
    m = sp.csr_matrix(dense)
    return CSRNDArray(
        _dense_array(m.data, ctx),
        _dense_array(m.indptr.astype(np.int64), ctx, dtype='int64'),
        _dense_array(m.indices.astype(np.int64), ctx, dtype='int64'),
        dense.shape, ctx)


def zeros(stype, shape, ctx=None, dtype='float32'):
    """Reference sparse.py zeros — an all-zero sparse array (no stored
    values)."""
    if stype == 'row_sparse':
        return RowSparseNDArray(
            _dense_array(np.zeros((0,) + tuple(shape[1:]), dtype), ctx),
            _dense_array(np.zeros((0,), np.int64), ctx, dtype='int64'),
            shape, ctx)
    if stype == 'csr':
        return CSRNDArray(
            _dense_array(np.zeros((0,), dtype), ctx),
            _dense_array(np.zeros((shape[0] + 1,), np.int64), ctx,
                         dtype='int64'),
            _dense_array(np.zeros((0,), np.int64), ctx, dtype='int64'),
            shape, ctx)
    from . import zeros as dense_zeros
    return dense_zeros(shape, ctx, dtype)


empty = zeros


def array(source, ctx=None, dtype='float32'):
    """Reference sparse.py array — sparse-in → same-stype copy."""
    if isinstance(source, RowSparseNDArray):
        return source.copy()
    if isinstance(source, CSRNDArray):
        return source.copy()
    import scipy.sparse as sp
    if sp.issparse(source):
        m = source.tocsr()
        return csr_matrix((m.data, m.indices, m.indptr), shape=m.shape,
                          ctx=ctx, dtype=dtype)
    raise ValueError('use mx.nd.array for dense sources')


# ---------------------------------------------------------------------------
# Sparse operators (reference src/operator/tensor/)
# ---------------------------------------------------------------------------

def cast_storage(arr, stype):
    """Reference cast_storage-inl.h: dense↔row_sparse↔csr."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == 'default':
        return arr.copy()
    if stype == 'row_sparse':
        return row_sparse_array(arr, ctx=arr.context,
                                dtype=arr.asnumpy().dtype)
    if stype == 'csr':
        if len(arr.shape) != 2:
            raise ValueError('csr requires a 2-d array')
        return csr_matrix(arr, ctx=arr.context, dtype=arr.asnumpy().dtype)
    raise ValueError('unknown storage type %r' % (stype,))


def retain(rsp, row_ids):
    """Reference sparse_retain op (tensor/sparse_retain-inl.h): keep only
    the requested rows of a row_sparse array (missing rows stay absent)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise TypeError('sparse_retain expects a RowSparseNDArray')
    want = np.unique(np.asarray(
        row_ids.asnumpy() if isinstance(row_ids, NDArray) else row_ids
    ).astype(np.int64))
    have = rsp.indices.asnumpy().astype(np.int64)
    mask = np.isin(have, want)
    sel = np.flatnonzero(mask)
    data = rsp.data.asnumpy()[sel] if len(sel) else \
        np.zeros((0,) + rsp.shape[1:], dtype=rsp.data.asnumpy().dtype)
    return RowSparseNDArray(
        _dense_array(data, rsp._ctx),
        _dense_array(have[mask], rsp._ctx, dtype='int64'),
        rsp.shape, rsp._ctx)


sparse_retain = retain


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot (reference tensor/dot-inl.h FInferStorageType):
    dot(csr, dense) → dense; dot(csrᵀ, dense) → row_sparse."""
    if transpose_b:
        raise NotImplementedError('transpose_b with sparse inputs '
                                  '(unsupported in the reference too)')
    if isinstance(lhs, CSRNDArray):
        rows = lhs._row_ids()
        cols = jnp.asarray(lhs.indices.asnumpy().astype(np.int64), jnp.int32)
        vals = lhs.data._data
        dense_rhs = (rhs.tostype('default')
                     if isinstance(rhs, BaseSparseNDArray) else rhs)._data
        if not transpose_a:
            # out[i] = Σ_nnz vals * rhs[cols] grouped by row — one gather
            # + segment-sum, FLOPs ∝ nnz
            import jax
            contrib = vals[:, None] * dense_rhs[cols]       # [nnz, N]
            out = jax.ops.segment_sum(contrib, rows,
                                      num_segments=lhs.shape[0])
            return NDArray(out.astype(dense_rhs.dtype), lhs._ctx)
        # csrᵀ × dense: scatter by column index → row_sparse output
        import jax
        contrib = vals[:, None] * dense_rhs[rows]           # [nnz, N]
        out = jax.ops.segment_sum(contrib, cols,
                                  num_segments=lhs.shape[1])
        nz = np.unique(lhs.indices.asnumpy().astype(np.int64))
        return RowSparseNDArray(
            NDArray(out[jnp.asarray(nz, jnp.int32)], lhs._ctx),
            _dense_array(nz, lhs._ctx, dtype='int64'),
            (lhs.shape[1], dense_rhs.shape[1]), lhs._ctx)
    if isinstance(rhs, BaseSparseNDArray) or isinstance(lhs,
                                                        BaseSparseNDArray):
        lhs_d = lhs.tostype('default') if isinstance(
            lhs, BaseSparseNDArray) else lhs
        rhs_d = rhs.tostype('default') if isinstance(
            rhs, BaseSparseNDArray) else rhs
        from . import dot as dense_dot
        return dense_dot(lhs_d, rhs_d, transpose_a=transpose_a)
    from . import dot as dense_dot
    return dense_dot(lhs, rhs, transpose_a=transpose_a,
                     transpose_b=transpose_b)


def square_sum(rsp, axis=None, keepdims=False):
    """Reference square_sum-inl.h: Σ x² over a row_sparse array without
    densifying — axis=1 keeps the row structure (row_sparse out)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise TypeError('square_sum expects a RowSparseNDArray')
    sq = rsp.data._data.astype(jnp.float32) ** 2
    if axis is None:
        out = sq.sum()
        return NDArray(out.reshape((1,) * len(rsp.shape)) if keepdims
                       else out, rsp._ctx)
    axis = int(axis) % len(rsp.shape)
    if axis == 1:
        row_sums = sq.sum(axis=tuple(range(1, sq.ndim)))
        if keepdims:
            data = NDArray(row_sums[:, None], rsp._ctx)
            return RowSparseNDArray(data, rsp.indices,
                                    (rsp.shape[0], 1), rsp._ctx)
        dense = jnp.zeros((rsp.shape[0],), jnp.float32)
        dense = dense.at[rsp.indices._data.astype(jnp.int32)].set(row_sums)
        return NDArray(dense, rsp._ctx)
    # axis == 0: reduce over rows → dense row vector
    out = sq.sum(axis=0)
    return NDArray(out[None] if keepdims else out, rsp._ctx)


def add(a, b):
    """elemwise_add(rsp, rsp) → rsp via index-union merge (reference
    elemwise_binary_op_basic.cc sparse kernels)."""
    if not (isinstance(a, RowSparseNDArray) and
            isinstance(b, RowSparseNDArray)):
        a_d = a.tostype('default') if isinstance(a, BaseSparseNDArray) else a
        b_d = b.tostype('default') if isinstance(b, BaseSparseNDArray) else b
        return a_d + b_d
    assert a.shape == b.shape, (a.shape, b.shape)
    ia = a.indices.asnumpy().astype(np.int64)
    ib = b.indices.asnumpy().astype(np.int64)
    union = np.union1d(ia, ib)
    pos = {r: i for i, r in enumerate(union)}
    out = np.zeros((len(union),) + a.shape[1:], a.data.asnumpy().dtype)
    da, db = a.data.asnumpy(), b.data.asnumpy()
    for j, r in enumerate(ia):
        out[pos[r]] += da[j]
    for j, r in enumerate(ib):
        out[pos[r]] += db[j]
    return RowSparseNDArray(_dense_array(out, a._ctx),
                            _dense_array(union, a._ctx, dtype='int64'),
                            a.shape, a._ctx)
