"""mx.nd.random / mx.random sampling namespace.

Reference: python/mxnet/ndarray/random.py (uniform/normal/... wrappers over
the sample_op.cc registrations).
"""
from .ndarray import invoke, NDArray
from ..context import current_context

__all__ = ['uniform', 'normal', 'gamma', 'exponential', 'poisson',
           'negative_binomial', 'generalized_negative_binomial',
           'multinomial', 'shuffle', 'randn']


def _sample(op_elem, op_scalar, params, shape, dtype, ctx, out, kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    if any(isinstance(p, NDArray) for p in params.values()):
        inputs = list(params.values())
        attrs = {'shape': shape or (), 'dtype': dtype}
        return invoke(op_elem, inputs, attrs, out)
    attrs = dict(params)
    attrs.update({'shape': shape or (1,), 'dtype': dtype})
    attrs.update(kwargs)
    return invoke(op_scalar, [], attrs, out)


def uniform(low=0, high=1, shape=None, dtype='float32', ctx=None, out=None, **kwargs):
    return _sample('_sample_uniform', '_random_uniform',
                   {'low': low, 'high': high}, shape, dtype, ctx, out, kwargs)


def normal(loc=0, scale=1, shape=None, dtype='float32', ctx=None, out=None, **kwargs):
    return _sample('_sample_normal', '_random_normal',
                   {'loc': loc, 'scale': scale} if not isinstance(loc, NDArray)
                   else {'mu': loc, 'sigma': scale}, shape, dtype, ctx, out, kwargs)


def randn(*shape, **kwargs):
    loc = kwargs.pop('loc', 0)
    scale = kwargs.pop('scale', 1)
    dtype = kwargs.pop('dtype', 'float32')
    return normal(loc, scale, shape, dtype, **kwargs)


def gamma(alpha=1, beta=1, shape=None, dtype='float32', ctx=None, out=None, **kwargs):
    return _sample('_sample_gamma', '_random_gamma',
                   {'alpha': alpha, 'beta': beta}, shape, dtype, ctx, out, kwargs)


def exponential(scale=1, shape=None, dtype='float32', ctx=None, out=None, **kwargs):
    lam = 1.0 / scale if not isinstance(scale, NDArray) else 1.0 / scale
    return _sample('_sample_exponential', '_random_exponential',
                   {'lam': lam}, shape, dtype, ctx, out, kwargs)


def poisson(lam=1, shape=None, dtype='float32', ctx=None, out=None, **kwargs):
    return _sample('_sample_poisson', '_random_poisson',
                   {'lam': lam}, shape, dtype, ctx, out, kwargs)


def negative_binomial(k=1, p=1, shape=None, dtype='float32', ctx=None,
                      out=None, **kwargs):
    return _sample('_sample_negative_binomial', '_random_negative_binomial',
                   {'k': k, 'p': p}, shape, dtype, ctx, out, kwargs)


def generalized_negative_binomial(mu=1, alpha=1, shape=None, dtype='float32',
                                  ctx=None, out=None, **kwargs):
    return _sample('_sample_generalized_negative_binomial',
                   '_random_generalized_negative_binomial',
                   {'mu': mu, 'alpha': alpha}, shape, dtype, ctx, out, kwargs)


def multinomial(data, shape=(), get_prob=False, out=None, dtype='int32'):
    return invoke('_sample_multinomial', [data],
                  {'shape': shape, 'get_prob': get_prob, 'dtype': dtype}, out)


def shuffle(data, out=None):
    return invoke('_shuffle', [data], {}, out)
