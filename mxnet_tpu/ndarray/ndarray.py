"""NDArray — the mutable, async-dispatch tensor every layer passes around.

Reference: include/mxnet/ndarray.h:93-1242 + src/ndarray/ndarray.cc +
python/mxnet/ndarray/ndarray.py:150.

TPU-native design (SURVEY.md §7): the reference's NDArray is a shared Chunk
(storage handle + engine Var); all mutation is an engine push and reads
synchronize via WaitToRead. Here the backing store is an immutable
``jax.Array`` and "mutation" rebinds ``_data`` — JAX's async dispatch gives
the same caller-returns-immediately pipelining the threaded engine provided,
and ``wait_to_read()`` maps to ``block_until_ready()``. Write-after-read
hazards cannot exist (buffers are immutable), which deletes the entire
ThreadedVar dependency-queue machinery (threaded_engine.h:111-213) with no
loss of semantics.
"""
import functools
import re

import numpy as np

import jax
import jax.numpy as jnp

from .. import autograd as _ag
from .. import random as _random
from ..base import MXNetError, np_dtype, normalize_attrs, numeric_types
from ..context import Context, current_context
from ..ops import registry as _reg

__all__ = ['NDArray', 'array', 'zeros', 'ones', 'empty', 'full', 'arange',
           'invoke', 'waitall', 'concatenate', 'moveaxis', 'onehot_encode',
           'imperative_invoke', 'from_jax', 'stack']


def waitall():
    """Block until all dispatched computation is done — a real barrier.

    Reference: MXNDArrayWaitAll / Engine::WaitForAll (engine.h:180).
    XLA devices execute programs in submission order, so dispatching a
    trivial program on each local device and fetching its result to
    the host drains everything queued before it (a host fetch, not
    block_until_ready: through tunneled runtimes only the device→host
    copy is a reliable fence)."""
    import numpy as _np
    for dev in jax.local_devices():
        try:
            fence = jax.device_put(_np.zeros((), _np.float32), dev)
            _np.asarray(fence + 1)
        except Exception:  # device gone/unreachable: nothing to drain
            pass


class NDArray:
    """Multi-dimensional, context-bound array (reference ndarray.py:150)."""

    __slots__ = ('_data', '_ctx', '_grad', '_leaf', '_node', '_out_idx',
                 '_fresh_grad', '__weakref__')

    def __init__(self, data, ctx=None):
        self._data = data
        self._ctx = ctx if ctx is not None else current_context()
        self._grad = None
        self._leaf = None
        self._node = None
        self._out_idx = 0
        self._fresh_grad = True

    # pickling carries values only (host numpy + context), never tape or
    # device state — same contract as the reference's NDArray __reduce__
    # (python/mxnet/ndarray.py save/load path)
    def __getstate__(self):
        npy = np.asarray(self._data)
        if npy.dtype.name == 'bfloat16':
            return {'data': npy.astype(np.float32), 'ctx': self._ctx,
                    'bf16': True}
        return {'data': npy, 'ctx': self._ctx, 'bf16': False}

    def __setstate__(self, state):
        import jax.numpy as jnp
        import jax
        dtype = jnp.bfloat16 if state.get('bf16') else None
        data = jnp.asarray(state['data'], dtype=dtype)
        ctx = state['ctx']
        try:
            data = jax.device_put(data, ctx.jax_device())
        except Exception:
            pass  # device unavailable in this process: keep default placement
        self.__init__(data, ctx=ctx)

    # -- basic properties -------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        # np.dtype handles bfloat16 via ml_dtypes and compares equal to
        # jnp.bfloat16, so one uniform return type (str() -> 'bfloat16')
        return np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return 'default'

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):
        """Opaque-handle compat: the backing jax.Array."""
        return self._data

    def __repr__(self):
        return '\n%s\n<NDArray %s @%s>' % (
            str(self.asnumpy()), 'x'.join(str(s) for s in self.shape), self._ctx)

    def __len__(self):
        if not self.shape:
            raise TypeError('len() of unsized object')
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError('The truth value of an NDArray with multiple '
                             'elements is ambiguous.')
        return bool(self.asscalar())

    # -- synchronization (engine semantics) -------------------------------
    def wait_to_read(self):
        """Reference ndarray.h:336 WaitToRead ≙ block_until_ready."""
        jax.block_until_ready(self._data)

    def wait_to_write(self):
        jax.block_until_ready(self._data)

    # -- host transfer ----------------------------------------------------
    def asnumpy(self):
        arr = np.asarray(self._data)
        if arr.dtype == jnp.bfloat16:
            arr = arr.astype(np.float32)
        if not arr.flags.writeable:
            # reference asnumpy() copies device->host: callers own the
            # result and may mutate it (e.g. the CustomOp examples do
            # y[i, l] -= 1 on a forward output); np.asarray over a
            # jax.Array is a read-only view of the device buffer
            arr = arr.copy()
        return arr

    def asscalar(self):
        if self.size != 1:
            raise ValueError('The current array is not a scalar')
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # -- copies / context movement ----------------------------------------
    def copy(self):
        return self.copyto(self._ctx)

    def copyto(self, other):
        """Reference ndarray.cc:497 CopyFromTo (engine copy op)."""
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, other._ctx.jax_device())
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()), other)
        raise TypeError('copyto does not support type ' + str(type(other)))

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    def astype(self, dtype, copy=True):
        d = np_dtype(dtype)
        if not copy and self._data.dtype == d:
            return self
        return invoke('Cast', [self], {'dtype': str(dtype)})

    def tostype(self, stype):
        """Reference cast_storage: dense → row_sparse / csr containers."""
        if stype == 'default':
            return self
        from .sparse import cast_storage
        return cast_storage(self, stype)

    # -- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req='write', stype=None):
        """Reference ndarray.py attach_grad → MXAutogradMarkVariables."""
        grad = NDArray(jnp.zeros_like(self._data), self._ctx)
        _ag.mark_variables([self], [grad], grad_req)
        self._fresh_grad = True

    def detach(self):
        out = NDArray(self._data, self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _ag.backward([self], [out_grad] if out_grad is not None else None,
                     retain_graph, train_mode)

    # -- mutation ---------------------------------------------------------
    def _set_data(self, new_data, node=None, out_idx=0):
        self._data = new_data
        self._node = node
        self._out_idx = out_idx

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, numeric_types):
            value = jnp.asarray(value, dtype=self._data.dtype)
        else:
            value = jnp.asarray(np.asarray(value), dtype=self._data.dtype)
        if key is None or key == slice(None):
            self._set_data(jnp.broadcast_to(value, self.shape).astype(self._data.dtype))
        else:
            self._set_data(self._data.at[key].set(value))

    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key._data
        out = self._data[key]
        res = NDArray(out, self._ctx)
        if _ag.is_recording() and (self._node is not None or self._leaf is not None):
            # record the slice so gradients flow through indexing
            return invoke('_slice_like_getitem', [self], {'key': _freeze_key(key)})
        return res

    # -- operator overloads (dispatch to registered ops, reference
    #    ndarray.py __add__ etc → broadcast_add/_plus_scalar) -------------
    def __add__(self, other):
        return _binary(self, other, 'broadcast_add', '_plus_scalar')

    def __radd__(self, other):
        return self.__add__(other)

    def __iadd__(self, other):
        out = _binary(self, other, 'broadcast_add', '_plus_scalar')
        self._set_data(out._data, out._node, out._out_idx)
        return self

    def __sub__(self, other):
        return _binary(self, other, 'broadcast_sub', '_minus_scalar')

    def __rsub__(self, other):
        return _scalar(self, other, '_rminus_scalar')

    def __isub__(self, other):
        out = self.__sub__(other)
        self._set_data(out._data, out._node, out._out_idx)
        return self

    def __mul__(self, other):
        return _binary(self, other, 'broadcast_mul', '_mul_scalar')

    def __rmul__(self, other):
        return self.__mul__(other)

    def __imul__(self, other):
        out = self.__mul__(other)
        self._set_data(out._data, out._node, out._out_idx)
        return self

    def __truediv__(self, other):
        return _binary(self, other, 'broadcast_div', '_div_scalar')

    def __rtruediv__(self, other):
        return _scalar(self, other, '_rdiv_scalar')

    def __itruediv__(self, other):
        out = self.__truediv__(other)
        self._set_data(out._data, out._node, out._out_idx)
        return self

    def __mod__(self, other):
        return _binary(self, other, 'broadcast_mod', '_mod_scalar')

    def __rmod__(self, other):
        return _scalar(self, other, '_rmod_scalar')

    def __pow__(self, other):
        return _binary(self, other, 'broadcast_power', '_power_scalar')

    def __rpow__(self, other):
        return _scalar(self, other, '_rpower_scalar')

    def __neg__(self):
        return invoke('negative', [self], {})

    def __abs__(self):
        return invoke('abs', [self], {})

    def __eq__(self, other):
        return _binary(self, other, 'broadcast_equal', '_equal_scalar')

    def __ne__(self, other):
        return _binary(self, other, 'broadcast_not_equal', '_not_equal_scalar')

    def __gt__(self, other):
        return _binary(self, other, 'broadcast_greater', '_greater_scalar')

    def __ge__(self, other):
        return _binary(self, other, 'broadcast_greater_equal', '_greater_equal_scalar')

    def __lt__(self, other):
        return _binary(self, other, 'broadcast_lesser', '_lesser_scalar')

    def __le__(self, other):
        return _binary(self, other, 'broadcast_lesser_equal', '_lesser_equal_scalar')

    def __hash__(self):
        return id(self)

    # -- common method forms of ops ---------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        shape = kwargs.get('shape', shape)
        return invoke('Reshape', [self], {'shape': tuple(shape)})

    def reshape_like(self, other):
        return invoke('reshape_like', [self, other], {})

    def broadcast_to(self, shape):
        return invoke('broadcast_to', [self], {'shape': tuple(shape)})

    def broadcast_axes(self, axis=(), size=()):
        return invoke('broadcast_axes', [self],
                      {'axis': (axis,) if isinstance(axis, int) else
                       tuple(axis),
                       'size': (size,) if isinstance(size, int) else
                       tuple(size)})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return invoke('transpose', [self], {'axes': axes} if axes else {})

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        return invoke('Flatten', [self], {})

    def expand_dims(self, axis):
        return invoke('expand_dims', [self], {'axis': axis})

    def squeeze(self, axis=None):
        return invoke('squeeze', [self], {'axis': axis})

    def swapaxes(self, dim1, dim2):
        return invoke('SwapAxis', [self], {'dim1': dim1, 'dim2': dim2})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke('SliceChannel', [self],
                      {'num_outputs': num_outputs, 'axis': axis,
                       'squeeze_axis': squeeze_axis})

    def slice(self, begin, end, step=None):
        return invoke('slice', [self], {'begin': tuple(begin), 'end': tuple(end),
                                        'step': tuple(step) if step else None})

    def slice_axis(self, axis, begin, end):
        return invoke('slice_axis', [self], {'axis': axis, 'begin': begin, 'end': end})

    def take(self, indices, axis=0, mode='clip'):
        return invoke('take', [self, indices], {'axis': axis, 'mode': mode})

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype='float32'):
        return invoke('one_hot', [self], {'depth': depth, 'on_value': on_value,
                                          'off_value': off_value, 'dtype': dtype})

    def pick(self, index, axis=-1, keepdims=False):
        return invoke('pick', [self, index], {'axis': axis, 'keepdims': keepdims})

    def clip(self, a_min, a_max):
        return invoke('clip', [self], {'a_min': a_min, 'a_max': a_max})

    def tile(self, reps):
        return invoke('tile', [self], {'reps': tuple(reps)})

    def repeat(self, repeats, axis=None):
        return invoke('repeat', [self], {'repeats': repeats, 'axis': axis})

    def flip(self, axis):
        return invoke('reverse', [self], {'axis': (axis,) if isinstance(axis, int) else tuple(axis)})

    def pad(self, mode, pad_width, constant_value=0):
        return invoke('Pad', [self], {'mode': mode, 'pad_width': tuple(pad_width),
                                      'constant_value': constant_value})

    def sort(self, axis=-1, is_ascend=True):
        return invoke('sort', [self], {'axis': axis, 'is_ascend': is_ascend})

    def argsort(self, axis=-1, is_ascend=True, dtype='float32'):
        return invoke('argsort', [self], {'axis': axis, 'is_ascend': is_ascend,
                                          'dtype': dtype})

    def topk(self, axis=-1, k=1, ret_typ='indices', is_ascend=False):
        return invoke('topk', [self], {'axis': axis, 'k': k, 'ret_typ': ret_typ,
                                       'is_ascend': is_ascend})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return invoke('dot', [self, other], {'transpose_a': transpose_a,
                                             'transpose_b': transpose_b})

    def as_jax(self):
        """Escape hatch to the raw jax.Array (TPU-native extension)."""
        return self._data


def _reduce_method(name):
    def method(self, axis=None, keepdims=False, **kwargs):
        attrs = {'axis': axis if axis is None or isinstance(axis, int)
                 else tuple(axis), 'keepdims': keepdims}
        attrs.update(kwargs)
        return invoke(name, [self], attrs)
    method.__name__ = name
    return method


def _unary_method(name):
    def method(self, **kwargs):
        return invoke(name, [self], kwargs)
    method.__name__ = name
    return method


for _n in ['sum', 'nansum', 'prod', 'nanprod', 'mean', 'max', 'min', 'norm',
           'argmax', 'argmin']:
    setattr(NDArray, _n, _reduce_method(_n))
for _n in ['abs', 'sign', 'round', 'rint', 'fix', 'floor', 'ceil', 'trunc',
           'sin', 'cos', 'tan', 'arcsin', 'arccos', 'arctan', 'degrees',
           'radians', 'sinh', 'cosh', 'tanh', 'arcsinh', 'arccosh', 'arctanh',
           'exp', 'expm1', 'log', 'log10', 'log2', 'log1p', 'sqrt', 'rsqrt',
           'cbrt', 'square', 'reciprocal', 'relu', 'sigmoid', 'softmax',
           'log_softmax', 'zeros_like', 'ones_like', 'sign']:
    setattr(NDArray, _n, _unary_method(_n))


# ---------------------------------------------------------------------------
# invoke — the imperative call path
# ---------------------------------------------------------------------------

def _freeze_key(key):
    """Make an indexing key hashable for the attr dict."""
    if isinstance(key, tuple):
        return tuple(_freeze_key(k) for k in key)
    if isinstance(key, slice):
        return ('__slice__', key.start, key.stop, key.step)
    if isinstance(key, (jnp.ndarray, np.ndarray)):
        return ('__array__', tuple(np.asarray(key).ravel().tolist()),
                tuple(key.shape))
    return key


def _thaw_key(key):
    if isinstance(key, tuple):
        if len(key) == 4 and key[0] == '__slice__':
            return slice(key[1], key[2], key[3])
        if len(key) == 3 and key[0] == '__array__':
            return np.array(key[1]).reshape(key[2]).astype(np.int64)
        return tuple(_thaw_key(k) for k in key)
    return key


@_reg.register('_slice_like_getitem', differentiable=True)
def _slice_like_getitem(attrs, x):
    return x[_thaw_key(attrs['key'])]


def _parent_entry(arr):
    if arr._node is not None:
        return (arr._node, arr._out_idx)
    if arr._leaf is not None:
        return (arr._leaf, 0)
    return (None, 0)


def invoke(op_name, inputs, attrs=None, out=None):
    """Execute a registered op imperatively.

    Reference call stack (SURVEY.md §3.1): generated fn → _imperative_invoke →
    MXImperativeInvoke → SetShapeType/SetDependency → PushFCompute →
    Engine::PushAsync. Here: cached jit closure + (if recording) jax.vjp;
    JAX's async dispatch replaces the engine push.
    """
    from .. import profiler as _profiler
    with _profiler.maybe_span(op_name):
        return _invoke_impl(op_name, inputs, attrs, out)


def _invoke_impl(op_name, inputs, attrs=None, out=None):
    op = _reg.get(op_name)
    _reg.record(op)   # execution-based coverage gate (conftest)
    # ctx is an op kwarg in the reference (SampleUniformParam etc. carry
    # a ctx field): it directs placement, never reaches the kernel, and
    # must not key the jit cache
    req_ctx = None
    if attrs and 'ctx' in attrs:
        attrs = dict(attrs)  # don't mutate the caller's (reusable) dict
        req_ctx = attrs.pop('ctx')
        if req_ctx is not None and not isinstance(req_ctx, Context):
            # string spelling 'cpu(0)' / 'gpu(1)' (the C-API kwarg form)
            m = re.match(r'(\w+)\((\d+)\)', str(req_ctx))
            req_ctx = Context(m.group(1), int(m.group(2))) if m else None
    attrs = normalize_attrs(attrs or {})
    if op.train_aware:
        attrs['__is_train__'] = _ag.is_training()

    arrays = [i._data for i in inputs]
    n_real = len(arrays)
    if op.needs_rng:
        arrays.append(_random.next_key())

    ctx = inputs[0]._ctx if inputs else (req_ctx or current_context())

    recording = _ag.is_recording() and op.differentiable and any(
        i._node is not None or i._leaf is not None for i in inputs)

    if op.host:
        # host ops (image codecs, legacy callback bridges) run python on
        # concrete arrays. When the tape needs a vjp they go through the
        # pure_callback bridge (traceable, legacy-backward-aware);
        # otherwise they are applied directly.
        f = (_reg.host_bridge(op, attrs) if recording
             else functools.partial(op.fn, attrs))
    else:
        f = _reg.jitted(op_name, attrs)
    node = None
    if recording:
        outs, vjp_fn = jax.vjp(f, *arrays)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)
        parents = [_parent_entry(i) for i in inputs]
        if op.needs_rng:
            parents.append((None, 0))
        node = _ag.record_op(vjp_fn, parents, len(outs_t), n_real,
                             op_info=(op_name, dict(attrs)))
        node.head_ids = [(o.shape, o.dtype) for o in outs_t]
    else:
        outs = f(*arrays)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)

    # write mutated aux outputs back into their input NDArrays
    # (reference: FMutateInputs / aux states, op_attr_types.h)
    for in_idx, out_idx in op.mutate_inputs.items():
        if out_idx < len(outs_t):
            inputs[in_idx]._data = outs_t[out_idx]

    if req_ctx is not None and not inputs:
        # honor the requested device for source ops (zero-input
        # samplers/initializers): data must live where _ctx says it does
        dev = req_ctx.jax_device()
        outs_t = tuple(jax.device_put(o, dev) for o in outs_t)

    n_vis = op.n_visible_outputs(attrs)
    results = []
    for i in range(n_vis):
        r = NDArray(outs_t[i], ctx)
        r._node = node
        r._out_idx = i
        results.append(r)

    if out is not None:
        outs_list = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs_list, results):
            # the reference rejects a shape-mismatched out buffer at
            # shape-inference time (SetShapeType); rebinding would
            # silently change dst.shape for downstream holders
            if dst._data.shape != src._data.shape:
                raise ValueError(
                    'out has shape %s but %s produced %s'
                    % (dst._data.shape, op_name, src._data.shape))
            dst._set_data(src._data, src._node, src._out_idx)
        return out

    if n_vis == 1:
        return results[0]
    return results


def imperative_invoke(op_name, *inputs, **kwargs):
    out = kwargs.pop('out', None)
    return invoke(op_name, list(inputs), kwargs, out)


def _binary(lhs, rhs, op_broadcast, op_scalar):
    if isinstance(rhs, NDArray):
        return invoke(op_broadcast, [lhs, rhs], {})
    if isinstance(rhs, numeric_types):
        return invoke(op_scalar, [lhs], {'scalar': float(rhs)})
    from .sparse import BaseSparseNDArray
    if isinstance(rhs, BaseSparseNDArray):
        # dense (op) sparse emits dense, like the reference's elemwise
        # dense/sparse fallbacks
        return invoke(op_broadcast, [lhs, rhs.tostype('default')], {})
    raise TypeError('type %s not supported' % str(type(rhs)))


def _scalar(lhs, rhs, op_scalar):
    return invoke(op_scalar, [lhs], {'scalar': float(rhs)})


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def from_jax(data, ctx=None):
    return NDArray(data, ctx)


def array(source_array, ctx=None, dtype=None):
    """Reference ndarray.py:1988 mx.nd.array."""
    ctx = ctx if ctx is not None else current_context()
    keep_dtype = isinstance(source_array, (np.ndarray, NDArray))
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    else:
        src = np.asarray(source_array)
    if dtype is None:
        # reference ndarray.py: python lists default to float32; numpy
        # arrays keep their dtype (64-bit narrowed: x64 stays off for TPU)
        if not keep_dtype:
            dtype = np.float32
        else:
            dtype = src.dtype
            if dtype == np.float64:
                dtype = np.float32
            elif dtype == np.int64:
                dtype = np.int32
    d = np_dtype(dtype)
    if not jax.config.jax_enable_x64 and d is not None:
        # jax silently truncates 64-bit dtypes when x64 is off; request
        # the narrowed dtype up front to keep the conversion warning-free
        if np.dtype(d) == np.int64:
            d = np.int32
        elif np.dtype(d) == np.float64:
            d = np.float32
    data = jax.device_put(jnp.asarray(src, dtype=d), ctx.jax_device())
    return NDArray(data, ctx)


def empty(shape, ctx=None, dtype='float32'):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype='float32', **kwargs):
    ctx = ctx if ctx is not None else current_context()
    if isinstance(shape, int):
        shape = (shape,)
    data = jax.device_put(jnp.zeros(shape, dtype=np_dtype(dtype)), ctx.jax_device())
    return NDArray(data, ctx)


def ones(shape, ctx=None, dtype='float32', **kwargs):
    ctx = ctx if ctx is not None else current_context()
    if isinstance(shape, int):
        shape = (shape,)
    data = jax.device_put(jnp.ones(shape, dtype=np_dtype(dtype)), ctx.jax_device())
    return NDArray(data, ctx)


def full(shape, val, ctx=None, dtype='float32', out=None):
    ctx = ctx if ctx is not None else current_context()
    if isinstance(shape, int):
        shape = (shape,)
    data = jax.device_put(jnp.full(shape, val, dtype=np_dtype(dtype)), ctx.jax_device())
    res = NDArray(data, ctx)
    if out is not None:
        out._set_data(res._data)
        return out
    return res


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype='float32'):
    ctx = ctx if ctx is not None else current_context()
    arr = jnp.arange(start, stop, step, dtype=np_dtype(dtype))
    if repeat > 1:
        arr = jnp.repeat(arr, repeat)
    return NDArray(jax.device_put(arr, ctx.jax_device()), ctx)


def concatenate(arrays, axis=0, always_copy=True):
    return invoke('Concat', list(arrays), {'dim': axis, 'num_args': len(arrays)})


def stack(*arrays, **kwargs):
    axis = kwargs.get('axis', 0)
    arrs = list(arrays[0]) if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)) else list(arrays)
    return invoke('stack', arrs, {'axis': axis, 'num_args': len(arrs)})


def moveaxis(tensor, source, destination):
    return NDArray(jnp.moveaxis(tensor._data, source, destination), tensor._ctx)


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = invoke('one_hot', [indices], {'depth': depth})
    out._set_data(res._data)
    return out


def __getattr__(name):
    """Deep-import compat: the reference defines module-level helpers
    (multiply, maximum, imdecode, ...) in ndarray/ndarray.py itself;
    here they live on the package — forward lookups there."""
    if name.startswith('_'):
        raise AttributeError(name)
    import sys as _s
    pkg = _s.modules[__package__]
    if hasattr(pkg, name):
        return getattr(pkg, name)
    raise AttributeError('module %r has no attribute %r'
                         % (__name__, name))
