"""Auto-generation of the nd.* operator namespace from the registry.

Reference: python/mxnet/ndarray/op.py:52-174 (_make_ndarray_function reads op
introspection from MXSymbolGetAtomicSymbolInfo and synthesizes python
functions at import time). Same design, one process: functions are generated
from the in-process registry.
"""
import functools

from ..context import Context as _Ctx
from ..ops import registry as _reg
from .ndarray import NDArray, invoke

__all__ = ['make_nd_function', 'install_ops']


_sparse_mod = None


def _sparse():
    global _sparse_mod
    if _sparse_mod is None:
        from . import sparse as sp
        _sparse_mod = sp
    return _sparse_mod


def _lower_sparse(a):
    """Sparse containers participate in the dense op namespace by
    dense-lowering (the SURVEY §8 ADR: TPU compute is dense-tiled; the
    real sparse kernels live in nd.sparse.*)."""
    if isinstance(a, _sparse().BaseSparseNDArray):
        return a.tostype('default')
    return a


def make_nd_function(op_name):
    op = _reg.get(op_name)
    routes_sparse_dot = op_name == 'dot'   # hoisted off the hot path

    def fn(*args, **kwargs):
        if args or kwargs:
            sp = _sparse()
            if routes_sparse_dot and args and \
                    isinstance(args[0], sp.CSRNDArray):
                # reference dot dispatches on storage type: csr lhs uses
                # the real sparse kernel (gather + segment_sum), same
                # numerics as dense-lowering but O(nnz). transpose_b has
                # no sparse kernel — fall through to dense-lowering.
                tb = bool(kwargs.get('transpose_b', False)) or \
                    (len(args) > 3 and bool(args[3]))
                if not tb:
                    # (lhs, rhs, transpose_a, transpose_b): sparse.dot's
                    # signature matches the dense op's, so positional
                    # and rhs=/transpose_a= spellings pass through
                    res = sp.dot(*args, **{k: v for k, v in kwargs.items()
                                           if k in ('rhs', 'transpose_a',
                                                    'transpose_b')})
                    out_nd = kwargs.get('out')
                    if out_nd is not None:
                        if tuple(out_nd.shape) != tuple(res.shape):
                            raise ValueError(
                                'out has shape %s but dot produced %s'
                                % (out_nd.shape, res.shape))
                        if isinstance(out_nd, sp.BaseSparseNDArray):
                            # sparse out buffer: rebind its payload
                            # (stype must match the kernel's result)
                            res_st = getattr(res, 'stype', 'default')
                            if res_st != out_nd.stype:
                                raise ValueError(
                                    'out has stype %s but dot produced '
                                    '%s' % (out_nd.stype, res_st))
                            out_nd.data = res.data
                            out_nd.indices = res.indices
                            if out_nd.stype == 'csr':
                                out_nd.indptr = res.indptr
                            return out_nd
                        # dense out: the reference densifies the sparse
                        # kernel's result (csr^T . dense -> row_sparse)
                        # into the provided dense buffer
                        # _set_data also clears any stale autograd
                        # node the buffer carried from a previous op
                        out_nd._set_data(_lower_sparse(res)._data)
                        return out_nd
                    return res
            args = [_lower_sparse(a) for a in args]
            kwargs = {k: (v if k == 'out' else _lower_sparse(v))
                      for k, v in kwargs.items()}
        out = kwargs.pop('out', None)
        kwargs.pop('name', None)
        inputs = []
        pos_inputs = [a for a in args if isinstance(a, NDArray)]
        # scalar positional args map onto declared params in order
        # (matches the generated-signature convention of ndarray/op.py);
        # a positional None is an omitted optional input, not a param.
        # A positional Context is the ctx kwarg (samplers' generated
        # signature ends ...shape, ctx, dtype), never a scalar param
        pos_attrs = []
        for a in args:
            if isinstance(a, (NDArray, type(None))):
                continue
            if isinstance(a, _Ctx):
                kwargs.setdefault('ctx', a)
            else:
                pos_attrs.append(a)
        if pos_attrs:
            for pname in op.param_defaults:
                if not pos_attrs:
                    break
                if pname not in kwargs:
                    kwargs[pname] = pos_attrs.pop(0)
        if op.variadic:
            inputs = pos_inputs
            if op.key_var_num_args and op.key_var_num_args not in kwargs:
                kwargs[op.key_var_num_args] = len(inputs)
            attrs = kwargs
        else:
            named = {}
            for k in list(kwargs):
                if k in op.input_names and isinstance(kwargs[k], NDArray):
                    named[k] = kwargs.pop(k)
            attrs = kwargs
            pos_iter = iter(pos_inputs)
            for name in op.input_names:
                if name in named:
                    inputs.append(named[name])
                else:
                    nxt = next(pos_iter, None)
                    if nxt is None:
                        break
                    inputs.append(nxt)
        return invoke(op_name, inputs, attrs, out)

    fn.__name__ = op_name
    fn.__doc__ = op.doc
    return fn


def install_ops(namespace):
    """Install one generated function per registered op into ``namespace``."""
    for name in _reg.list_ops():
        if name.startswith('_slice_like'):
            continue
        namespace[name] = make_nd_function(name)
        # public aliases for leading-underscore arithmetic helpers
    return namespace
