"""NDArray serialization.

Reference: include/mxnet/ndarray.h:361-373 + src/ndarray/ndarray.cc:814
(NDArray::Save/Load, dmlc::Stream binary) + python/mxnet/ndarray/
utils.py save/load (dict/list of arrays).

Two on-disk formats:

- the native container (single .npz with a manifest) — default for
  ``save``;
- the REFERENCE binary format (list magic 0x112, per-array V2 magic
  0xF993fac9, little-endian dmlc streams, ndarray.cc:809-1040) —
  ``load`` auto-detects it, so ``.params``/``.ndarray`` files written
  by the reference load directly (the checkpoint-migration path), and
  ``save(..., fmt='mxnet')`` writes it for the reverse direction.
"""
import struct
import warnings

import numpy as np

from .ndarray import empty, zeros  # noqa: F401  (reference utils.py re-exports)

from .ndarray import NDArray, array

__all__ = ['save', 'load']

_LIST_KEY = '__mxtpu_list__%d'

_LIST_MAGIC = 0x112
_V2_MAGIC = 0xF993FAC9
_V1_MAGIC = 0xF993FAC8
# mshadow type flags (mshadow/base.h)
_TYPE_FLAGS = {0: np.float32, 1: np.float64, 2: np.float16,
               3: np.uint8, 4: np.int32, 5: np.int8, 6: np.int64}
_FLAG_OF = {np.dtype(v): k for k, v in _TYPE_FLAGS.items()}


def save(fname, data, fmt='npz'):
    """Save NDArrays. ``fmt='npz'`` (native container) or ``'mxnet'``
    (the reference's binary list format, loadable by the reference)."""
    if isinstance(data, NDArray):
        data = [data]
    if fmt == 'mxnet':
        return _save_mxnet(fname, data)
    if fmt != 'npz':
        raise ValueError("fmt must be 'npz' or 'mxnet'")
    if isinstance(data, dict):
        arrays = {k: v.asnumpy() for k, v in data.items()}
        container = 'dict'
    elif isinstance(data, (list, tuple)):
        arrays = {_LIST_KEY % i: v.asnumpy() for i, v in enumerate(data)}
        container = 'list'
    else:
        raise ValueError('data must be NDArray, list or dict')
    with open(fname, 'wb') as f:  # savez would append .npz to a str path
        np.savez(f, __format__=container, **arrays)


def load(fname):
    """Load NDArrays; the reference's binary format is auto-detected by
    its list magic, anything else parses as the native npz."""
    with open(fname, 'rb') as f:
        head = f.read(8)
    if len(head) == 8 and struct.unpack('<Q', head)[0] == _LIST_MAGIC:
        return _load_mxnet(fname)
    with np.load(fname, allow_pickle=False) as f:
        container = str(f['__format__'])
        keys = [k for k in f.files if k != '__format__']
        if container == 'list':
            out = []
            for i in range(len(keys)):
                out.append(array(f[_LIST_KEY % i]))
            return out
        return {k: array(f[k]) for k in keys}


# ---------------------------------------------------------------------------
# Reference binary format (src/ndarray/ndarray.cc:814-1040)
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def take(self, n):
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise ValueError('truncated reference NDArray file')
        self.pos += n
        return b

    def u32(self):
        return struct.unpack('<I', self.take(4))[0]

    def i32(self):
        return struct.unpack('<i', self.take(4))[0]

    def u64(self):
        return struct.unpack('<Q', self.take(8))[0]

    def shape(self):
        # TShape::Save (nnvm Tuple): uint32 ndim + int64 per-dim —
        # NDARRAY_V1_MAGIC marks exactly the int64_t TShape change
        # (reference src/ndarray/ndarray.cc:806-812); uint32 dims exist
        # only in the pre-V1 magic-as-ndim legacy branch.
        ndim = self.u32()
        return tuple(struct.unpack('<%dq' % ndim, self.take(8 * ndim)))


def _read_one(r):
    """One NDArray (ndarray.cc NDArray::Load / LegacyLoad)."""
    magic = r.u32()
    stype, sshape, nad = 0, None, 0
    if magic == _V2_MAGIC:
        stype = r.i32()
        nad = {1: 1, 2: 2}.get(stype, 0)   # row_sparse / csr aux counts
        if nad > 0:
            sshape = r.shape()
        shape = r.shape()
    elif magic == _V1_MAGIC:
        shape = r.shape()
    else:
        ndim = magic                       # legacy: the magic IS ndim
        shape = tuple(struct.unpack('<%dI' % ndim, r.take(4 * ndim)))
    if len(shape) == 0:
        return array(np.zeros((0,), np.float32))
    r.i32()  # dev_type (placement is ours to choose)
    r.i32()  # dev_id
    type_flag = r.i32()
    dtype = _TYPE_FLAGS.get(type_flag)
    if dtype is None:
        raise ValueError('unknown reference dtype flag %d' % type_flag)
    aux = []
    for _ in range(nad):
        at = r.i32()
        ash = r.shape()
        aux.append((_TYPE_FLAGS[at], ash))
    data_shape = sshape if nad > 0 else shape
    n = int(np.prod(data_shape)) if data_shape else 1
    data = np.frombuffer(r.take(n * np.dtype(dtype).itemsize),
                         dtype=dtype).reshape(data_shape)
    aux_data = []
    for at, ash in aux:
        an = int(np.prod(ash)) if ash else 1
        aux_data.append(np.frombuffer(
            r.take(an * np.dtype(at).itemsize), dtype=at).reshape(ash))
    if nad == 0:
        return array(_guard_narrowing(data.copy()))
    from . import sparse
    data = _guard_narrowing(data.copy())
    aux_data = [_guard_narrowing(a.astype(np.int64)) for a in aux_data]
    if stype == 1:  # row_sparse: aux = [indices]
        return sparse.RowSparseNDArray(
            array(data), array(aux_data[0]), shape)
    # csr: aux = [indptr, indices] (ndarray.h:82-87 aux order)
    return sparse.CSRNDArray(
        array(data), array(aux_data[0]), array(aux_data[1]), shape)


def _guard_narrowing(npy):
    """jax (x64 off) stores 64-bit payloads as 32-bit: raise on integer
    overflow (silent wrap would corrupt saved indices), warn on float64
    precision narrowing."""
    if npy.dtype == np.int64:
        if npy.size and (np.abs(npy) > np.iinfo(np.int32).max).any():
            raise ValueError(
                'reference file holds int64 values beyond int32 range; '
                'this runtime (jax without x64) cannot represent them')
        return npy
    if npy.dtype == np.float64:
        warnings.warn('float64 payload narrowed to float32 (jax x64 off)',
                      stacklevel=3)
    return npy


def _load_mxnet(fname):
    with open(fname, 'rb') as f:
        r = _Reader(f.read())
    assert r.u64() == _LIST_MAGIC
    r.u64()  # reserved
    n = r.u64()
    arrays = [_read_one(r) for _ in range(n)]
    n_names = r.u64()
    names = []
    for _ in range(n_names):
        ln = r.u64()
        names.append(r.take(ln).decode())
    if not names:
        return arrays
    if len(names) != len(arrays):
        raise ValueError('invalid reference NDArray file (name count)')
    return dict(zip(names, arrays))


def _shape_bytes(shape):
    # uint32 ndim + int64 dims, matching TShape::Save under V1/V2 magics
    # (reference src/ndarray/ndarray.cc:806-812).
    return struct.pack('<I', len(shape)) + \
        struct.pack('<%dq' % len(shape), *shape)


def _body_bytes(npy):
    """context + type_flag + raw data (shared by dense and sparse)."""
    return (struct.pack('<ii', 1, 0) +                   # cpu(0)
            struct.pack('<i', _FLAG_OF[np.dtype(npy.dtype)]))


def _write_one(f, arr):
    from . import sparse as _sp
    if isinstance(arr, _sp.BaseSparseNDArray):
        return _write_sparse(f, arr)
    npy = arr.asnumpy()
    if np.dtype(npy.dtype) not in _FLAG_OF:
        npy = npy.astype(np.float32)   # bf16 etc.: widen for the reference
    if npy.ndim == 0:
        # the reference has no 0-d arrays; its scalar convention is (1,)
        npy = npy.reshape(1)
    f.write(struct.pack('<I', _V2_MAGIC))
    f.write(struct.pack('<i', 0))                        # kDefaultStorage
    f.write(_shape_bytes(npy.shape))
    f.write(_body_bytes(npy))
    f.write(np.ascontiguousarray(npy).tobytes())


def _write_sparse(f, arr):
    """RowSparse (stype 1, aux [indices]) / CSR (stype 2, aux
    [indptr, indices]) in the reference layout (ndarray.h:82-87)."""
    from . import sparse as _sp
    data = arr.data.asnumpy()
    if np.dtype(data.dtype) not in _FLAG_OF:
        data = data.astype(np.float32)
    if isinstance(arr, _sp.RowSparseNDArray):
        stype, auxes = 1, [arr.indices.asnumpy().astype(np.int64)]
    else:
        stype = 2
        auxes = [arr.indptr.asnumpy().astype(np.int64),
                 arr.indices.asnumpy().astype(np.int64)]
    f.write(struct.pack('<I', _V2_MAGIC))
    f.write(struct.pack('<i', stype))
    f.write(_shape_bytes(data.shape))                    # storage shape
    f.write(_shape_bytes(arr.shape))
    f.write(_body_bytes(data))
    for a in auxes:
        f.write(struct.pack('<i', _FLAG_OF[np.dtype(a.dtype)]))
        f.write(_shape_bytes(a.shape))
    f.write(np.ascontiguousarray(data).tobytes())
    for a in auxes:
        f.write(np.ascontiguousarray(a).tobytes())


def _save_mxnet(fname, data):
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    else:
        raise ValueError('data must be NDArray, list or dict')
    with open(fname, 'wb') as f:
        f.write(struct.pack('<QQ', _LIST_MAGIC, 0))
        f.write(struct.pack('<Q', len(arrays)))
        for a in arrays:
            _write_one(f, a)
        f.write(struct.pack('<Q', len(names)))
        for nm in names:
            b = nm.encode()
            f.write(struct.pack('<Q', len(b)))
            f.write(b)
