"""NDArray serialization.

Reference: include/mxnet/ndarray.h:361-373 NDArray::Save/Load (versioned
binary) + python/mxnet/ndarray/utils.py save/load (dict/list of arrays).

Format here: a single .npz container with a manifest — functionally
equivalent (dict/list round-trip, dtype/shape preserved); the on-disk bytes
differ from the reference's dmlc::Stream format by design (no CUDA/mshadow
layout baggage).
"""
import numpy as np

from .ndarray import NDArray, array

__all__ = ['save', 'load']

_LIST_KEY = '__mxtpu_list__%d'


def save(fname, data):
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        arrays = {k: v.asnumpy() for k, v in data.items()}
        fmt = 'dict'
    elif isinstance(data, (list, tuple)):
        arrays = {_LIST_KEY % i: v.asnumpy() for i, v in enumerate(data)}
        fmt = 'list'
    else:
        raise ValueError('data must be NDArray, list or dict')
    with open(fname, 'wb') as f:  # savez would append .npz to a str path
        np.savez(f, __format__=fmt, **arrays)


def load(fname):
    with np.load(fname, allow_pickle=False) as f:
        fmt = str(f['__format__'])
        keys = [k for k in f.files if k != '__format__']
        if fmt == 'list':
            out = []
            for i in range(len(keys)):
                out.append(array(f[_LIST_KEY % i]))
            return out
        return {k: array(f[k]) for k in keys}
