"""mx.nd — imperative tensor namespace.

Reference: python/mxnet/ndarray/__init__.py (ndarray + generated op module).
"""
import sys as _sys

from .ndarray import (NDArray, array, zeros, ones, empty, full, arange,
                      invoke, imperative_invoke, waitall, concatenate, stack,
                      moveaxis, onehot_encode, from_jax)
from . import register as _register
from .utils import save, load
from . import random  # noqa: F401
from . import sparse  # noqa: F401
from . import linalg  # noqa: F401
from . import op  # noqa: F401  (generated-op module path)
from . import _internal  # noqa: F401
from .sparse import csr_matrix, row_sparse_array

_register.install_ops(globals())


def cast_storage(data, stype='default'):
    """Eager cast_storage — the real container conversion
    (reference c_api cast_storage → ndarray/sparse.py). The registry op
    of the same name is the symbol-world identity annotation."""
    return sparse.cast_storage(data, stype)


def Custom(*args, **kwargs):
    """Eager Custom op: host-python execution + autograd recording
    (reference custom.cc ExecType::kLocal). The registry 'Custom' op
    remains the symbolic-executor form."""
    from ..operator import custom_eager
    return custom_eager(*args, **kwargs)


def sparse_retain(data, indices):
    """Eager sparse_retain: row_sparse in → row_sparse out
    (reference sparse_retain-inl.h); dense input uses the registry op's
    dense lowering (rows outside ``indices`` become zero)."""
    if isinstance(data, sparse.BaseSparseNDArray):
        return sparse.retain(data, indices)
    return invoke('_sparse_retain', [data, indices], {})

# method-style conveniences that MXNet exposes at module level
from .ndarray import _binary as _nd_binary  # noqa: F401


def add(lhs, rhs):
    return lhs + rhs


def subtract(lhs, rhs):
    return lhs - rhs


def multiply(lhs, rhs):
    return lhs * rhs


def divide(lhs, rhs):
    return lhs / rhs


def power(lhs, rhs):
    return lhs ** rhs


def maximum(lhs, rhs):
    if isinstance(rhs, NDArray):
        return invoke('broadcast_maximum', [lhs, rhs], {})
    return invoke('_maximum_scalar', [lhs], {'scalar': float(rhs)})


def minimum(lhs, rhs):
    if isinstance(rhs, NDArray):
        return invoke('broadcast_minimum', [lhs, rhs], {})
    return invoke('_minimum_scalar', [lhs], {'scalar': float(rhs)})


def equal(l, r):
    return l == r


def not_equal(l, r):
    return l != r


def greater(l, r):
    return l > r


def greater_equal(l, r):
    return l >= r


def lesser(l, r):
    return l < r


def lesser_equal(l, r):
    return l <= r


def negative(data):
    return -data


def true_divide(lhs, rhs):
    return divide(lhs, rhs)


def modulo(lhs, rhs):
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return invoke('broadcast_mod', [lhs, rhs], {})
    if isinstance(lhs, NDArray):
        return invoke('_mod_scalar', [lhs], {'scalar': float(rhs)})
    return invoke('_rmod_scalar', [rhs], {'scalar': float(lhs)})


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0,
             channels=3, mean=None):
    """Decode an image bytestring to NDArray (reference
    ndarray.py:imdecode over the cv codec op). ``clip_rect``
    (x0, y0, x1, y1) crops after decode."""
    import numpy as _np
    flag = 1 if channels == 3 else 0
    buf = array(_np.frombuffer(
        str_img if isinstance(str_img, bytes) else str_img.encode('latin1'),
        dtype=_np.uint8), dtype=_np.uint8)
    img = invoke('_cvimdecode', [buf], {'flag': flag, 'to_rgb': False})
    x0, y0, x1, y1 = clip_rect
    if x1 > x0 and y1 > y0:
        img = img[y0:y1, x0:x1]
    if mean is not None:
        img = img - mean
    if out is not None:
        # a 4-D out is a pre-allocated batch; `index` picks the slot
        # (reference ndarray.py:imdecode)
        if out.ndim == 4:
            out[index] = img
        else:
            out[:] = img
        return out
    return img

from . import contrib  # noqa: E402,F401  (mx.nd.contrib.*)


def __getattr__(name):
    """Late-binding for ops registered after import (Custom ops, plugins —
    reference re-runs _init_ops on MXCustomOpRegister)."""
    from ..ops import registry as _late_reg
    if _late_reg.exists(name):
        fn = _register.make_nd_function(name)
        globals()[name] = fn
        return fn
    raise AttributeError('module %r has no attribute %r' % (__name__, name))
