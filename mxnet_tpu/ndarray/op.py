"""mx.nd.op — the generated-operator module path.

Reference: python/mxnet/ndarray/op.py (where _make_ndarray_function
installs the generated wrappers; the public names are re-exported into
mx.nd). Any registered op resolves lazily.
"""
from ..ops.registry import lazy_op_module
from .register import make_nd_function

__getattr__, __dir__ = lazy_op_module(globals(), make_nd_function)
