"""mx.nd.linalg — advanced linear algebra namespace.

Reference: python/mxnet/ndarray/linalg.py (generated from the
``linalg_*`` operator family, src/operator/tensor/la_op.cc); the short
names are the registry ops' generated wrappers, so positional scalar
params and ``out=`` behave like every other nd function.
"""
from . import register as _register

__all__ = ['gemm', 'gemm2', 'potrf', 'potri', 'trmm', 'trsm', 'syrk',
           'gelqf', 'sumlogdiag']

gemm = _register.make_nd_function('linalg_gemm')
gemm2 = _register.make_nd_function('linalg_gemm2')
potrf = _register.make_nd_function('linalg_potrf')
potri = _register.make_nd_function('linalg_potri')
trmm = _register.make_nd_function('linalg_trmm')
trsm = _register.make_nd_function('linalg_trsm')
syrk = _register.make_nd_function('linalg_syrk')
gelqf = _register.make_nd_function('linalg_gelqf')
sumlogdiag = _register.make_nd_function('linalg_sumlogdiag')
