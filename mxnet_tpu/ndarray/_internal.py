"""mx.nd._internal — underscore-prefixed operator namespace
(reference python/mxnet/ndarray/_internal.py). Lazily generated.
"""
from ..ops.registry import lazy_op_module
from .register import make_nd_function

__getattr__, __dir__ = lazy_op_module(globals(), make_nd_function,
                                      underscore_only=True)
