"""Profiler — Chrome-trace / TensorBoard profiling control.

Reference: python/mxnet/profiler.py (108 LoC: profiler_set_config/
set_state/dump_profile) over src/engine/profiler.{h,cc} which emitted
Chrome trace-event JSON.

TPU-native: delegates to the JAX/XLA profiler (jax.profiler), which captures
device traces viewable in TensorBoard/Perfetto — same role, richer data.
A lightweight host-side op-timeline (chrome trace JSON) is kept for parity
with the reference's output format.
"""
import atexit
import json
import os
import threading
import time

import jax

__all__ = ['profiler_set_config', 'profiler_set_state', 'dump_profile',
           'Profiler', 'note_step']

_state = {'mode': 'symbolic', 'filename': 'profile.json', 'running': False,
          'events': [], 'jax_dir': None, 'ran': False, 'dumped': False}
_written = set()   # profile paths THIS process wrote (merge on re-dump)
_lock = threading.Lock()


def _xla_trace_allowed():
    """Whether to attach jax.profiler alongside the host-span trace.

    NEVER against the tunneled axon chip: a killed traced process wedges
    the tunnel claim for hours (verify SKILL.md, round-2 incident).
    MXTPU_PROFILER_XLA_TRACE=0/1 overrides in either direction."""
    from .config import flags
    ov = flags.get('MXTPU_PROFILER_XLA_TRACE')
    if ov != 'auto':
        return ov == '1'
    try:
        return jax.default_backend() != 'axon'
    except Exception:
        return False


def _atexit_dump():
    """Reference initialize.cc:57-67 — the profile is written at process
    exit even when the script never calls dump_profile (the example
    scripts rely on this). Events recorded AFTER a mid-run user dump are
    flushed too: dump_profile merges into a file this process already
    wrote, so a periodic-dump pattern loses nothing and an
    already-complete dump is simply rewritten unchanged."""
    if _state['running']:
        try:
            # jax.profiler.stop_trace can raise during interpreter
            # shutdown; an atexit hook must not turn a successful run
            # into a nonzero exit
            profiler_set_state('stop')
        except Exception:
            pass
    if _state['ran'] and (_state['events'] or not _state['dumped']):
        try:
            dump_profile()
        except Exception:
            pass


# -- MXTPU_XPROF: step-windowed jax.profiler capture -------------------------
#
# MXTPU_XPROF=start:stop arms a one-shot device-trace capture over a
# window of TRAINING STEPS: the trace starts once `start` steps have
# completed and stops once `stop` have, landing a TensorBoard/Perfetto
# trace in MXTPU_XPROF_DIR without bracketing code by hand — steady-state
# windows (past warmup/compile) are exactly what a perf investigation
# wants. The fit loops report progress via note_step(); the fused paths
# advance a whole window at a time, so boundaries quantize to window
# multiples there. The capture honors the same axon-backend guard as the
# chrome-trace profiler (_xla_trace_allowed): a killed trace against the
# tunneled chip wedges the claim for hours.

_xprof = 'unset'   # 'unset' -> parsed lazily on first note_step; None = off


def _xprof_parse():
    from .config import flags
    try:
        raw = flags.get('MXTPU_XPROF')
    except Exception:  # noqa: BLE001 — undeclared in stripped builds
        raw = ''
    if not raw:
        return None
    try:
        a, b = raw.split(':', 1)
        start, stop = int(a), int(b)
        if start < 0 or stop <= start:
            raise ValueError
    except ValueError:
        import logging
        logging.warning("MXTPU_XPROF=%r ignored — expected 'start:stop' "
                        'with stop > start >= 0', raw)
        return None
    try:
        trace_dir = flags.get('MXTPU_XPROF_DIR')
    except Exception:  # noqa: BLE001
        trace_dir = ''
    return {'start': start, 'stop': stop,
            'dir': os.path.expanduser(trace_dir or 'xprof_trace'),
            'steps': 0, 'on': False}


def _xprof_atexit():
    """Never leave a device trace running past interpreter teardown."""
    w = _xprof
    if isinstance(w, dict) and w['on']:
        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass
        w['on'] = False


def note_step(n=1):
    """Advance the training-step count for the MXTPU_XPROF capture
    window (the fit loops call this; n = steps completed by the call).
    Free when the flag is unset: one global load + None check."""
    global _xprof
    w = _xprof
    if w is None:
        return
    if w == 'unset':
        w = _xprof = _xprof_parse()
        if w is None:
            return
    w['steps'] += n
    was_on = w['on']
    if not w['on'] and w['steps'] >= w['start']:
        import logging
        if not _xla_trace_allowed():
            logging.warning(
                'MXTPU_XPROF: device trace suppressed on this backend '
                '(MXTPU_PROFILER_XLA_TRACE guard) — no capture')
            _xprof = None
            return
        try:
            jax.profiler.start_trace(w['dir'])
            w['on'] = True
            atexit.register(_xprof_atexit)
            logging.info('MXTPU_XPROF: device trace started at step %d '
                         '-> %s', w['steps'], w['dir'])
        except Exception as e:  # noqa: BLE001 — a capture failure must
            logging.warning('MXTPU_XPROF: start_trace failed: %s', e)
            _xprof = None       # not kill training
            return
    # stop only on a call AFTER the one that started the trace: when a
    # fused window jumps past both boundaries at once, the capture
    # still spans one full window instead of closing empty
    if was_on and w['steps'] >= w['stop']:
        import logging
        try:
            jax.profiler.stop_trace()
            logging.info('MXTPU_XPROF: device trace stopped at step %d '
                         '(window %d:%d) — open %s in TensorBoard/'
                         'Perfetto', w['steps'], w['start'], w['stop'],
                         w['dir'])
        except Exception as e:  # noqa: BLE001
            logging.warning('MXTPU_XPROF: stop_trace failed: %s', e)
        w['on'] = False
        _xprof = None           # one-shot: further steps cost one check


def _xprof_reset_for_tests():
    global _xprof
    if isinstance(_xprof, dict) and _xprof['on']:
        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass
    _xprof = 'unset'


def profiler_set_config(mode='symbolic', filename='profile.json'):
    """Reference profiler.py:25. mode: 'symbolic' or 'all'."""
    _state['mode'] = mode
    _state['filename'] = filename


def profiler_set_state(state='stop'):
    """Reference profiler.py:42. state: 'run' or 'stop'."""
    from . import _native
    lib = _native.get_lib()
    if lib is not None:  # native engine-op spans (src/profiler.cc)
        lib.MXTProfilerSetState(1 if state == 'run' else 0)
    with _lock:
        if state == 'run' and not _state['running']:
            _state['running'] = True
            if not _state['ran']:
                _state['ran'] = True
                atexit.register(_atexit_dump)
            _state['dumped'] = False
            _state['events'] = []
            _state['start'] = time.time()
            _state['jax_dir'] = None
            if _xla_trace_allowed():
                jax_dir = os.path.splitext(_state['filename'])[0] + '_xla'
                try:
                    jax.profiler.start_trace(jax_dir)
                    _state['jax_dir'] = jax_dir
                except Exception:
                    _state['jax_dir'] = None
        elif state == 'stop' and _state['running']:
            _state['running'] = False
            if _state['jax_dir']:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass


def record_event(name, start_us, end_us, category='operator'):
    """Host-side event hook (engine profiler OprExecStat analog).
    Thread-safe: prefetch iterators invoke ops off the main thread."""
    if _state['running']:
        ev = {'name': name, 'cat': category, 'ph': 'X',
              'ts': start_us, 'dur': end_us - start_us,
              'pid': os.getpid(), 'tid': threading.get_ident()}
        with _lock:
            _state['events'].append(ev)


def is_running():
    """Fast gate for callers that would otherwise pay timing overhead."""
    return _state['running']


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        pass


_NULL_SPAN = _NullSpan()


def maybe_span(name, category='operator'):
    """span(...) when profiling is on, a shared no-op otherwise — the
    one-liner gate for hot call sites (eager invoke, executor fwd/bwd)."""
    return span(name, category) if _state['running'] else _NULL_SPAN


class span:
    """Time a host-side region into the trace (executor fwd/bwd, eager
    invokes). Events are dispatch-side spans — inside a fused XLA step
    the per-op schedule belongs to the XLA trace, not this one."""

    __slots__ = ('name', 'cat', 't0')

    def __init__(self, name, category='operator'):
        self.name = name
        self.cat = category

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *a):
        if _state['running']:
            t1 = time.time()
            record_event(self.name, int(self.t0 * 1e6), int(t1 * 1e6),
                         self.cat)


def dump_profile():
    """Reference profiler.py:57 — writes Chrome trace-event JSON (python
    events merged with the native engine's op spans)."""
    # drain python events (the native dump below also drains its buffer,
    # so repeated dumps are symmetric: each event appears exactly once)
    with _lock:
        events = list(_state['events'])
        _state['events'] = []
    from . import _native
    lib = _native.get_lib()
    if lib is not None:
        import tempfile
        with tempfile.NamedTemporaryFile('r', suffix='.json',
                                         delete=False) as tmp:
            path = tmp.name
        try:
            if lib.MXTProfilerDump(path.encode()) == 0:
                with open(path) as f:
                    events.extend(json.load(f).get('traceEvents', []))
        finally:
            os.unlink(path)
    path = _state['filename']
    if path in _written and os.path.exists(path):
        # repeated dumps in one process accumulate (each drain appears
        # exactly once): merge with what this process wrote before
        try:
            with open(path) as f:
                events = json.load(f).get('traceEvents', []) + events
        except Exception:
            pass
    with open(path, 'w') as f:
        json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
    _written.add(path)
    _state['dumped'] = True


class Profiler:
    """Context manager convenience (TPU-native extension)."""

    def __init__(self, mode='all', filename='profile.json'):
        profiler_set_config(mode, filename)

    def __enter__(self):
        profiler_set_state('run')
        return self

    def __exit__(self, *args):
        profiler_set_state('stop')
        dump_profile()
