"""Torch interop — module-path parity for the reference's mx.torch.

Reference: python/mxnet/torch.py exposed the Lua-torch op bridge
(plugin/torch). The modern equivalent wraps **pytorch** modules and
criteria as differentiable operators; see
:mod:`mxnet_tpu.plugin.torch_bridge` for the implementation.
"""
from .plugin.torch_bridge import TorchModule, TorchCriterion

__all__ = ['TorchModule', 'TorchCriterion']
