"""Streaming RecordIO image pipeline.

Reference: src/io/iter_image_recordio_2.cc:46 (ImageRecordIOParser2:
chunked reads + an OMP pool decoding/augmenting records in parallel,
:122-130), src/io/image_aug_default.cc (per-image augmentation order:
resize → scale jitter → crop → mirror), src/io/iter_prefetcher.h:46
(bounded prefetch queue in front of the consumer).

Design here: one framing-only offset scan at construction (no decode),
then per epoch a producer thread walks the (optionally shuffled,
num_parts-sharded) offset order, a ThreadPoolExecutor of
``preprocess_threads`` workers decodes + augments individual records
(PIL decode and numpy release the GIL), and assembled numpy batches
flow through a ``prefetch_buffer``-bounded queue. Memory is
O(batch_size × prefetch_buffer), independent of dataset size — a
multi-GB .rec streams with flat RSS (tools/io_bench.py measures this).
Device arrays are only created on the consumer thread: worker threads
never touch jax.

Augmentation is per-image (each image draws its own crop offset and
mirror coin), matching the reference's ImageAugmenter contract; the
exotic augmenters (rotate/shear/HSL/aspect) are accepted and warned
about once, not silently dropped.
"""
import logging
import queue as _queue
import struct
import threading
import warnings
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .. import random as _random
from ..recordio import MXRecordIO, _kMagic, unpack

__all__ = ['StreamingImageRecordIter']

_UNSUPPORTED_AUG = ('max_rotate_angle', 'max_shear_ratio', 'random_h',
                    'random_s', 'random_l', 'max_aspect_ratio',
                    'random_resized_crop', 'brightness', 'contrast',
                    'saturation', 'pca_noise')


def scan_record_offsets(path):
    """One framing-only pass over a .rec: byte offsets of record STARTS
    (cflag 0 = whole record, 1 = first part of a multi-part record;
    continuation parts 2/3 are skipped). No payload is decoded, so a
    multi-GB file scans at sequential-read speed."""
    offsets = []
    with open(path, 'rb') as f:
        while True:
            pos = f.tell()
            head = f.read(8)
            if len(head) < 8:
                break
            magic, lrec = struct.unpack('<II', head)
            if magic != _kMagic:
                raise IOError('invalid RecordIO magic at offset %d' % pos)
            cflag = lrec >> 29
            length = lrec & 0x1fffffff
            if cflag in (0, 1):
                offsets.append(pos)
            f.seek(length + (4 - length % 4) % 4, 1)
    return offsets


def _decode_hwc(payload):
    """Decode one packed image payload to HWC uint8 (RAW0 or codec)."""
    if payload[:4] == b'RAW0':
        ndim = struct.unpack('<I', payload[4:8])[0]
        shape = tuple(np.frombuffer(payload[8:8 + 4 * ndim],
                                    dtype=np.int32))
        img = np.frombuffer(payload[8 + 4 * ndim:],
                            dtype=np.uint8).reshape(shape)
        if img.ndim == 3 and img.shape[0] in (1, 3) \
                and img.shape[2] not in (1, 3):
            img = img.transpose(1, 2, 0)       # stored CHW
        elif img.ndim == 2:
            img = img[:, :, None]
        return img
    try:
        from PIL import Image
        import io as _io
    except ImportError:
        raise ImportError('JPEG/PNG decode requires pillow; '
                          'use .raw packed records')
    img = np.asarray(Image.open(_io.BytesIO(payload)))
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def _resize_short(img, size):
    """Resize so the SHORT side equals ``size`` (reference default
    resize augmenter)."""
    h, w = img.shape[:2]
    if min(h, w) == size:
        return img
    if h < w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    return _resize(img, nh, nw)


def _resize(img, nh, nw):
    from PIL import Image
    squeeze = img.shape[2] == 1
    pil = Image.fromarray(img[:, :, 0] if squeeze else img)
    out = np.asarray(pil.resize((nw, nh), Image.BILINEAR))
    return out[:, :, None] if squeeze else out


class StreamingImageRecordIter:
    """Backend shared by ImageRecordIter: yields (data, label, pad)
    numpy batches from a bounded prefetch queue."""

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, mean=(0, 0, 0), std=(1, 1, 1), scale=1.0,
                 rand_crop=False, rand_mirror=False, preprocess_threads=4,
                 prefetch_buffer=4, round_batch=True, resize=-1, pad=0,
                 fill_value=127, max_random_scale=1.0, min_random_scale=1.0,
                 num_parts=1, part_index=0, aug_kwargs=None,
                 device_augment=False, host_crop=False):
        self.path = path_imgrec
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.shuffle = shuffle
        self.scale = scale
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)
        self.rand_crop = bool(int(rand_crop))
        self.rand_mirror = bool(int(rand_mirror))
        self.threads = max(1, int(preprocess_threads))
        self.prefetch = max(1, int(prefetch_buffer))
        self.round_batch = round_batch
        self.resize = int(resize)
        self.pad = int(pad)
        self.fill_value = int(fill_value)
        self.max_random_scale = float(max_random_scale)
        self.min_random_scale = float(min_random_scale)
        for k, v in (aug_kwargs or {}).items():
            if k in _UNSUPPORTED_AUG and v:
                warnings.warn(
                    'ImageRecordIter: augmenter %r is not applied by the '
                    'TPU pipeline (reference image_aug_default.cc '
                    'supports it; file an issue if needed)' % k,
                    stacklevel=3)
        # device-augment mode (VERDICT r4 #6 "feed the chip"): worker
        # threads stop at a FIXED-SIZE uint8 HWC image — crop, mirror,
        # and normalize move into one jitted device call per batch
        # (io/__init__.py ImageRecordIter._device_aug). On a few-core
        # host this removes the float conversion + crop from the
        # decode-bound path; with RAW0 records host work is file reads.
        self.device_augment = bool(int(device_augment))
        # host-crop refinement: workers crop (rand or center) to the
        # target H x W BEFORE handover, so the uploaded window carries
        # H*W/S^2 of the source bytes (23% fewer for 224^2-from-256^2)
        # — a per-image uint8 slice against a smaller transfer, the
        # right trade on any transfer-constrained host->device link.
        # Mirror + normalize stay on device.
        self.host_crop = bool(int(host_crop)) and self.device_augment
        self._src_hw = None
        if self.device_augment:
            C, H, W = self.data_shape
            if self.resize > 0:
                side = self.resize + 2 * self.pad
                if side < max(H, W):
                    raise ValueError(
                        'device_augment: resize+2*pad (%d) must cover the '
                        'crop %dx%d' % (side, H, W))
                self._src_hw = (side, side)
            if self.max_random_scale != 1.0 or self.min_random_scale != 1.0:
                warnings.warn('device_augment: random scale jitter is not '
                              'applied on-device; ignoring', stacklevel=3)
            if self.resize > 0 and self.rand_crop:
                warnings.warn(
                    'device_augment: random crops sample from the CENTER '
                    'square of the resized image (the host path samples '
                    'the full resize-short rectangle) — the augmentation '
                    'distribution differs on non-square sources',
                    stacklevel=3)
        # fused normalize: chw*scale, -mean, /std as ONE uint8->f32 LUT
        # per channel (the 3-pass float formulation costs ~1.7 ms per
        # 224^2 image; the LUT ~0.4 ms)
        lut = (np.arange(256, dtype=np.float32)[None, :] * self.scale
               - self.mean.reshape(-1, 1)) / self.std.reshape(-1, 1)
        self._lut = lut.astype(np.float32)
        offsets = scan_record_offsets(path_imgrec)
        if not offsets:
            raise ValueError('empty record file %s' % path_imgrec)
        # full offset list retained: set_shard (elastic input
        # re-balancing, telemetry/cluster.py) re-slices it without a
        # re-scan; the slice applies at the next start_epoch
        self._all_offsets = offsets
        self.num_parts = int(num_parts)
        self.part_index = int(part_index)
        self._offsets = offsets[part_index::num_parts]
        logging.getLogger(__name__).debug(
            'ImageRecordIter: %d records (%d after sharding %d/%d)',
            len(offsets), len(self._offsets), part_index, num_parts)
        self._producer = None
        self._stop = None
        self._q = None

    def set_shard(self, part_index):
        """Move this reader onto shard ``part_index`` of the same
        ``num_parts`` partition. The live producer (if any) keeps its
        epoch; the new slice applies at the next start_epoch."""
        self.part_index = int(part_index) % max(1, self.num_parts)
        self._offsets = self._all_offsets[self.part_index::self.num_parts]

    # -- epoch lifecycle ---------------------------------------------------
    def start_epoch(self):
        self.stop()
        # seeds drawn on the caller thread from the framework host RNG,
        # so mx.random.seed() makes epochs reproducible
        seed = int(_random.host_rng().randint(0, 2 ** 31 - 1))
        order = np.array(self._offsets)
        if self.shuffle:
            np.random.RandomState(seed).shuffle(order)
        self._stop = threading.Event()
        self._q = _queue.Queue(maxsize=self.prefetch)
        self._producer = threading.Thread(
            target=self._produce, args=(order, seed, self._q, self._stop),
            daemon=True)
        self._producer.start()

    def stop(self):
        if self._producer is not None:
            self._stop.set()
            while True:     # unblock a producer waiting on a full queue
                try:
                    self._q.get_nowait()
                except _queue.Empty:
                    break
            self._producer.join(timeout=10)
            self._producer = None

    def next_batch(self):
        """(data, label, pad) or None at epoch end."""
        if self._producer is None:
            self.start_epoch()
        item = self._q.get()
        if item is None:
            self._producer.join(timeout=10)
            self._producer = None
            return None
        if isinstance(item, BaseException):
            self._producer = None
            raise item
        return item

    # -- producer ----------------------------------------------------------
    def _produce(self, order, seed, q, stop):
        try:
            reader = MXRecordIO(self.path, 'r')
            pool = ThreadPoolExecutor(self.threads)
            try:
                B = self.batch_size
                n = len(order)
                if self.device_augment and self._src_hw is None and n:
                    # infer the uniform source size on THIS thread before
                    # the pool fans out (avoids a first-batch write race)
                    reader.seek_pos(int(order[0]))
                    self._decode_fixed(reader.read())
                for start in range(0, n, B):
                    if stop.is_set():
                        return
                    idxs = list(range(start, min(start + B, n)))
                    npad = 0
                    if len(idxs) < B:
                        if not self.round_batch:
                            break
                        npad = B - len(idxs)
                        # wrap cyclically (round_batch): modulo handles
                        # shards smaller than one batch
                        idxs += [i % n for i in range(npad)]
                    raws = []
                    for i in idxs:
                        reader.seek_pos(int(order[i]))
                        raws.append(reader.read())
                    # all augmentation randomness drawn HERE in bulk
                    # (one RandomState per batch, seeded from the epoch
                    # seed) — workers stay rng-free and cheap
                    if self.device_augment and self.host_crop:
                        brng = np.random.RandomState(
                            (seed + start) & 0x7fffffff)
                        draws = brng.uniform(size=(len(idxs), 2))
                        recs = list(pool.map(
                            self._decode_fixed_crop, raws, draws))
                    elif self.device_augment:
                        recs = list(pool.map(self._decode_fixed, raws))
                    else:
                        brng = np.random.RandomState(
                            (seed + start) & 0x7fffffff)
                        draws = brng.uniform(size=(len(idxs), 4))
                        recs = list(pool.map(
                            self._decode_augment, raws, draws))
                    data = np.stack([r[0] for r in recs])
                    label = np.stack([r[1] for r in recs])
                    if self.label_width == 1:
                        label = label.reshape(B)
                    while not stop.is_set():
                        try:
                            q.put((data, label, npad), timeout=0.1)
                            break
                        except _queue.Full:
                            continue
                    else:
                        return
            finally:
                pool.shutdown(wait=False)
                reader.close()
            q.put(None)
        except BaseException as e:  # noqa: BLE001 — surface in consumer
            # the queue may be full for a long time (consumer inside a
            # multi-second device call): make room by discarding a
            # buffered batch and retry, so the error ALWAYS reaches the
            # consumer instead of leaving it blocked on get() forever
            while not stop.is_set():
                try:
                    q.put(e, timeout=0.1)
                    return
                except _queue.Full:
                    try:
                        q.get_nowait()
                    except _queue.Empty:
                        pass

    # -- per-image work (worker threads; numpy/PIL only, never jax) -------
    def _label_of(self, header):
        lab = np.atleast_1d(np.asarray(header.label, np.float32))
        if self.label_width == 1:
            return lab[:1]
        return np.pad(lab[:self.label_width],
                      (0, max(0, self.label_width - lab.size)))

    def _coerce_channels(self, img):
        C = self.data_shape[0]
        if img.shape[2] != C:
            if C == 3 and img.shape[2] == 1:
                img = np.repeat(img, 3, axis=2)
            elif C == 1:
                img = img.mean(axis=2, keepdims=True).astype(img.dtype)
        return img

    def _decode_fixed(self, raw):
        """device_augment worker: decode to a FIXED-SIZE uint8 HWC image
        (resize-short + pad + center-crop-to-square when `resize` is
        set; fixed-size records pass through, padded up to the crop
        size if needed). All randomness and all float math happen on
        device."""
        header, payload = unpack(raw)
        img = self._coerce_channels(_decode_hwc(payload))
        _, H, W = self.data_shape
        if self.resize > 0:
            img = _resize_short(img, self.resize)
            if self.pad > 0:
                img = np.pad(img, ((self.pad, self.pad),
                                   (self.pad, self.pad), (0, 0)),
                             constant_values=self.fill_value)
            S = self._src_hw[0]
            ih, iw = img.shape[:2]
            # place the square so the device's later center crop lands
            # exactly where the host path's single (long-crop)//2 crop
            # would (the naive (long-S)//2 is off by 1 px when both
            # parities are odd)
            y = min(max(0, (ih - H) // 2 - (S - H) // 2), max(0, ih - S))
            x = min(max(0, (iw - W) // 2 - (S - W) // 2), max(0, iw - S))
            img = img[y:y + S, x:x + S]
            if img.shape[0] < S or img.shape[1] < S:
                img = np.pad(img, ((0, S - img.shape[0]),
                                   (0, S - img.shape[1]), (0, 0)),
                             constant_values=self.fill_value)
        else:
            # same semantics as the host path: `pad` always applies,
            # and undersized records are padded up to the crop size
            if self.pad > 0:
                img = np.pad(img, ((self.pad, self.pad),
                                   (self.pad, self.pad), (0, 0)),
                             constant_values=self.fill_value)
            ih, iw = img.shape[:2]
            if ih < H or iw < W:
                img = np.pad(img, ((0, max(0, H - ih)),
                                   (0, max(0, W - iw)), (0, 0)),
                             constant_values=self.fill_value)
            if self._src_hw is None:
                self._src_hw = img.shape[:2]
            if img.shape[:2] != self._src_hw:
                raise ValueError(
                    'device_augment without resize needs uniform record '
                    'sizes: got %s after %s — set resize=<short side>'
                    % (img.shape[:2], self._src_hw))
        return img, self._label_of(header)

    def _decode_fixed_crop(self, raw, draws):
        """host-crop worker: the fixed-size image of _decode_fixed,
        then the crop applied HOST-side with the producer's per-image
        uniforms — (H, W, C) uint8 out. Offsets use the host-augment
        path's exact formulas (center: (S-H)//2; random:
        int(u * (S-H+1))), so randomness-off pixels match the
        device-crop path bit-for-bit."""
        u_y, u_x = draws
        img, lab = self._decode_fixed(raw)
        _, H, W = self.data_shape
        ih, iw = img.shape[:2]
        if self.rand_crop:
            y = int(u_y * (ih - H + 1))
            x = int(u_x * (iw - W + 1))
        else:
            y, x = (ih - H) // 2, (iw - W) // 2
        return img[y:y + H, x:x + W], lab

    def _decode_augment(self, raw, draws):
        """``draws`` = 4 uniforms from the producer's per-batch stream:
        (scale jitter, crop-y, crop-x, mirror coin)."""
        u_scale, u_y, u_x, u_flip = draws
        header, payload = unpack(raw)
        img = _decode_hwc(payload)
        C, H, W = self.data_shape
        if self.resize > 0:
            img = _resize_short(img, self.resize)
        if self.pad > 0:
            img = np.pad(img, ((self.pad, self.pad), (self.pad, self.pad),
                               (0, 0)), constant_values=self.fill_value)
        # random scale jitter: resample the crop SOURCE size, so the
        # crop covers a larger/smaller field of view at fixed output
        if self.max_random_scale > self.min_random_scale:
            s = self.min_random_scale + u_scale * \
                (self.max_random_scale - self.min_random_scale)
        else:
            s = self.max_random_scale
        if s != 1.0:
            img = _resize(img, max(H, int(round(img.shape[0] * s))),
                          max(W, int(round(img.shape[1] * s))))
        ih, iw = img.shape[:2]
        if ih < H or iw < W:
            img = np.pad(img, ((0, max(0, H - ih)), (0, max(0, W - iw)),
                               (0, 0)), constant_values=self.fill_value)
            ih, iw = img.shape[:2]
        if self.rand_crop:           # per-image random crop offset
            y = int(u_y * (ih - H + 1))
            x = int(u_x * (iw - W + 1))
        else:                        # center crop (reference default)
            y, x = (ih - H) // 2, (iw - W) // 2
        img = img[y:y + H, x:x + W]
        if self.rand_mirror and u_flip < 0.5:       # per-image coin
            img = img[:, ::-1]
        img = self._coerce_channels(img)
        # fused scale/mean/std via the per-channel uint8 LUT
        chw = np.empty((C, H, W), np.float32)
        for c in range(C):
            np.take(self._lut[c], img[:, :, c], out=chw[c])

        return chw, self._label_of(header)
