"""Data iterators.

Reference: python/mxnet/io.py (932 LoC: DataDesc/DataBatch, DataIter,
NDArrayIter:516, PrefetchingIter:343, ResizeIter, MXDataIter) + src/io/
(MNISTIter, CSVIter, ImageRecordIter family — the C++ decode→augment→batch
→prefetch pipeline).

TPU-native: host-side pipelines feed device arrays; the PrefetchingIter
double-buffers with a background thread (the engine-façade host worker),
overlapping host IO with device compute like the reference's
PrefetcherIter (src/io/iter_prefetcher.h:46).
"""
from collections import namedtuple
import os
import struct
import gzip
import threading
import time

import numpy as np

from .. import random as _random
from .. import telemetry as _tele

from ..ndarray import NDArray, array
from ..base import MXNetError

__all__ = ['DataDesc', 'DataBatch', 'DataIter', 'NDArrayIter', 'CSVIter',
           'MNISTIter', 'ResizeIter', 'PrefetchingIter', 'ImageRecordIter',
           'ImageDetRecordIter', 'LibSVMIter', 'MXDataIter', 'auto_shard']


def auto_shard():
    """``{'num_parts': P, 'part_index': i}`` derived from the LIVE
    process set — construct data iterators with ``**mx.io.auto_shard()``
    and an elastic job keeps every example covered exactly once however
    many hosts survive: a supervisor relaunch onto fewer hosts
    re-derives the shard ranges from the smaller set instead of leaving
    the dead host's shard orphaned (module/checkpointing.py remaps the
    resumed iterator cursor to match). Prefers the launcher env
    (MXTPU_NUM_HOSTS / MXTPU_HOST_ID — tools/launch.py exports both);
    falls back to jax's process set when the env is silent but
    jax.distributed is up."""
    n, i = 1, 0
    try:
        from ..config import flags
        flags.reload('MXTPU_NUM_HOSTS')
        flags.reload('MXTPU_HOST_ID')
        n = int(flags.get('MXTPU_NUM_HOSTS'))
        i = int(flags.get('MXTPU_HOST_ID'))
    except Exception:  # noqa: BLE001 — stripped builds without the flags
        pass
    if n <= 1:
        try:
            import jax
            n = int(jax.process_count())
            i = int(jax.process_index())
        except Exception:  # noqa: BLE001 — backend not up yet
            pass
    n = max(1, n)
    return {'num_parts': n, 'part_index': i % n}


class DataDesc(namedtuple('DataDesc', ['name', 'shape'])):
    """Reference io.py DataDesc (name, shape, dtype, layout)."""

    def __new__(cls, name, shape, dtype=np.float32, layout='NCHW'):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find('N')


class DataBatch:
    """Reference io.py DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), 'Data must be list of NDArrays'
        if label is not None:
            assert isinstance(label, (list, tuple)), 'Label must be list of NDArrays'
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Reference io.py:176."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            _tele.counter('io.batches').inc()
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


def _init_data(data, allow_empty, default_name):
    """Reference io.py:476 _init_data."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {('_%d_%s' % (i, default_name)): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError('Input must be NDArray, numpy.ndarray, a list of them '
                        'or dict with them as values')
    out = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            v = array(np.asarray(v))
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """In-memory iterator with pad/discard/roll_over (reference io.py:516)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle='pad', data_name='data',
                 label_name='softmax_label'):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            _random.host_rng().shuffle(self.idx)
        self._shuffle = shuffle

        if last_batch_handle == 'discard':
            new_n = self.data[0][1].shape[0] - self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]

        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            'batch_size needs to be smaller than data size.'
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle
        # numpy staging for fast fancy-indexing
        self._np_data = [x[1].asnumpy() for x in self.data]
        self._np_label = [x[1].asnumpy() for x in self.label]

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self._shuffle:
            _random.host_rng().shuffle(self.idx)
        if self.last_batch_handle == 'roll_over' and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            _tele.counter('io.batches').inc()
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)
        raise StopIteration

    def _getdata(self, arrays):
        assert self.cursor < self.num_data, 'DataIter needs reset.'
        if self.cursor + self.batch_size <= self.num_data:
            sel = self.idx[self.cursor:self.cursor + self.batch_size]
        else:
            pad = self.batch_size - self.num_data + self.cursor
            sel = np.concatenate([self.idx[self.cursor:], self.idx[:pad]])
        return [array(a[sel]) for a in arrays]

    def getdata(self):
        return self._getdata(self._np_data)

    def getlabel(self):
        return self._getdata(self._np_label)

    def getpad(self):
        if self.last_batch_handle == 'pad' and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize the epoch length of an iterator (reference io.py:288)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread double-buffering (reference io.py:343 / iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i], daemon=True)
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        if _tele.enabled():
            # how long the consumer stalled on the producer thread(s) —
            # the "is the input pipeline the bottleneck?" histogram
            t0 = time.time()
            for e in self.data_ready:
                e.wait()
            _tele.histogram('io.prefetch_wait').observe(
                (time.time() - t0) * 1e3)
        else:
            for e in self.data_ready:
                e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, 'Number of entry mismatches between iterators'
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                'Number of entry mismatches between iterators'
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index,
            provide_data=self.provide_data, provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        # no io.batches inc here: the producer thread's inner
        # iters[i].next() calls already count each batch once
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _read_mnist_images(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic, num, rows, cols = struct.unpack('>IIII', f.read(16))
        assert magic == 2051
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(num, rows, cols)


def _read_mnist_labels(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic, num = struct.unpack('>II', f.read(8))
        assert magic == 2049
        return np.frombuffer(f.read(), dtype=np.uint8)


class MNISTIter(NDArrayIter):
    """Reference src/io/iter_mnist.cc — reads idx-format files.

    If the files are absent, generates a deterministic synthetic set with
    class-separable structure so training/convergence tests run hermetically.
    """

    def __init__(self, image='train-images-idx3-ubyte', label='train-labels-idx1-ubyte',
                 batch_size=128, shuffle=True, flat=False, silent=False,
                 seed=0, num_parts=1, part_index=0, input_shape=None, **kwargs):
        if os.path.exists(image) or os.path.exists(image + '.gz'):
            img_path = image if os.path.exists(image) else image + '.gz'
            lab_path = label if os.path.exists(label) else label + '.gz'
            images = _read_mnist_images(img_path).astype(np.float32) / 255.0
            labels = _read_mnist_labels(lab_path).astype(np.float32)
        else:
            images, labels = synthetic_mnist(12000 if 'train' in image else 2000,
                                             seed=seed)
        # the full (pre-shard) set is kept ONLY for genuinely sharded
        # construction, so an elastic re-balance (telemetry/cluster.py
        # apply_shard_shift) can re-slice it: set_shard(j) rebuilds
        # this iterator on shard j of num_parts. Unsharded iterators
        # (num_parts=1 — elastic has nothing to rotate and disables
        # itself) don't pay the extra retention
        self._shard_full = (images, labels) if num_parts > 1 else None
        self._shard_args = dict(batch_size=batch_size, shuffle=shuffle,
                                flat=flat, seed=seed)
        self._num_parts = int(num_parts)
        self._part_index = int(part_index)
        self._shard_init(images, labels)

    def _shard_init(self, images, labels):
        a = self._shard_args
        if self._num_parts > 1:
            images = images[self._part_index::self._num_parts]
            labels = labels[self._part_index::self._num_parts]
        if a['flat']:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1, 28, 28)
        if a['shuffle']:
            # reference iter_mnist.cc shuffles ONCE at init with `seed`;
            # reset() rewinds to the SAME order. Scripts rely on this:
            # e.g. module/mnist_mlp.py aligns predict(merge_batches=False)
            # outputs against a second pass of the iterator by index.
            perm = np.random.RandomState(a['seed']).permutation(len(labels))
            images, labels = images[perm], labels[perm]
        super().__init__(images, labels, batch_size=a['batch_size'],
                         shuffle=False, last_batch_handle='discard',
                         label_name='softmax_label')

    def shard_info(self):
        """(num_parts, part_index) — the elastic-input shard protocol."""
        return self._num_parts, self._part_index

    def set_shard(self, part_index):
        """Re-slice this iterator onto shard ``part_index`` of the same
        ``num_parts`` partition (elastic input re-balancing; the rebuilt
        order is deterministic from the original seed). Takes effect
        immediately — callers apply it at an epoch boundary. A no-op on
        unsharded iterators (num_parts=1: there is only shard 0)."""
        if self._shard_full is None:
            return
        self._part_index = int(part_index) % max(1, self._num_parts)
        images, labels = self._shard_full
        self._shard_init(images, labels)


def synthetic_mnist(n, seed=0):
    """Class-separable synthetic digits: 10 fixed random prototype images +
    noise. Linearly separable enough for LeNet/MLP convergence tests."""
    protos = np.random.RandomState(42).rand(10, 28, 28).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n)
    images = protos[labels] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
    return np.clip(images, 0, 1).astype(np.float32), labels.astype(np.float32)


class CSVIter(NDArrayIter):
    """Reference src/io/iter_csv.cc."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, data_name='data',
                 label_name='softmax_label', **kwargs):
        data = np.loadtxt(data_csv, delimiter=',', dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=',', dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros(data.shape[0], dtype=np.float32)
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle='pad' if round_batch else 'discard',
                         data_name=data_name, label_name=label_name)


class LibSVMIter(DataIter):
    """Reference src/io/iter_libsvm.cc — sparse libsvm text format.

    Each line: ``label [label...] idx:value idx:value ...`` (indices
    0-based like the reference's default). Batches come out as
    CSRNDArray data (the sparse path the reference feeds to sparse
    FullyConnected / linear models) with dense label arrays. An optional
    separate ``label_libsvm`` file provides multi-dim sparse labels,
    densified per batch.
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True,
                 data_name='data', label_name='softmax_label', **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape) if not isinstance(
            data_shape, int) else (data_shape,)
        ncol = int(np.prod(self.data_shape))
        rows, labels = self._parse(data_libsvm, ncol)
        self._csr = rows                     # scipy csr [N, ncol]
        if label_libsvm is not None:
            lab_ncol = int(np.prod(label_shape)) if label_shape else 1
            lab, _ = self._parse(label_libsvm, lab_ncol, labels_inline=False)
            self._labels = np.asarray(lab.todense(), np.float32)
        else:
            self._labels = np.asarray(labels, np.float32)
        self.num_data = self._csr.shape[0]
        if self.num_data < batch_size:
            raise ValueError('fewer rows (%d) than batch_size (%d)'
                             % (self.num_data, batch_size))
        self.round_batch = round_batch
        # naming matches the reference frontend: every C++-registered
        # iterator surfaces through MXDataIter whose defaults are
        # data_name='data', label_name='softmax_label' (python io.py:766)
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        lshape = (batch_size,) if self._labels.ndim == 1 else \
            (batch_size,) + self._labels.shape[1:]
        self.provide_label = [DataDesc(label_name, lshape)]
        self.reset()

    @staticmethod
    def _parse(path, ncol, labels_inline=True):
        import scipy.sparse as sp
        data, indices, indptr, labels = [], [], [0], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                i = 0
                if labels_inline:
                    labels.append(float(parts[0]))
                    i = 1
                for tok in parts[i:]:
                    idx, val = tok.split(':')
                    indices.append(int(idx))
                    data.append(float(val))
                indptr.append(len(data))
        mat = sp.csr_matrix(
            (np.asarray(data, np.float32),
             np.asarray(indices, np.int64), np.asarray(indptr, np.int64)),
            shape=(len(indptr) - 1, ncol))
        return mat, np.asarray(labels, np.float32)

    def reset(self):
        self._cursor = 0

    def iter_next(self):
        if self._cursor + self.batch_size <= self.num_data:
            return True
        if self.round_batch and self._cursor < self.num_data:
            return True
        return False

    def next(self):
        if not self.iter_next():
            raise StopIteration
        from ..ndarray.sparse import csr_matrix as _csr_nd
        start = self._cursor
        stop = start + self.batch_size
        pad = 0
        if stop <= self.num_data:
            sub = self._csr[start:stop]
            lab = self._labels[start:stop]
        else:  # wrap-around pad (reference round_batch semantics)
            pad = stop - self.num_data
            import scipy.sparse as sp
            sub = sp.vstack([self._csr[start:], self._csr[:pad]]).tocsr()
            lab = np.concatenate([self._labels[start:], self._labels[:pad]])
        self._cursor = stop
        data = _csr_nd((sub.data, sub.indices, sub.indptr),
                       shape=(self.batch_size,) + self.data_shape)
        from .. import ndarray as _nd
        return DataBatch(data=[data], label=[_nd.array(lab)], pad=pad,
                         index=None)

    def getpad(self):
        return 0


def _read_imgrec(path_imgrec, data_shape, scale, means, stds):
    """Shared RecordIO image loader: decode every record, normalize.

    Returns (data (N,C,H,W) float32, raw label list). Used by both
    ImageRecordIter and ImageDetRecordIter (reference shares this in
    ImageRecordIOParser)."""
    from ..recordio import MXRecordIO, unpack_img
    record = MXRecordIO(path_imgrec, 'r')
    images, labels = [], []
    while True:
        item = record.read()
        if item is None:
            break
        header, img = unpack_img(item, data_shape=tuple(data_shape))
        images.append(img)
        labels.append(header.label)
    record.close()
    if not images:
        raise ValueError('empty record file %s' % path_imgrec)
    data = np.stack(images).astype(np.float32) * scale
    mean = np.asarray(means, dtype=np.float32).reshape(3, 1, 1)
    std = np.asarray(stds, dtype=np.float32).reshape(3, 1, 1)
    if data.shape[1] == 3:
        data = (data - mean) / std
    return data, labels


class ImageRecordIter(DataIter):
    """Reference src/io/iter_image_recordio_2.cc — RecordIO image pipeline.

    Streaming (round 4): a framing-only offset scan at construction,
    then a producer thread + ``preprocess_threads`` decode/augment
    workers + a ``prefetch_buffer``-bounded batch queue
    (io/image_record.py). Memory is O(batch x prefetch), independent of
    dataset size; augmentation (rand_crop / rand_mirror / scale jitter
    / pad) is per-image, matching image_aug_default.cc.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, mean_r=0, mean_g=0, mean_b=0, std_r=1,
                 std_g=1, std_b=1, scale=1.0, rand_crop=False,
                 rand_mirror=False, preprocess_threads=4, round_batch=True,
                 prefetch_buffer=4, resize=-1, pad=0, fill_value=127,
                 max_random_scale=1.0, min_random_scale=1.0, num_parts=1,
                 part_index=0, data_name='data', label_name='softmax_label',
                 device_augment=None, host_crop=None, **kwargs):
        super().__init__(batch_size)
        from .image_record import StreamingImageRecordIter
        from ..config import flags
        self.data_shape = tuple(data_shape)
        self._data_name = data_name
        self._label_name = label_name
        self._label_width = label_width
        if device_augment is None:
            # opt-in for unmodified scripts: MXTPU_DEVICE_AUGMENT=1
            device_augment = flags.get('MXTPU_DEVICE_AUGMENT')
        self._device_augment = bool(int(device_augment or 0))
        if host_crop is None:
            host_crop = flags.get('MXTPU_HOST_CROP')
        self._host_crop = bool(int(host_crop or 0)) and self._device_augment
        self._aug_params = dict(
            scale=float(scale), mean=(mean_r, mean_g, mean_b),
            std=(std_r, std_g, std_b), rand_crop=bool(int(rand_crop)),
            rand_mirror=bool(int(rand_mirror)))
        self._aug_fn = None
        self._defer_aug = False
        self._stream = StreamingImageRecordIter(
            path_imgrec, self.data_shape, batch_size,
            label_width=label_width, shuffle=shuffle,
            mean=(mean_r, mean_g, mean_b), std=(std_r, std_g, std_b),
            scale=scale, rand_crop=rand_crop, rand_mirror=rand_mirror,
            preprocess_threads=preprocess_threads,
            prefetch_buffer=prefetch_buffer, round_batch=round_batch,
            resize=resize, pad=pad, fill_value=fill_value,
            max_random_scale=max_random_scale,
            min_random_scale=min_random_scale,
            num_parts=num_parts, part_index=part_index, aug_kwargs=kwargs,
            device_augment=self._device_augment, host_crop=self._host_crop)
        self._pending = None
        self._exhausted = False

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._label_width == 1 else \
            (self.batch_size, self._label_width)
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        self._stream.start_epoch()
        self._pending = None
        self._exhausted = False

    def shard_info(self):
        """(num_parts, part_index) — the elastic-input shard protocol
        (telemetry/cluster.py apply_shard_shift)."""
        return self._stream.num_parts, self._stream.part_index

    def set_shard(self, part_index):
        """Move this iterator onto another shard of the same partition;
        applies at the next reset() (epoch boundary)."""
        self._stream.set_shard(part_index)

    def next(self):
        if self._pending is not None:
            batch, self._pending = self._pending, None
            return batch
        if self._exhausted:
            raise StopIteration
        item = self._stream.next_batch()
        if item is None:
            self._exhausted = True
            raise StopIteration
        _tele.counter('io.batches').inc()
        data, label, pad = item
        from .. import ndarray as _nd
        if self._device_augment and self._defer_aug:
            # deferred mode (enabled by the fused fit loop via
            # defer_device_aug): hand over the raw uint8 batch AND its
            # label HOST-resident; the consumer stacks a whole window
            # and crosses to the device in ONE transfer, tracing
            # device_aug_pure() INSIDE its compiled program. Per-batch
            # device calls cost ~65-85 ms of pure dispatch latency on
            # a tunneled runtime (measured 2026-08-02, the 221 img/s
            # fed-fit plateau) — defer mode leaves zero of them
            import jax
            from ..context import current_context
            from ..ndarray.ndarray import from_jax
            ctx = current_context()
            try:
                host = jax.local_devices(backend='cpu')[0]
            except RuntimeError:   # no cpu backend: plain jnp arrays
                host = None

            def host_nd(a):
                # one host copy per batch (cpu-backend device_put);
                # the window stack's np.asarray may copy again — the
                # alternative (numpy inside NDArray._data) would break
                # the wrapper's jax-array invariant for ~2 ms/batch,
                # noise next to the 65-85 ms dispatches defer removes
                if host is not None:
                    arr = jax.device_put(np.ascontiguousarray(a), host)
                else:
                    import jax.numpy as jnp
                    arr = jnp.asarray(a)
                return from_jax(arr, ctx)

            return DataBatch(data=[host_nd(data)], label=[host_nd(label)],
                             pad=pad, index=None,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)
        elif self._device_augment:
            data_nd = self._apply_device_aug(data)
        else:
            data_nd = _nd.array(data)
        return DataBatch(data=[data_nd], label=[_nd.array(label)],
                         pad=pad, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def _apply_device_aug(self, data_u8):
        """One jitted device call: (B, S, S, C) uint8 → augmented
        (B, C, H, W) float32 (crop / mirror / scale-mean-std). The
        uint8 upload is 4x smaller than the host-augmented f32 batch,
        and the float math rides the accelerator instead of the
        decode-bound host cores (reference inline-augment role:
        src/io/iter_image_recordio_2.cc:122-130)."""
        import jax
        from .. import random as _random
        from ..ndarray.ndarray import from_jax
        from ..context import current_context
        if self._aug_fn is None:
            self._aug_fn = jax.jit(self.device_aug_pure())
        ctx = current_context()
        dev = jax.device_put(np.ascontiguousarray(data_u8),
                             ctx.jax_device())
        return from_jax(self._aug_fn(dev, _random.next_key()), ctx)

    def device_aug_pure(self):
        """The device-augment math as a PURE jax function
        ``(uint8 (B, Sh, Sw, C'), key) -> float32 (B, C, H, W)`` —
        source dims read off the traced batch, so one function serves
        any record geometry. Eager mode jits it per batch
        (_apply_device_aug); the fused fit loop traces it inside its
        window program instead (defer_device_aug), which removes the
        per-batch dispatch entirely."""
        import jax
        import jax.numpy as jnp
        C, H, W = self.data_shape
        p = self._aug_params
        # slice to the target channel count (grayscale data_shape
        # uses only the first channel's mean/std, like the host LUT)
        mean_c = tuple(p['mean'][:C])
        std_c = tuple(p['std'][:C])
        scale_v = float(p['scale'])
        rand_crop, rand_mirror = p['rand_crop'], p['rand_mirror']
        pre_cropped = self._host_crop

        def aug(batch, key):
            B = batch.shape[0]
            # source may be non-square (uniform raw records): crop
            # offsets range over each axis independently
            Sh, Sw = int(batch.shape[1]), int(batch.shape[2])
            mean = jnp.asarray(mean_c, jnp.float32)[:, None, None]
            std = jnp.asarray(std_c, jnp.float32)[:, None, None]
            ky, kx, kf = jax.random.split(key, 3)
            if pre_cropped:
                # host-crop mode: workers already cropped to (H, W) —
                # only mirror + normalize ride the device
                imgs = batch
            else:
                if rand_crop and (Sh > H or Sw > W):
                    ys = jax.random.randint(ky, (B,), 0, Sh - H + 1)
                    xs = jax.random.randint(kx, (B,), 0, Sw - W + 1)
                else:
                    ys = jnp.full((B,), (Sh - H) // 2, jnp.int32)
                    xs = jnp.full((B,), (Sw - W) // 2, jnp.int32)
                crop = lambda im, y, x: jax.lax.dynamic_slice(  # noqa: E731
                    im, (y, x, 0), (H, W, C))
                imgs = jax.vmap(crop)(batch, ys, xs)     # (B,H,W,C) u8
            if rand_mirror:
                coins = jax.random.uniform(kf, (B,)) < 0.5
                imgs = jnp.where(coins[:, None, None, None],
                                 imgs[:, :, ::-1, :], imgs)
            chw = imgs.transpose(0, 3, 1, 2).astype(jnp.float32)
            return (chw * jnp.float32(scale_v) - mean) / std

        return aug

    def device_aug_signature(self):
        """Hashable description of the augmentation MATH a consumer
        bakes into a compiled program (fused-fit defer mode): two
        iterators agreeing on this signature produce identical
        device_aug_pure functions, so compiled windows may be shared;
        any difference (mean/std/scale/rand flags/target shape) must
        compile a fresh window."""
        p = self._aug_params
        return ('image-record-aug', tuple(self.data_shape), p['scale'],
                tuple(p['mean']), tuple(p['std']),
                p['rand_crop'], p['rand_mirror'], self._host_crop)

    def defer_device_aug(self, on):
        """Switch deferred-augment mode (the compiled-window loops'
        internal protocol — module/fused_fit.py today): when on,
        next() returns RAW uint8 host batches and the consumer must
        apply device_aug_pure() itself (in-graph). Only meaningful in
        device-augment mode — returns whether the switch engaged.
        Always flip back off (try/finally) so other consumers of the
        same iterator see augmented batches again: the fused eval
        window (module/fused_eval.py) and the per-batch score/predict
        loops all draw through the eager per-batch augment path."""
        if not self._device_augment:
            return False
        self._defer_aug = bool(on)
        return True

    def iter_next(self):
        if self._pending is not None:
            return True
        if self._exhausted:
            return False
        try:
            self._pending = self.next()
            return True
        except StopIteration:
            return False


class ImageDetRecordIter(DataIter):
    """Detection RecordIO pipeline — reference src/io/iter_image_det_recordio.cc.

    Records are packed by tools/im2rec.py with ``--pack-label`` from a
    detection .lst: label = [header_width, object_width, (extra header...),
    then per-object rows of object_width values, conventionally
    [class_id, xmin, ymin, xmax, ymax, ...]].

    Labels are padded to a common (max_objects, object_width) block with
    ``label_pad_value`` (reference's DefaultPadLabel), so a batch is one
    dense (B, max_objects*object_width [+2 header]) array — dynamic object
    counts never reach the device, which is what XLA needs.
    """

    @staticmethod
    def _is_det_header(lab):
        """Packed-label detection header: [hdr_w>=2, obj_w>=1, ...] with the
        body an exact multiple of obj_w (iter_image_det_recordio.cc
        ImageDetLabelMap sanity checks)."""
        if lab.size < 2:
            return False
        hdr_w, ow = float(lab[0]), float(lab[1])
        if hdr_w < 2 or ow < 1 or hdr_w != int(hdr_w) or ow != int(ow):
            return False
        body = lab.size - int(hdr_w)
        return body >= 0 and body % int(ow) == 0

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=-1,
                 label_pad_width=-1, label_pad_value=-1.0, shuffle=False,
                 mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                 scale=1.0, rand_mirror=False, round_batch=True, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        data, raw_labels = _read_imgrec(path_imgrec, self.data_shape, scale,
                                        (mean_r, mean_g, mean_b),
                                        (std_r, std_g, std_b))

        # normalize labels to [hdr_w, obj_w, objects...]
        parsed = []
        max_objs = 0
        obj_w = None
        for rec_i, lab in enumerate(raw_labels):
            lab = np.atleast_1d(np.asarray(lab, dtype=np.float32))
            if self._is_det_header(lab):
                ow = int(lab[1])
                body = lab[int(lab[0]):]
            else:  # plain label row: promote to 1 object row
                ow = max(int(lab.size), 1)
                body = lab
            if obj_w is None:
                obj_w = ow
            elif ow != obj_w:
                raise ValueError(
                    'record %d: inconsistent object width: %d vs %d'
                    % (rec_i, ow, obj_w))
            objs = body.reshape(-1, obj_w) if body.size else \
                np.zeros((0, obj_w), np.float32)
            parsed.append(objs)
            max_objs = max(max_objs, objs.shape[0])
        # the flat label pads to EXACTLY label_pad_width (or wider if the
        # data needs it) so train/val iterators built with the same pad
        # width always shape-match — the request need not be object-aligned
        width = 2 + max_objs * obj_w
        if label_pad_width > 0:
            width = max(width, label_pad_width)
        self.label_object_width = obj_w
        self.max_objects = max_objs

        label = np.full((len(parsed), width), label_pad_value,
                        dtype=np.float32)
        label[:, 0] = 2.0
        label[:, 1] = float(obj_w)
        for i, objs in enumerate(parsed):
            label[i, 2:2 + objs.size] = objs.ravel()

        self._inner = NDArrayIter(
            data, label, batch_size=batch_size, shuffle=shuffle,
            last_batch_handle='pad' if round_batch else 'discard')
        self._rand_mirror = rand_mirror
        self._pad_value = label_pad_value

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def _mirror_batch(self, batch):
        """Horizontal flip + x-coordinate label flip (reference
        DefaultImageDetAugmenter HorizontalFlip: normalized [0,1] coords,
        xmin' = 1-xmax, xmax' = 1-xmin for [id,xmin,ymin,xmax,ymax,...])."""
        data = [d.flip(axis=3) if d.ndim == 4 else d for d in batch.data]
        labels = []
        for lab_nd in batch.label:
            lab = lab_nd.asnumpy().copy()
            ow = self.label_object_width
            if ow >= 5:
                # only the object-aligned block holds boxes; any extra
                # label_pad_width tail cells are pure padding
                end = 2 + self.max_objects * ow
                objs = lab[:, 2:end].reshape(lab.shape[0], -1, ow)
                valid = objs[:, :, 0] != self._pad_value
                xmin = objs[:, :, 1].copy()
                xmax = objs[:, :, 3].copy()
                objs[:, :, 1] = np.where(valid, 1.0 - xmax, objs[:, :, 1])
                objs[:, :, 3] = np.where(valid, 1.0 - xmin, objs[:, :, 3])
                lab[:, 2:end] = objs.reshape(lab.shape[0], -1)
            labels.append(array(lab))
        return DataBatch(data, labels, batch.pad, batch.index,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def next(self):
        batch = self._inner.next()
        if self._rand_mirror and _random.host_rng().rand() < 0.5:
            batch = self._mirror_batch(batch)
        return batch

    def iter_next(self):
        return self._inner.iter_next()


class MXDataIter(DataIter):
    """Wrapper around an engine-owned iterator handle (reference
    io.py:758 wraps a ctypes DataIterHandle; here the handle IS the
    underlying python iterator object — the same object the C ABI's
    MXDataIterCreateIter hands out through the embedded interpreter).
    Exposes the handle-style protocol: next/getdata/getlabel/getpad
    with single-buffer semantics."""

    def __init__(self, handle, data_name='data',
                 label_name='softmax_label', **_):
        if not isinstance(handle, DataIter):
            raise TypeError('MXDataIter wraps a data-iterator handle; '
                            'got %r' % (handle,))
        super().__init__(getattr(handle, 'batch_size', 1))
        self.handle = handle
        self._debug_skip_load = False
        self.first_batch = handle.next()
        data = self.first_batch.data[0]
        self.provide_data = [DataDesc(data_name, data.shape, data.dtype)]
        if self.first_batch.label:
            label = self.first_batch.label[0]
            self.provide_label = [DataDesc(label_name, label.shape,
                                           label.dtype)]
        else:
            self.provide_label = []
        self._current = None

    def debug_skip_load(self):
        """Reference parity: skip loading and return the first batch."""
        self._debug_skip_load = True

    def reset(self):
        self._current = None
        self.first_batch = None
        self.handle.reset()

    def next(self):
        if self._debug_skip_load and self.first_batch is not None:
            self._current = self.first_batch
            return self.first_batch
        if self.first_batch is not None:
            batch, self.first_batch = self.first_batch, None
            self._current = batch
            return batch
        self._current = self.handle.next()
        return self._current

    def iter_next(self):
        try:
            self.next()
            return True
        except StopIteration:
            self._current = None
            return False

    def getdata(self):
        return self._current.data[0]

    def getlabel(self):
        return self._current.label[0] if self._current.label else None

    def getindex(self):
        return getattr(self._current, 'index', None)

    def getpad(self):
        return getattr(self._current, 'pad', 0) or 0
