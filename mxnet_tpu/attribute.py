"""Attribute scoping for symbols.

Reference: python/mxnet/attribute.py (AttrScope — carries ctx_group for
manual model parallelism, lr_mult/wd_mult etc.) and python/mxnet/name.py
(NameManager/Prefix auto-naming).
"""
import threading

__all__ = ['AttrScope', 'NameManager', 'Prefix']

_local = threading.local()


class AttrScope:
    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError('Attributes need to be strings')
        self._attr = kwargs

    def get(self, attr):
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        if not hasattr(_local, 'attr_stack'):
            _local.attr_stack = [AttrScope()]
        merged = dict(_local.attr_stack[-1]._attr)
        merged.update(self._attr)
        scope = AttrScope.__new__(AttrScope)
        scope._attr = merged
        _local.attr_stack.append(scope)
        return self

    def __exit__(self, *args):
        _local.attr_stack.pop()

    @staticmethod
    def current():
        if not hasattr(_local, 'attr_stack'):
            _local.attr_stack = [AttrScope()]
        return _local.attr_stack[-1]


class NameManager:
    """Auto-namer for symbols (reference name.py:27)."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name:
            return name
        hint = hint.lower()
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return '%s%d' % (hint, idx)

    def __enter__(self):
        if not hasattr(_local, 'name_stack'):
            _local.name_stack = [NameManager()]
        _local.name_stack.append(self)
        return self

    def __exit__(self, *args):
        _local.name_stack.pop()

    @staticmethod
    def current():
        if not hasattr(_local, 'name_stack'):
            _local.name_stack = [NameManager()]
        return _local.name_stack[-1]


class Prefix(NameManager):
    """Prepends a prefix to every auto-generated name (reference name.py:74)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return name if name else self._prefix + super().get(None, hint)
