"""Network visualization / summary.

Reference: python/mxnet/visualization.py — print_summary (per-layer
params table) and plot_network (graphviz; gated on availability here).
"""
import json

__all__ = ['print_summary', 'plot_network']


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Reference visualization.py:26 print_summary."""
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError('Input shape is incomplete')
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf['nodes']
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ['Layer (type)', 'Output Shape', 'Param #', 'Previous Layer']

    def print_row(fields, positions):
        line = ''
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += ' ' * (positions[i] - len(line))
        print(line)

    print('_' * line_length)
    print_row(to_display, positions)
    print('=' * line_length)

    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node['op']
        pre_node = []
        pre_filter = 0
        if op != 'null':
            inputs = node['inputs']
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node['name']
                if input_node['op'] != 'null' or item[0] in heads:
                    pre_node.append(input_name)
                    if show_shape:
                        key = input_name + '_output' if input_node['op'] != 'null' \
                            else input_name
                        if key in shape_dict:
                            shape = shape_dict[key][1:]
                            pre_filter = pre_filter + int(shape[0]) if shape else 0
        cur_param = 0
        attrs = node.get('attrs', {})
        if op == 'Convolution':
            num_group = int(attrs.get('num_group', '1'))
            kernel = eval(attrs['kernel']) if isinstance(attrs.get('kernel'), str) \
                else attrs.get('kernel', ())
            import numpy as _np
            cur_param = pre_filter * int(attrs['num_filter']) // num_group * \
                int(_np.prod(kernel))
            if attrs.get('no_bias') not in ('True', True):
                cur_param += int(attrs['num_filter'])
        elif op == 'FullyConnected':
            if attrs.get('no_bias') in ('True', True):
                cur_param = pre_filter * int(attrs['num_hidden'])
            else:
                cur_param = (pre_filter + 1) * int(attrs['num_hidden'])
        elif op == 'BatchNorm':
            key = node['name'] + '_output'
            if show_shape and key in shape_dict:
                num_filter = shape_dict[key][1]
                cur_param = int(num_filter) * 2
        first_connection = '' if not pre_node else pre_node[0]
        fields = [node['name'] + '(' + op + ')',
                  'x'.join([str(x) for x in out_shape]),
                  cur_param, first_connection]
        print_row(fields, positions)
        if len(pre_node) > 1:
            for i in range(1, len(pre_node)):
                fields = ['', '', '', pre_node[i]]
                print_row(fields, positions)
        return cur_param

    heads = set(conf['arg_nodes'])
    for i, node in enumerate(nodes):
        out_shape = []
        op = node['op']
        if op == 'null' and i > 0:
            continue
        if op != 'null' or i in heads:
            if show_shape:
                key = node['name'] + '_output' if op != 'null' else node['name']
                if key in shape_dict:
                    out_shape = shape_dict[key][1:]
        total_params[0] += print_layer_summary(node, out_shape)
        if i == len(nodes) - 1:
            print('=' * line_length)
        else:
            print('_' * line_length)
    print('Total params: {params}'.format(params=total_params[0]))
    print('_' * line_length)


def plot_network(symbol, title='plot', save_format='pdf', shape=None,
                 node_attrs=None, hide_weights=True):
    """Reference visualization.py plot_network (graphviz)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError('plot_network requires graphviz; '
                          'use print_summary instead')
    conf = json.loads(symbol.tojson())
    nodes = conf['nodes']
    dot = Digraph(name=title)
    for i, node in enumerate(nodes):
        name = node['name']
        if node['op'] == 'null':
            if hide_weights and (name.endswith('_weight') or
                                 name.endswith('_bias') or
                                 name.endswith('_gamma') or
                                 name.endswith('_beta') or
                                 name.endswith('moving_mean') or
                                 name.endswith('moving_var')):
                continue
            dot.node(name=name, label=name, shape='oval')
        else:
            dot.node(name=name, label='%s\n%s' % (name, node['op']),
                     shape='box')
        for item in node.get('inputs', []):
            input_node = nodes[item[0]]
            if input_node['op'] == 'null' and hide_weights and (
                    input_node['name'].endswith('_weight') or
                    input_node['name'].endswith('_bias') or
                    input_node['name'].endswith('_gamma') or
                    input_node['name'].endswith('_beta') or
                    'moving' in input_node['name']):
                continue
            dot.edge(input_node['name'], name)
    return dot
