"""Device context — TPU-native analog of MXNet's Context.

Reference: python/mxnet/context.py (Context, mx.cpu()/mx.gpu(), current_context)
and include/mxnet/base.h (Context struct, dev_type/dev_id).

Design: a Context names a JAX device. ``tpu(i)`` maps to the i-th TPU chip;
``cpu(i)`` maps to the i-th host CPU device (with
``--xla_force_host_platform_device_count=N`` this gives the multi-device-
without-a-cluster testing story the reference got from ``mx.cpu(1..n)``,
tests/python/unittest/test_multi_device_exec.py). ``gpu(i)`` is accepted for
API compatibility and resolves to the best available accelerator.
"""
import threading

import jax

__all__ = ['Context', 'cpu', 'gpu', 'tpu', 'cpu_pinned', 'current_context', 'num_gpus', 'num_tpus']

_thread_local = threading.local()


class Context:
    """Execution device. Immutable, hashable, usable as a `with` scope."""

    devtype2str = {1: 'cpu', 2: 'gpu', 3: 'cpu_pinned', 4: 'tpu'}
    devstr2type = {'cpu': 1, 'gpu': 2, 'cpu_pinned': 3, 'tpu': 4}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if isinstance(device_type, str):
                device_type = self.devstr2type[device_type]
            self.device_typeid = device_type
            self.device_id = device_id
        self._jax_device = None

    def __getstate__(self):
        # the cached jax Device is process-local and unpicklable
        return {'device_typeid': self.device_typeid,
                'device_id': self.device_id}

    def __setstate__(self, state):
        self.device_typeid = state['device_typeid']
        self.device_id = state['device_id']
        self._jax_device = None

    @property
    def device_type(self):
        return self.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __repr__(self):
        return '%s(%d)' % (self.device_type, self.device_id)

    def __enter__(self):
        if not hasattr(_thread_local, 'stack'):
            _thread_local.stack = []
        _thread_local.stack.append(self)
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        _thread_local.stack.pop()

    # -- JAX mapping ------------------------------------------------------
    def jax_device(self):
        """Resolve this context to a concrete jax.Device (cached)."""
        if self._jax_device is None:
            self._jax_device = _resolve_device(self.device_type, self.device_id)
        return self._jax_device

    def empty_cache(self):
        """MXNet API compat (GPU mem pool flush). No-op: XLA owns HBM."""


def _platform_devices(platform):
    try:
        return jax.devices(platform)
    except RuntimeError:
        return []


def _resolve_device(device_type, device_id):
    if device_type == 'cpu' or device_type == 'cpu_pinned':
        devs = _platform_devices('cpu')
        if not devs:  # TPU-only runtime: fall back to default devices
            devs = jax.devices()
        return devs[device_id % len(devs)]
    # accelerator request: prefer tpu, then gpu, then cpu (so tests run anywhere)
    for plat in ('tpu', 'gpu', 'cpu'):
        devs = _platform_devices(plat)
        if devs:
            return devs[device_id % len(devs)]
    raise RuntimeError('no jax devices available')


def cpu(device_id=0):
    return Context('cpu', device_id)


def cpu_pinned(device_id=0):
    return Context('cpu_pinned', device_id)


def gpu(device_id=0):
    """Compatibility alias: resolves to the best available accelerator."""
    return Context('gpu', device_id)


def tpu(device_id=0):
    return Context('tpu', device_id)


def num_gpus():
    return len(_platform_devices('gpu')) or len(_platform_devices('tpu'))


def num_tpus():
    return len(_platform_devices('tpu'))


def current_context():
    if getattr(_thread_local, 'stack', None):
        return _thread_local.stack[-1]
    return Context('cpu', 0)
