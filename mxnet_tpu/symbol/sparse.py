"""mx.sym.sparse — symbolic sparse-op namespace (reference
python/mxnet/symbol/sparse.py). In the symbol world storage types are
annotations over dense XLA buffers (see ops/sparse_ops.py); the names
here keep ported code importing.
"""
from . import register as _register

__all__ = ['cast_storage', 'retain', 'dot', 'square_sum', 'zeros_like']

cast_storage = _register.make_sym_function('cast_storage')
retain = _register.make_sym_function('_sparse_retain')
dot = _register.make_sym_function('dot')
square_sum = _register.make_sym_function('_square_sum')
zeros_like = _register.make_sym_function('zeros_like')
