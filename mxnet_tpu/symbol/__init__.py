"""mx.sym — symbolic namespace (reference python/mxnet/symbol/__init__.py)."""
from .symbol import Symbol, Variable, var, Group, load, load_json, create
from . import register as _register

_register.install_ops(globals())


def zeros(shape, dtype='float32', **kwargs):
    return _register.make_sym_function('_zeros')(shape=tuple(shape) if not isinstance(shape, int) else (shape,), dtype=dtype, **kwargs)


def ones(shape, dtype='float32', **kwargs):
    return _register.make_sym_function('_ones')(shape=tuple(shape) if not isinstance(shape, int) else (shape,), dtype=dtype, **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype='float32', **kwargs):
    return _register.make_sym_function('_arange')(start=start, stop=stop, step=step,
                                                  repeat=repeat, dtype=dtype, **kwargs)

def full(shape, val, dtype='float32', **kwargs):
    """Symbol filled with ``val`` (reference symbol.py:full)."""
    z = zeros(shape, dtype=dtype, **kwargs)
    return _register.make_sym_function('_plus_scalar')(z, scalar=float(val))


def _sym_or_scalar_binary(lhs, rhs, sym_op, lscalar_op, rscalar_op):
    if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
        return _register.make_sym_function(sym_op)(lhs, rhs)
    if isinstance(lhs, Symbol):
        return _register.make_sym_function(rscalar_op)(lhs, scalar=float(rhs))
    if isinstance(rhs, Symbol):
        return _register.make_sym_function(lscalar_op)(rhs, scalar=float(lhs))
    raise TypeError('at least one argument must be a Symbol')


def maximum(lhs, rhs):
    return _sym_or_scalar_binary(lhs, rhs, '_maximum',
                                 '_maximum_scalar', '_maximum_scalar')


def minimum(lhs, rhs):
    return _sym_or_scalar_binary(lhs, rhs, '_minimum',
                                 '_minimum_scalar', '_minimum_scalar')


def hypot(lhs, rhs):
    """sqrt(lhs^2 + rhs^2) elementwise (reference symbol.py:hypot)."""
    return _sym_or_scalar_binary(lhs, rhs, '_hypot',
                                 '_hypot_scalar', '_hypot_scalar')


from . import contrib  # noqa: E402,F401  (mx.sym.contrib.*)
from . import linalg    # noqa: E402,F401  (mx.sym.linalg.*)
from . import random    # noqa: E402,F401  (mx.sym.random.*)
from . import sparse    # noqa: E402,F401  (mx.sym.sparse.*)
from . import op        # noqa: E402,F401  (generated-op module path)
from . import _internal  # noqa: E402,F401


def __getattr__(name):
    """Late-binding for ops registered after import (mirrors ndarray)."""
    from ..ops import registry as _late_reg
    if _late_reg.exists(name):
        fn = _register.make_sym_function(name)
        globals()[name] = fn
        return fn
    raise AttributeError('module %r has no attribute %r' % (__name__, name))
