"""mx.sym.random — symbolic samplers (reference
python/mxnet/symbol/random.py over the random/ operator family).

Scalar-parameter forms lower to ``_random_*``; Symbol-parameter forms
lower to the ``_sample_*`` ops where the reference registers one, as
in the reference's helper (symbol/random.py _random_helper).
"""
from . import register as _register
from .symbol import Symbol

__all__ = ['uniform', 'normal', 'gamma', 'exponential', 'poisson',
           'negative_binomial', 'generalized_negative_binomial',
           'multinomial']


def _sampler(scalar_op, sample_op, pnames):
    scalar_fn = _register.make_sym_function(scalar_op)
    sample_fn = (_register.make_sym_function(sample_op)
                 if sample_op else None)

    def fn(*args, **kwargs):
        vals = dict(zip(pnames, args))
        # positionals past the distribution params follow the
        # reference's generated signature: shape, then dtype
        for extra_name, extra in zip(('shape', 'dtype'),
                                     args[len(pnames):]):
            if extra_name in kwargs:
                raise TypeError('%s() got multiple values for argument '
                                '%r' % (fn.__name__, extra_name))
            kwargs[extra_name] = extra
        for n in pnames:
            if n in kwargs:
                if n in vals:
                    raise TypeError('%s() got multiple values for '
                                    'argument %r' % (fn.__name__, n))
                vals[n] = kwargs.pop(n)
        n_sym = sum(isinstance(v, Symbol) for v in vals.values())
        if n_sym:
            if sample_fn is None:
                raise TypeError('%s does not take Symbol parameters'
                                % scalar_op)
            if n_sym != len(pnames) or len(vals) != len(pnames):
                # reference symbol/random.py _random_helper contract
                raise ValueError('Distribution parameters must all '
                                 'have the same type (all Symbol or '
                                 'all numbers)')
            return sample_fn(*[vals[n] for n in pnames], **kwargs)
        kwargs.update(vals)
        return scalar_fn(**kwargs)
    fn.__name__ = scalar_op.replace('_random_', '')
    return fn


uniform = _sampler('_random_uniform', '_sample_uniform', ('low', 'high'))
normal = _sampler('_random_normal', '_sample_normal', ('loc', 'scale'))
gamma = _sampler('_random_gamma', '_sample_gamma', ('alpha', 'beta'))
exponential = _sampler('_random_exponential', '_sample_exponential',
                       ('lam',))
poisson = _sampler('_random_poisson', '_sample_poisson', ('lam',))
negative_binomial = _sampler('_random_negative_binomial', None,
                             ('k', 'p'))
generalized_negative_binomial = _sampler(
    '_random_generalized_negative_binomial', None, ('mu', 'alpha'))
multinomial = _register.make_sym_function('_sample_multinomial')
