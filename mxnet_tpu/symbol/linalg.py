"""mx.sym.linalg — symbolic linear-algebra namespace (reference
python/mxnet/symbol/linalg.py over the ``linalg_*`` family). Short
names are the generated wrappers, so positional scalars behave like
the nd counterparts.
"""
from . import register as _register

__all__ = ['gemm', 'gemm2', 'potrf', 'potri', 'trmm', 'trsm', 'syrk',
           'gelqf', 'sumlogdiag']

gemm = _register.make_sym_function('linalg_gemm')
gemm2 = _register.make_sym_function('linalg_gemm2')
potrf = _register.make_sym_function('linalg_potrf')
potri = _register.make_sym_function('linalg_potri')
trmm = _register.make_sym_function('linalg_trmm')
trsm = _register.make_sym_function('linalg_trsm')
syrk = _register.make_sym_function('linalg_syrk')
gelqf = _register.make_sym_function('linalg_gelqf')
sumlogdiag = _register.make_sym_function('linalg_sumlogdiag')
