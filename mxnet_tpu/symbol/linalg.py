"""mx.sym.linalg — symbolic linear-algebra namespace (reference
python/mxnet/symbol/linalg.py over the ``linalg_*`` family).
"""
from . import register as _register

__all__ = ['gemm', 'gemm2', 'potrf', 'potri', 'trmm', 'trsm', 'syrk',
           'gelqf', 'sumlogdiag']


def _op(name):
    base = _register.make_sym_function('linalg_' + name)

    def fn(*args, **kwargs):
        return base(*args, **kwargs)
    fn.__name__ = name
    fn.__doc__ = 'mx.sym.linalg.%s — see the linalg_%s operator.' % (
        name, name)
    return fn


gemm = _op('gemm')
gemm2 = _op('gemm2')
potrf = _op('potrf')
potri = _op('potri')
trmm = _op('trmm')
trsm = _op('trsm')
syrk = _op('syrk')
gelqf = _op('gelqf')
sumlogdiag = _op('sumlogdiag')
