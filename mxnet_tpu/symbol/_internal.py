"""mx.sym._internal — underscore-prefixed symbolic operator namespace
(reference python/mxnet/symbol/_internal.py). Lazily generated.
"""
from ..ops.registry import lazy_op_module
from .register import make_sym_function

__getattr__, __dir__ = lazy_op_module(globals(), make_sym_function,
                                      underscore_only=True)
