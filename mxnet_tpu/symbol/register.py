"""Auto-generation of the sym.* operator namespace.

Reference: python/mxnet/symbol/op.py:65 (_make_atomic_symbol_function) —
same introspection-driven generation as the nd namespace, producing symbol
composers instead of imperative calls.
"""
from ..ops import registry as _reg
from .symbol import Symbol, _invoke_sym

__all__ = ['make_sym_function', 'install_ops']


def make_sym_function(op_name):
    op = _reg.get(op_name)

    def fn(*args, **kwargs):
        inputs = [a for a in args if isinstance(a, Symbol)]
        # positional scalars map onto declared params in order, the
        # generated-signature convention shared with make_nd_function
        pos_attrs = [a for a in args
                     if not isinstance(a, Symbol) and a is not None]
        if pos_attrs:
            for pname in op.param_defaults:
                if not pos_attrs:
                    break
                if pname not in kwargs:
                    kwargs[pname] = pos_attrs.pop(0)
        return _invoke_sym(op_name, inputs, kwargs)

    fn.__name__ = op_name
    fn.__doc__ = op.doc
    return fn


def install_ops(namespace):
    for name in _reg.list_ops():
        if name.startswith('_slice_like'):
            continue
        namespace[name] = make_sym_function(name)
    return namespace
