"""Shape/type inference over a Symbol graph.

Reference: src/executor/infer_graph_attr_pass.cc:302-338 (InferShape/
InferType fixpoint over per-op FInferShape) — the piece of the reference's
bind pipeline that must stay host-side even in the XLA world, because
simple_bind allocates parameter arrays before any tracing happens.

Design: forward topo walk with jax.eval_shape per node; unknown *parameter*
shapes are filled by per-op hooks keyed on the data input's shape + attrs
(the practically-used direction of the reference's bidirectional solver).
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError, np_dtype
from ..ops import registry as _reg

__all__ = ['infer_shapes', 'infer_types', 'param_shape_hook']

_PARAM_HOOKS = {}


def param_shape_hook(op_name):
    def deco(fn):
        _PARAM_HOOKS[op_name] = fn
        return fn
    return deco


@param_shape_hook('FullyConnected')
def _fc_params(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return {}
    flat = int(np.prod(data[1:])) if attrs.get('flatten', True) else data[-1]
    n = int(attrs['num_hidden'])
    out = {'weight': (n, flat)}
    if not attrs.get('no_bias', False):
        out['bias'] = (n,)
    return out


@param_shape_hook('Convolution')
def _conv_params(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return {}
    nf = int(attrs['num_filter'])
    g = int(attrs.get('num_group', 1))
    kernel = tuple(attrs['kernel'])
    out = {'weight': (nf, data[1] // g) + kernel}
    if not attrs.get('no_bias', False):
        out['bias'] = (nf,)
    return out


@param_shape_hook('_contrib_DeformableConvolution')
def _deform_conv_params(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return {}
    nf = int(attrs['num_filter'])
    g = int(attrs.get('num_group', 1))
    kernel = tuple(attrs['kernel'])
    out = {'weight': (nf, data[1] // g) + kernel}
    if not attrs.get('no_bias', False):
        out['bias'] = (nf,)
    return out


@param_shape_hook('Deconvolution')
def _deconv_params(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return {}
    nf = int(attrs['num_filter'])
    g = int(attrs.get('num_group', 1))
    kernel = tuple(attrs['kernel'])
    out = {'weight': (data[1], nf // g) + kernel}
    if not attrs.get('no_bias', True):
        out['bias'] = (nf,)
    return out


@param_shape_hook('BatchNorm')
def _bn_params(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return {}
    ax = int(attrs.get('axis', 1)) % len(data)
    c = data[ax]
    return {'gamma': (c,), 'beta': (c,), 'moving_mean': (c,), 'moving_var': (c,)}


@param_shape_hook('InstanceNorm')
def _in_params(attrs, in_shapes):
    data = in_shapes[0]
    return {'gamma': (data[1],), 'beta': (data[1],)} if data else {}


@param_shape_hook('LayerNorm')
def _ln_params(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return {}
    ax = int(attrs.get('axis', -1)) % len(data)
    return {'gamma': (data[ax],), 'beta': (data[ax],)}


@param_shape_hook('Embedding')
def _emb_params(attrs, in_shapes):
    return {'weight': (int(attrs['input_dim']), int(attrs['output_dim']))}


@param_shape_hook('LeakyReLU')
def _lrelu_params(attrs, in_shapes):
    if attrs.get('act_type') == 'prelu' and in_shapes[0]:
        return {'gamma': (in_shapes[0][1],)}
    return {}


@param_shape_hook('RNN')
def _rnn_params(attrs, in_shapes):
    from ..ops.rnn_ops import rnn_param_size
    data = in_shapes[0]
    if data is None:
        return {}
    H = int(attrs['state_size'])
    L = int(attrs.get('num_layers', 1))
    bi = bool(attrs.get('bidirectional', False))
    dirs = 2 if bi else 1
    mode = attrs.get('mode', 'lstm')
    n = rnn_param_size(L, H, data[2], bi, mode)
    out = {'parameters': (n,), 'state': (L * dirs, data[1], H)}
    if mode == 'lstm':
        out['state_cell'] = (L * dirs, data[1], H)
    return out


@param_shape_hook('SoftmaxOutput')
def _softmax_out_params(attrs, in_shapes):
    """Reference softmax_output-inl.h label inference from the data
    shape: (N,) by default; (N, d2, ...) with multi_output (class axis
    1 removed); data shape minus the last axis with preserve_shape."""
    data = in_shapes[0]
    if data is None:
        return {}
    if attrs.get('preserve_shape', False):
        return {'label': tuple(data[:-1])}
    if attrs.get('multi_output', False):
        return {'label': (data[0],) + tuple(data[2:])}
    return {'label': (data[0],)}


@param_shape_hook('SVMOutput')
def _svm_out_params(attrs, in_shapes):
    data = in_shapes[0]
    return {'label': (data[0],)} if data else {}


def _reg_out_params(attrs, in_shapes):
    """Regression outputs: label has the data's shape (reference
    regression_output-inl.h)."""
    data = in_shapes[0]
    return {'label': tuple(data)} if data else {}


for _name in ('LinearRegressionOutput', 'MAERegressionOutput',
              'LogisticRegressionOutput'):
    param_shape_hook(_name)(_reg_out_params)


def _node_arg_name(node, i):
    op = node.opdef()
    names = op.input_names
    return names[i] if i < len(names) else 'arg%d' % i


def infer_shapes(symbol, known, partial=False, known_types=None):
    """Returns (arg_shapes, out_shapes, aux_shapes) in canonical orders."""
    known_types = known_types or {}
    shapes = {}   # id(node) -> tuple per output
    var_shape = {}

    for n in symbol._topo():
        if n.is_variable():
            s = known.get(n.name)
            if s is None and '__shape__' in n.attr_dict:
                import ast
                s = tuple(ast.literal_eval(n.attr_dict['__shape__']))
            if s is not None and any(d == 0 for d in s):
                s = None  # 0-dims mean "unknown" (MXNet convention)
            var_shape[n.name] = tuple(s) if s is not None else None
            shapes[id(n)] = [var_shape[n.name]]
            continue
        op = n.opdef()
        in_shapes = []
        for (p, idx) in n.inputs:
            sh = shapes.get(id(p))
            in_shapes.append(sh[idx] if sh is not None and sh[idx] is not None else None)
        # fill unknown parameter-variable shapes via hook
        hook = _PARAM_HOOKS.get(n.op)
        if hook is not None:
            fills = hook(n.attrs, in_shapes)
            for i, (p, idx) in enumerate(n.inputs):
                if in_shapes[i] is None and p.is_variable():
                    want = fills.get(_node_arg_name(n, i))
                    if want is not None:
                        var_shape[p.name] = tuple(int(x) for x in want)
                        shapes[id(p)] = [var_shape[p.name]]
                        in_shapes[i] = var_shape[p.name]
        if any(s is None for s in in_shapes):
            if partial:
                shapes[id(n)] = [None] * op.n_outputs(n.attrs)
                continue
            missing = [_node_arg_name(n, i) for i, s in enumerate(in_shapes) if s is None]
            raise MXNetError('cannot infer shape for inputs %s of node %s(%s)'
                             % (missing, n.name, n.op))
        out_shapes = _eval_node_shape(n, in_shapes, known_types)
        shapes[id(n)] = out_shapes

    args = symbol.list_arguments()
    auxs = symbol.list_auxiliary_states()
    arg_shapes = [var_shape.get(a) for a in args]
    aux_shapes = [var_shape.get(a) for a in auxs]
    out_shapes = []
    for node, idx in symbol._outputs:
        s = shapes.get(id(node))
        out_shapes.append(s[idx] if s else None)
    return arg_shapes, out_shapes, aux_shapes


def _eval_node_shape(n, in_shapes, known_types):
    op = n.opdef()
    attrs = dict(n.attrs)
    if op.train_aware:
        attrs['__is_train__'] = False
    specs = [jax.ShapeDtypeStruct(s, np_dtype(known_types.get(None, 'float32')))
             for s in in_shapes]
    if op.needs_rng:
        specs.append(jax.ShapeDtypeStruct((2,), np.uint32))

    if op.host:
        # host ops cannot be traced; their shape contract comes from
        # shape_fn (legacy infer_shape callbacks, codec geometry)
        if op.shape_fn is None:
            raise MXNetError(
                'host op %s(%s) has a data-dependent output shape; it can '
                'only be used imperatively' % (n.name, n.op))
        out_shapes, _ = op.shape_fn(attrs, [tuple(s) for s in in_shapes])
        return [tuple(s) for s in out_shapes]

    def f(*arrays):
        return op.fn(attrs, *arrays)
    try:
        out = jax.eval_shape(f, *specs)
    except Exception as e:
        raise MXNetError('shape inference failed at %s(%s) with inputs %s: %s'
                         % (n.name, n.op, in_shapes, e))
    if not isinstance(out, (tuple, list)):
        out = (out,)
    return [tuple(o.shape) for o in out]


def _is_floating(t):
    """Floating check covering bfloat16 (outside numpy's hierarchy;
    ``t`` may be a np.dtype, a numpy scalar type, or the jnp.bfloat16
    class)."""
    dt = np.dtype(t)
    return dt.name in ('bfloat16', 'float16') or \
        np.issubdtype(dt, np.floating)


def infer_types(symbol, known):
    dtypes = {}
    var_dtype = {}
    for n in symbol._topo():
        if n.is_variable():
            t = known.get(n.name)
            if t is None and '__dtype__' in n.attr_dict:
                t = n.attr_dict['__dtype__']
            # None = not yet known; resolved from the first consumer
            # below (the practical direction of the reference's
            # bidirectional InferType fixpoint — parameters of a bf16
            # node become bf16)
            var_dtype[n.name] = np_dtype(t) if t is not None else None
            dtypes[id(n)] = [var_dtype[n.name]]
            continue
        in_dtypes = [dtypes[id(p)][i] for (p, i) in n.inputs]
        # seed from the first FLOATING known input: integer inputs
        # (Embedding/take indices) must not type float parameters
        seed = next((t for t in in_dtypes if t is not None
                     and _is_floating(t)), np.dtype('float32'))
        for (p, i), t in zip(n.inputs, in_dtypes):
            if t is None and p.is_variable():
                var_dtype[p.name] = seed
                dtypes[id(p)] = [seed]
        in_dtypes = [dtypes[id(p)][i] for (p, i) in n.inputs]
        # forward propagate: result dtype = first input (simplified)
        out_t = in_dtypes[0] if in_dtypes else np.dtype('float32')
        if n.op == 'Cast':
            out_t = np_dtype(n.attrs['dtype'])
        op = n.opdef()
        dtypes[id(n)] = [out_t] * op.n_outputs(n.attrs)
    args = symbol.list_arguments()
    auxs = symbol.list_auxiliary_states()
    f32 = np.dtype('float32')
    outs = [dtypes[id(node)][idx] or f32 for node, idx in symbol._outputs]
    return ([var_dtype.get(a) or f32 for a in args], outs,
            [var_dtype.get(a) or f32 for a in auxs])
