"""mx.sym.op — the generated symbolic-operator module path
(reference python/mxnet/symbol/op.py). Lazily generated.
"""
from ..ops.registry import lazy_op_module
from .register import make_sym_function

__getattr__, __dir__ = lazy_op_module(globals(), make_sym_function)
