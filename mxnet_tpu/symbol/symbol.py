"""Symbol — the lazy graph IR.

Reference: python/mxnet/symbol/symbol.py:51 (compose, list_arguments,
infer_shape:905, bind:1514, simple_bind:1250, tojson:1183, Group, internals)
over the nnvm Symbol/Graph C++ IR.

TPU-native: the graph is a pure-python DAG of op nodes; "compilation" of a
bound symbol is XLA tracing of one pure function over the argument arrays
(executor.py). JSON round-trips use the reference's node-list schema so
checkpoints remain structurally familiar.
"""
import json

import numpy as np

from ..attribute import AttrScope, NameManager
from ..base import MXNetError, normalize_attrs
from ..ops import registry as _reg

__all__ = ['Symbol', 'Variable', 'var', 'Group', 'load', 'load_json']


class Node:
    """One graph node: a variable (op=None) or an op application."""
    __slots__ = ('op', 'attrs', 'inputs', 'name', 'attr_dict', '_num_args')

    def __init__(self, op, attrs, inputs, name, attr_dict=None, num_args=None):
        self.op = op            # str op name or None for variables
        self.attrs = attrs      # normalized op attrs
        self.inputs = inputs    # list[(Node, int)]
        self.name = name
        self.attr_dict = attr_dict or {}  # user attrs (ctx_group, lr_mult…)
        self._num_args = num_args

    def is_variable(self):
        return self.op is None

    def opdef(self):
        return _reg.get(self.op)


class Symbol:
    """A list of output entries over the shared graph."""

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list[(Node, int)]

    # -- identity / composition ------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        return '<Symbol %s>' % (self.name or 'Grouped')

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise ValueError('cannot find output %s' % index)
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __call__(self, *args, **kwargs):
        """Compose: replace variable placeholders (reference symbol.py:391)."""
        s = self.__copy__()
        s._compose(*args, **kwargs)
        return s

    def __copy__(self):
        return Symbol(list(self._outputs))

    def _compose(self, *args, **kwargs):
        kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        name_map = {}
        for k, v in kwargs.items():
            name_map[k] = v._outputs[0]
        arg_syms = [a for a in args if isinstance(a, Symbol)]
        free = [n for n in self._topo() if n.is_variable()]
        pos = 0
        replace = {}
        for n in free:
            if n.name in name_map:
                replace[n] = name_map[n.name]
            elif pos < len(arg_syms):
                replace[n] = arg_syms[pos]._outputs[0]
                pos += 1
        if replace:
            self._outputs = [_rewrite(e, replace, {}) for e in self._outputs]

    # -- graph walks ------------------------------------------------------
    def _topo(self):
        order, seen = [], set()
        stack = [(n, False) for n, _ in reversed(self._outputs)]
        while stack:
            node, done = stack.pop()
            if done:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for p, _ in reversed(node.inputs):
                if id(p) not in seen:
                    stack.append((p, False))
        return order

    def list_arguments(self):
        """Free variables in DFS order, aux excluded (reference symbol.py:820)."""
        args = []
        aux = set(self._aux_nodes())
        for n in self._topo():
            if n.is_variable() and id(n) not in aux:
                args.append(n.name)
        return args

    def list_auxiliary_states(self):
        """Reference symbol.py:860 — aux states (BatchNorm moving stats…)."""
        aux_ids = self._aux_nodes()
        out, emitted = [], set()
        for n in self._topo():
            if n.is_variable() and id(n) in aux_ids and id(n) not in emitted:
                emitted.add(id(n))
                out.append(n.name)
        return out

    def _aux_nodes(self):
        aux = set()
        for n in self._topo():
            if n.is_variable():
                continue
            op = n.opdef()
            if op.aux_inputs:
                names = op.input_names
                for i, (p, _) in enumerate(n.inputs):
                    if i < len(names) and names[i] in op.aux_inputs and p.is_variable():
                        aux.add(id(p))
        return aux

    def list_outputs(self):
        out = []
        for node, idx in self._outputs:
            if node.is_variable():
                out.append(node.name)
            else:
                op = node.opdef()
                nvis = op.n_visible_outputs(node.attrs)
                if nvis == 1:
                    out.append(node.name + '_output')
                else:
                    out.append('%s_output%d' % (node.name, idx))
        return out

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_variable()]

    def get_internals(self):
        """Reference symbol.py:584: every node's outputs as a grouped symbol."""
        entries = []
        for n in self._topo():
            if n.is_variable():
                entries.append((n, 0))
            else:
                for i in range(n.opdef().n_visible_outputs(n.attrs)):
                    entries.append((n, i))
        return Symbol(entries)

    def get_children(self):
        kids = []
        for node, _ in self._outputs:
            kids.extend(node.inputs)
        return Symbol(kids) if kids else None

    # -- attrs ------------------------------------------------------------
    def attr(self, key):
        if len(self._outputs) == 1:
            return self._outputs[0][0].attr_dict.get(key, None)
        return None

    def attr_dict(self):
        out = {}
        for n in self._topo():
            if n.attr_dict:
                out[n.name] = dict(n.attr_dict)
        return out

    def _set_attr(self, **kwargs):
        for n, _ in self._outputs:
            n.attr_dict.update(kwargs)

    # -- arithmetic sugar (reference symbol.py __add__ etc.) ---------------
    def __add__(self, other):
        return _sym_binary(self, other, 'broadcast_add' if isinstance(other, Symbol) else '_plus_scalar', 'elemwise_add')

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _sym_binary(self, other, 'broadcast_sub' if isinstance(other, Symbol) else '_minus_scalar', 'elemwise_sub')

    def __rsub__(self, other):
        return _sym_scalar(self, other, '_rminus_scalar')

    def __mul__(self, other):
        return _sym_binary(self, other, 'broadcast_mul' if isinstance(other, Symbol) else '_mul_scalar', 'elemwise_mul')

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _sym_binary(self, other, 'broadcast_div' if isinstance(other, Symbol) else '_div_scalar', 'elemwise_div')

    def __rtruediv__(self, other):
        return _sym_scalar(self, other, '_rdiv_scalar')

    def __pow__(self, other):
        return _sym_binary(self, other, 'broadcast_power' if isinstance(other, Symbol) else '_power_scalar', None)

    def __neg__(self):
        return create('negative', [self], {})

    def __eq__(self, other):
        return _sym_binary(self, other, 'broadcast_equal' if isinstance(other, Symbol) else '_equal_scalar', None)

    def __ne__(self, other):
        return _sym_binary(self, other, 'broadcast_not_equal' if isinstance(other, Symbol) else '_not_equal_scalar', None)

    def __gt__(self, other):
        return _sym_binary(self, other, 'broadcast_greater' if isinstance(other, Symbol) else '_greater_scalar', None)

    def __ge__(self, other):
        return _sym_binary(self, other, 'broadcast_greater_equal' if isinstance(other, Symbol) else '_greater_equal_scalar', None)

    def __lt__(self, other):
        return _sym_binary(self, other, 'broadcast_lesser' if isinstance(other, Symbol) else '_lesser_scalar', None)

    def __le__(self, other):
        return _sym_binary(self, other, 'broadcast_lesser_equal' if isinstance(other, Symbol) else '_lesser_equal_scalar', None)

    def __hash__(self):
        return id(self)

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """Reference symbol.py:905. Returns (arg_shapes, out_shapes, aux_shapes).
        Parameter shapes are inferred from data shapes via per-op hooks
        (symbol/infer.py) + jax.eval_shape forward propagation."""
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise
        except Exception as e:
            raise MXNetError('infer_shape error: %s' % e)

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        from .infer import infer_shapes
        known = {}
        if args:
            for name, shape in zip(self.list_arguments(), args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items()})
        return infer_shapes(self, known, partial=partial)

    def infer_type(self, *args, **kwargs):
        from .infer import infer_types
        known = {}
        if args:
            for name, t in zip(self.list_arguments(), args):
                if t is not None:
                    known[name] = t
        known.update(kwargs)
        return infer_types(self, known)

    # -- gradient ---------------------------------------------------------
    def gradient(self, wrt):
        raise NotImplementedError('use Executor.backward (XLA computes '
                                  'gradients at bind time)')

    # -- serialization (reference symbol.py:1183 tojson) -------------------
    def tojson(self):
        nodes = self._topo()
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes, arg_nodes = [], []
        for i, n in enumerate(nodes):
            if n.is_variable():
                arg_nodes.append(i)
                jnodes.append({'op': 'null', 'name': n.name, 'inputs': []})
            else:
                attrs = {k: _attr_to_str(v) for k, v in n.attrs.items()
                         if not k.startswith('__')}
                jnodes.append({
                    'op': n.op, 'name': n.name, 'attrs': attrs,
                    'inputs': [[nid[id(p)], idx, 0] for p, idx in n.inputs]})
            if n.attr_dict:
                jnodes[-1].setdefault('attrs', {}).update(
                    {'__user__' + k: str(v) for k, v in n.attr_dict.items()})
        heads = [[nid[id(n)], idx, 0] for n, idx in self._outputs]
        return json.dumps({'nodes': jnodes, 'arg_nodes': arg_nodes,
                           'node_row_ptr': list(range(len(nodes) + 1)),
                           'heads': heads,
                           'attrs': {'mxnet_version': ['int', 1100]}}, indent=2)

    def save(self, fname):
        with open(fname, 'w') as f:
            f.write(self.tojson())

    # -- executor entry points (impl in executor.py) ----------------------
    def bind(self, ctx, args, args_grad=None, grad_req='write', aux_states=None,
             group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx)

    def simple_bind(self, ctx, grad_req='write', type_dict=None,
                    group2ctx=None, shared_exec=None, **kwargs):
        from ..executor import simple_bind
        return simple_bind(self, ctx, grad_req, type_dict, group2ctx,
                           shared_exec, **kwargs)

    def eval(self, ctx=None, **kwargs):
        from ..context import current_context
        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # convenience op methods mirroring mx.sym.<op>(self, ...); positional
    # scalars map onto declared params in order, the generated-signature
    # convention (symbol/register.py make_sym_function)
    def _op_method(name):  # noqa: N805
        def method(self, *args, **kwargs):
            inputs = [self] + [a for a in args if isinstance(a, Symbol)]
            pos_attrs = [a for a in args if not isinstance(a, Symbol)]
            if pos_attrs:
                params = list(_reg.get(name).param_defaults)
                if len(pos_attrs) > len(params):
                    raise TypeError(
                        '%s: %d positional argument(s) beyond the '
                        'declared params'
                        % (name, len(pos_attrs) - len(params)))
                # python call semantics: positionals fill params in
                # declaration order; a clash with a kwarg is an error,
                # and None is a real value (axis=None etc.)
                for pname, val in zip(params, pos_attrs):
                    if pname in kwargs:
                        raise TypeError(
                            '%s() got multiple values for argument %r'
                            % (name, pname))
                    kwargs[pname] = val
            return _invoke_sym(name, inputs, kwargs)
        return method

    for _n in ['sum', 'mean', 'max', 'min', 'prod', 'argmax', 'argmin',
               'norm', 'abs', 'sign', 'sqrt', 'square', 'exp', 'log',
               'sigmoid', 'relu', 'tanh', 'softmax', 'log_softmax',
               'transpose', 'expand_dims', 'squeeze', 'clip', 'flatten',
               'sort', 'argsort', 'topk', 'take', 'one_hot', 'pick', 'tile',
               'repeat', 'dot', 'broadcast_axes', 'broadcast_to', 'ceil',
               'fix', 'flip', 'floor', 'nanprod', 'nansum', 'ones_like',
               'pad', 'rint', 'round', 'slice', 'split', 'swapaxes',
               'trunc', 'zeros_like']:
        locals()[_n] = _op_method(_n)
    del _op_method, _n

    def copy(self):
        """Deep graph copy (reference MXSymbolCopy): mutating attrs on
        the copy must not leak into the original. Iterative over the
        topo order — graphs can be deeper than the recursion limit."""
        memo = {}
        for node in self._topo():          # parents precede consumers
            memo[id(node)] = Node(
                node.op, dict(node.attrs),
                [(memo[id(p)], i) for p, i in node.inputs],
                node.name, dict(node.attr_dict), node._num_args)
        return Symbol([(memo[id(n)], i) for n, i in self._outputs])

    def list_attr(self, recursive=False):
        """User attrs of the head node (reference symbol.py:list_attr);
        recursive=True raises like modern reference versions — use
        attr_dict() for the whole graph."""
        if recursive:
            raise DeprecationWarning(
                'list_attr(recursive=True) is deprecated: use attr_dict()')
        if len(self._outputs) == 1:
            return dict(self._outputs[0][0].attr_dict)
        return {}

    def debug_str(self):
        """Human-readable graph dump (reference Symbol::DebugStr)."""
        lines = []
        for n in self._topo():
            if n.is_variable():
                lines.append('Variable:%s' % n.name)
            else:
                ins = ', '.join('%s[%d]' % (p.name, i) for p, i in n.inputs)
                lines.append('Op:%s, Name=%s\nInputs:\n\t%s'
                             % (n.op, n.name, ins))
        return '\n'.join(lines) + '\n'

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if 'shape' in kwargs:
            shape = kwargs['shape']
        return _invoke_sym('Reshape', [self], {'shape': tuple(shape)})

    def astype(self, dtype):
        return _invoke_sym('Cast', [self], {'dtype': str(dtype)})

    def slice_axis(self, axis, begin, end):
        return _invoke_sym('slice_axis', [self],
                           {'axis': axis, 'begin': begin, 'end': end})


def _rewrite(entry, replace, memo):
    node, idx = entry
    if node in replace:
        return (replace[node][0], replace[node][1])
    if id(node) in memo:
        return (memo[id(node)], idx)
    if node.is_variable():
        memo[id(node)] = node
        return entry
    new_inputs = [_rewrite(e, replace, memo) for e in node.inputs]
    new_node = Node(node.op, node.attrs, new_inputs, node.name,
                    dict(node.attr_dict), node._num_args)
    memo[id(node)] = new_node
    return (new_node, idx)


def _attr_to_str(v):
    if isinstance(v, bool):
        return 'True' if v else 'False'
    if isinstance(v, tuple):
        return '(' + ', '.join(str(x) for x in v) + ')'
    return str(v)


def _parse_attr(s):
    if not isinstance(s, str):
        return s
    import ast
    low = s.strip()
    if low in ('True', 'true'):
        return True
    if low in ('False', 'false'):
        return False
    if low in ('None',):
        return None
    try:
        return ast.literal_eval(low)
    except (ValueError, SyntaxError):
        return s


# ---------------------------------------------------------------------------
# construction API
# ---------------------------------------------------------------------------

def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Reference symbol.py:2425 mx.sym.Variable."""
    attr_dict = AttrScope.current().get(attr or {})
    if shape is not None:
        attr_dict['__shape__'] = str(tuple(shape))
    if dtype is not None:
        attr_dict['__dtype__'] = str(dtype)
    if lr_mult is not None:
        attr_dict['__lr_mult__'] = str(lr_mult)
    if wd_mult is not None:
        attr_dict['__wd_mult__'] = str(wd_mult)
    if init is not None:
        if isinstance(init, str):
            # resolve string specs so '__init__' always holds the json
            # form Initializer.__call__ expects
            from ..initializer import create as _create_init
            init = _create_init(init)
        attr_dict['__init__'] = init.dumps() if hasattr(init, 'dumps') \
            else str(init)
    node = Node(None, {}, [], name, attr_dict)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    outputs = []
    for s in symbols:
        outputs.extend(s._outputs)
    return Symbol(outputs)


def create(op_name, input_syms, attrs, name=None):
    """Create an op node — the Symbol side of the shared registry."""
    op = _reg.get(op_name)
    attrs = normalize_attrs(attrs)
    name = NameManager.current().get(name, op_name.lstrip('_'))
    inputs = [s._outputs[0] for s in input_syms]
    attr_dict = AttrScope.current().get({})
    node = Node(op_name, attrs, inputs, name, attr_dict,
                num_args=len(inputs) if op.variadic else None)
    nvis = op.n_visible_outputs(attrs)
    return Symbol([(node, i) for i in range(nvis)])


def _invoke_sym(op_name, input_syms, kwargs):
    name = kwargs.pop('name', None)
    kwargs.pop('attr', None)
    op = _reg.get(op_name)
    # separate symbol inputs passed as kwargs
    named = {}
    for k in list(kwargs):
        if isinstance(kwargs[k], Symbol):
            named[k] = kwargs.pop(k)
    inputs = list(input_syms)
    if not op.variadic and named:
        merged = []
        pos_iter = iter(inputs)
        for n in op.input_names:
            if n in named:
                merged.append(named[n])
            else:
                nxt = next(pos_iter, None)
                if nxt is not None:
                    merged.append(nxt)
        inputs = merged
    elif op.variadic and (named or op_name == 'Custom'):
        # keyword symbol inputs to a variadic op (the reference's Custom
        # example style: mx.sym.Custom(data=..., label=..., op_type=...)).
        # For Custom the prop declares the input order; otherwise keep
        # keyword insertion order.
        order = None
        if op_name == 'Custom' and 'op_type' in kwargs:
            from ..operator import _CUSTOM_OPS, _CUSTOM_RESERVED
            prop_kwargs = {k: v for k, v in kwargs.items()
                           if k not in _CUSTOM_RESERVED
                           and k != op.key_var_num_args}
            n_args = 0
            try:
                prop = _CUSTOM_OPS[kwargs['op_type']](**prop_kwargs)
                # aux states bind as trailing inputs (reference custom.cc
                # input layout), so they belong in the keyword order too
                args_order = list(prop.list_arguments())
                n_args = len(args_order)
                order = args_order + list(prop.list_auxiliary_states())
            except Exception:
                order = None
        if order is not None:
            # Custom with a declared input order: merge positional and
            # keyword inputs, and AUTO-CREATE a <name>_<arg> Variable
            # for every declared input not passed (reference compose
            # semantics — e.g. Custom(data=fc3, name='softmax',
            # op_type='softmax') grows a 'softmax_label' input, which
            # FeedForward/Module label binding relies on).
            unknown = [k for k in named if k not in order]
            if unknown:
                raise ValueError(
                    'unknown keyword input(s) %s for Custom op %r; '
                    'declared inputs are %s' %
                    (unknown, kwargs.get('op_type'), order))
            if len(inputs) > len(order):
                raise ValueError(
                    'Custom op %r takes inputs %s; %d extra positional '
                    'input(s) given' % (kwargs.get('op_type'), order,
                                        len(inputs) - len(order)))
            final_name = NameManager.current().get(name, 'custom')
            merged = []
            omitted_aux = None
            for idx, n in enumerate(order):
                if idx < len(inputs):
                    # positionals fill the LEADING declared slots only —
                    # re-slotting a positional around a keyword-bound
                    # name would silently build the wrong graph
                    if n in named:
                        raise ValueError(
                            'Custom op %r input %r is bound both '
                            'positionally and by keyword' %
                            (kwargs.get('op_type'), n))
                    merged.append(inputs[idx])
                elif n in named:
                    if omitted_aux is not None:
                        # trailing inputs map to aux slots by position:
                        # a gap would silently misbind this one
                        raise ValueError(
                            'Custom op %r: aux input %r passed but '
                            'earlier aux %r omitted' %
                            (kwargs.get('op_type'), n, omitted_aux))
                    merged.append(named[n])
                elif idx < n_args:
                    # missing ARGUMENTS become <name>_<arg> Variables
                    # (reference compose semantics: softmax_label).
                    # Missing AUX states are NOT created — the bind
                    # machinery allocates them from the prop's
                    # infer_shape, like any layer's auxiliary state.
                    merged.append(Variable('%s_%s' % (final_name, n)))
                else:
                    omitted_aux = n
            # aux states are all-or-nothing: trailing inputs map to aux
            # slots by position, so a partial suffix would misbind
            # (operator.py _split_aux splits only on an exact count)
            n_aux_given = len(merged) - n_args
            if n_aux_given not in (0, len(order) - n_args):
                raise ValueError(
                    'Custom op %r: pass all %d aux states or none '
                    '(%d given)' % (kwargs.get('op_type'),
                                    len(order) - n_args, n_aux_given))
            if op.key_var_num_args and op.key_var_num_args not in kwargs:
                kwargs[op.key_var_num_args] = len(merged)
            return create(op_name, merged, kwargs, final_name)
        # Mixing positional and keyword symbol inputs is ambiguous for
        # variable-length ops without a declared order — reject it (the
        # reference errors the same way, symbol.py _compose). A
        # positional-only Custom whose prop failed to instantiate above
        # composes as before (prop errors surface at bind/exec time).
        if inputs and named:
            raise ValueError(
                'operator %s takes variable-length inputs: pass symbol '
                'inputs either all positionally or all by keyword, not '
                'mixed' % op_name)
        inputs = inputs + list(named.values())
    if op.variadic and op.key_var_num_args and op.key_var_num_args not in kwargs:
        kwargs[op.key_var_num_args] = len(inputs)
    # auto-create missing trailing parameter variables (MXNet creates
    # fc0_weight etc. automatically at compose time)
    if not op.variadic:
        final_name = NameManager.current().get(name, op_name.lstrip('_'))
        needed = op.arg_names(kwargs)
        if op_name in ('FullyConnected', 'Convolution', 'Deconvolution') and \
                kwargs.get('no_bias', op.param_defaults.get('no_bias', False)):
            needed = [n for n in needed if n != 'bias']
        if op_name == 'LeakyReLU':
            needed = ['data', 'gamma'] if kwargs.get('act_type') == 'prelu' else ['data']
        if op_name == 'RNN':
            needed = ['data', 'parameters', 'state'] + \
                (['state_cell'] if kwargs.get('mode', 'lstm') == 'lstm' else [])
        while len(inputs) < len(needed):
            pname = needed[len(inputs)]
            inputs.append(Variable('%s_%s' % (final_name, pname)))
        return create(op_name, inputs, kwargs, final_name)
    return create(op_name, inputs, kwargs, name)


def _not_for_symbol(name):
    def method(self, *args, **kwargs):
        from ..base import NotImplementedForSymbol
        raise NotImplementedForSymbol(method, None, *args)
    method.__name__ = name
    method.__doc__ = ('NDArray-only operation: not supported for Symbol '
                      '(reference symbol.py raises the same).')
    return method


for _n in ('asnumpy', 'asscalar', 'as_in_context', 'backward', 'detach',
           'wait_to_read'):
    setattr(Symbol, _n, _not_for_symbol(_n))
del _not_for_symbol


def _sym_binary(lhs, rhs, op_name, elem_name):
    if isinstance(rhs, Symbol):
        return create(op_name, [lhs, rhs], {})
    return create(op_name, [lhs], {'scalar': float(rhs)})


def _sym_scalar(lhs, scalar, op_name):
    return create(op_name, [lhs], {'scalar': float(scalar)})


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------

def _entry(nodes, e):
    """Graph entry [node, out_idx(, version)] — the reference wrote
    2-element entries pre-0.9 and 3-element after."""
    return (nodes[e[0]], e[1] if len(e) > 1 else 0)


def load_json(json_str):
    """Parse graph JSON — ours and the reference's (both the 0.11 form
    with 'attrs' and the legacy form with 'param' op-attrs + 'attr'
    user-attrs, e.g. tests/python/unittest/save_000800.json)."""
    g = json.loads(json_str)
    nodes = []
    for jn in g['nodes']:
        if jn['op'] == 'null':
            attr_dict = {}
            for src in (jn.get('attrs', {}), jn.get('attr', {})):
                for k, v in src.items():
                    if k.startswith('__user__'):
                        attr_dict[k[len('__user__'):]] = v
                    else:
                        attr_dict[k] = v
            nodes.append(Node(None, {}, [], jn['name'], attr_dict))
        else:
            attrs = {}
            attr_dict = dict(jn.get('attr', {}))  # legacy user attrs
            for k, v in jn.get('attrs', jn.get('param', {})).items():
                if k.startswith('__user__'):
                    attr_dict[k[len('__user__'):]] = v
                else:
                    attrs[k] = _parse_attr(v)
            inputs = [_entry(nodes, e) for e in jn['inputs']]
            # legacy graphs omit auxiliary-state inputs (BatchNorm
            # moving_mean/var were implicit pre-0.9): synthesize ONLY
            # the missing trailing aux variables, compose-named
            if _reg.exists(jn['op']):
                op = _reg.get(jn['op'])
                names = op.input_names
                n_aux = len(op.aux_inputs)
                if n_aux and len(inputs) == len(names) - n_aux:
                    for miss in names[len(inputs):]:
                        inputs.append((Node(None, {}, [],
                                            '%s_%s' % (jn['name'], miss),
                                            {}), 0))
            nodes.append(Node(jn['op'], normalize_attrs(attrs), inputs,
                              jn['name'], attr_dict,
                              num_args=len(inputs)))
    outputs = [_entry(nodes, e) for e in g['heads']]
    return Symbol(outputs)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def __getattr__(name):
    """Deep-import compat: the reference defines module-level helpers
    (arange, maximum, hypot, ...) in symbol/symbol.py itself; here they
    live on the package — forward lookups there."""
    if name.startswith('_'):
        raise AttributeError(name)
    import sys as _s
    pkg = _s.modules[__package__]
    if hasattr(pkg, name):
        return getattr(pkg, name)
    raise AttributeError('module %r has no attribute %r'
                         % (__name__, name))
