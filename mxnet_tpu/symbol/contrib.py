"""contrib namespace — experimental ops (reference symbol/contrib.py).

Exposes every registered ``_contrib_*`` operator without the prefix:
``sym.contrib.MultiBoxPrior`` ≙ the reference's
mx.sym.contrib.MultiBoxPrior (src/operator/contrib/).
"""
from ..ops import registry as _reg
from .register import make_sym_function as _make

for _name in _reg.list_ops():
    if _name.startswith('_contrib_'):
        globals()[_name[len('_contrib_'):]] = _make(_name)
del _name
