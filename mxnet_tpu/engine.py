"""Engine façade — the dependency-scheduler API surface over JAX dispatch.

Reference: include/mxnet/engine.h:93-268 + src/engine/ (ThreadedEngine,
NaiveEngine). The reference's engine exists to order reads/writes on mutable
buffers across worker threads. On TPU the compute path is XLA async dispatch
over immutable buffers, so the ordering problem vanishes for device work;
what remains (and what this module provides) is the *API*: WaitForAll /
WaitForVar semantics, a bulk/naive mode switch (MXNET_ENGINE_TYPE), and a
host-side work queue for genuinely stateful host tasks (IO prefetch,
checkpoint writes) — see io.py's prefetcher for its use.
"""
import ctypes
import itertools
import os
import queue
import threading
import traceback

import jax

from . import _native

__all__ = ['push', 'wait_for_var', 'wait_for_all', 'engine_type',
           'set_bulk_size', 'Engine']

from .config import flags as _flags
_engine_type = _flags.get('MXTPU_ENGINE_TYPE')


class Engine:
    """Native async dependency engine (src/engine.cc, reference
    include/mxnet/engine.h:93-268).

    Ops declare read (`const_vars`) / write (`mutable_vars`) sets over
    opaque vars; per var, writers serialize and order against readers in
    arrival order, and independent ops run concurrently on the worker
    pool. This schedules host-side work (IO decode, prefetch, checkpoint
    writes) — device compute goes through XLA.

    >>> eng = Engine()
    >>> v = eng.new_var()
    >>> eng.push(task, mutable_vars=[v], priority=1, name='decode')
    >>> eng.wait_for_var(v)
    """

    def __init__(self, num_workers=None):
        lib = _native.get_lib()
        if lib is None:
            raise RuntimeError('native runtime unavailable '
                               '(g++ missing or MXTPU_NO_NATIVE set)')
        if num_workers is None:
            num_workers = _flags.get('MXTPU_ENGINE_WORKERS')
        if naive():
            num_workers = 0  # inline synchronous execution
        self._lib = lib
        self._h = ctypes.c_void_p()
        _native.check_call(lib.MXTEngineCreate(num_workers,
                                               ctypes.byref(self._h)))
        self._cb_lock = threading.Lock()
        self._callbacks = {}
        self._ids = itertools.count(1)

        def _run(param):
            key = param or 0
            with self._cb_lock:
                fn = self._callbacks.pop(key, None)
            if fn is None:
                return
            try:
                fn()
            except Exception:  # never propagate into the C worker
                traceback.print_exc()

        self._trampoline = _native.SYNC_FN(_run)
        self._tramp_ptr = ctypes.cast(self._trampoline, ctypes.c_void_p)

    def new_var(self):
        v = ctypes.c_void_p()
        _native.check_call(self._lib.MXTEngineNewVar(self._h,
                                                     ctypes.byref(v)))
        return v

    def delete_var(self, var):
        _native.check_call(self._lib.MXTEngineDeleteVar(self._h, var))

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             name='host_op'):
        key = next(self._ids)
        with self._cb_lock:
            self._callbacks[key] = fn
        cv = (ctypes.c_void_p * max(1, len(const_vars)))(*const_vars)
        mv = (ctypes.c_void_p * max(1, len(mutable_vars)))(*mutable_vars)
        _native.check_call(self._lib.MXTEnginePushSync(
            self._h, self._tramp_ptr, key,
            cv, len(const_vars), mv, len(mutable_vars),
            priority, name.encode()))

    def wait_for_var(self, var):
        _native.check_call(self._lib.MXTEngineWaitForVar(self._h, var))

    def wait_for_all(self):
        _native.check_call(self._lib.MXTEngineWaitForAll(self._h))

    def pending_ops(self):
        n = ctypes.c_int64()
        _native.check_call(self._lib.MXTEnginePendingOps(self._h,
                                                         ctypes.byref(n)))
        return n.value

    def __del__(self):
        try:
            if getattr(self, '_h', None):
                self._lib.MXTEngineFree(self._h)
                self._h = None
        except Exception:
            pass


_global_engine = None
_global_engine_lock = threading.Lock()


def get_engine():
    """Process-global native engine (Engine::Get(), engine.h:200);
    None when the native runtime is unavailable."""
    global _global_engine
    if _global_engine is None and _native.available():
        with _global_engine_lock:
            if _global_engine is None:
                _global_engine = Engine()
    return _global_engine


def engine_type():
    return _engine_type


def naive():
    """True when MXNET_ENGINE_TYPE=NaiveEngine: synchronous execution for
    debugging (reference engine.cc:32)."""
    return _engine_type == 'NaiveEngine'


class _HostWorker:
    """Single background worker for host-side async tasks (the analog of the
    reference's CPU worker pool, threaded_engine_perdevice.cc:44)."""

    def __init__(self):
        self._q = None
        self._thread = None
        self._lock = threading.Lock()

    def _ensure(self):
        with self._lock:
            if self._thread is None:
                self._q = queue.Queue()
                self._thread = threading.Thread(target=self._loop, daemon=True)
                self._thread.start()

    def _loop(self):
        while True:
            fn, done = self._q.get()
            try:
                fn()
            finally:
                done.set()

    def push(self, fn):
        if naive():
            fn()
            ev = threading.Event()
            ev.set()
            return ev
        self._ensure()
        done = threading.Event()
        self._q.put((fn, done))
        return done


_worker = _HostWorker()


_host_serial_var = None


def push(fn, sync=False):
    """Push a host-side task; returns an Event completing when done.

    Tasks run serialized in submission order (they may share handles —
    checkpoint writers, prefetch state): on the native engine they all
    write one shared var, which its scheduler serializes; the Python
    fallback is a single worker thread."""
    global _host_serial_var
    eng = get_engine() if not naive() else None
    if eng is not None:
        if _host_serial_var is None:
            # under the engine lock: two first-use racers must not each
            # mint a distinct serial var (that would unserialize them)
            with _global_engine_lock:
                if _host_serial_var is None:
                    _host_serial_var = eng.new_var()
        ev = threading.Event()

        def task():
            try:
                fn()
            finally:
                ev.set()

        eng.push(task, mutable_vars=[_host_serial_var], name='host_task')
    else:
        ev = _worker.push(fn)
    if sync:
        ev.wait()
    return ev


def wait_for_var(arr):
    """Engine::WaitForVar ≙ block on the array's buffer."""
    arr.wait_to_read()


def wait_for_all():
    """Engine::WaitForAll (engine.h:180)."""
    from .ndarray.ndarray import waitall
    waitall()


_bulk_size = int(os.environ.get('MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN', 15))


def set_bulk_size(size):
    """API compat: XLA fuses the whole graph; bulk segments are moot."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = size
    return prev
