"""Engine façade — the dependency-scheduler API surface over JAX dispatch.

Reference: include/mxnet/engine.h:93-268 + src/engine/ (ThreadedEngine,
NaiveEngine). The reference's engine exists to order reads/writes on mutable
buffers across worker threads. On TPU the compute path is XLA async dispatch
over immutable buffers, so the ordering problem vanishes for device work;
what remains (and what this module provides) is the *API*: WaitForAll /
WaitForVar semantics, a bulk/naive mode switch (MXNET_ENGINE_TYPE), and a
host-side work queue for genuinely stateful host tasks (IO prefetch,
checkpoint writes) — see io.py's prefetcher for its use.
"""
import os
import queue
import threading

import jax

__all__ = ['push', 'wait_for_var', 'wait_for_all', 'engine_type', 'set_bulk_size']

_engine_type = os.environ.get('MXNET_ENGINE_TYPE', 'ThreadedEngine')


def engine_type():
    return _engine_type


def naive():
    """True when MXNET_ENGINE_TYPE=NaiveEngine: synchronous execution for
    debugging (reference engine.cc:32)."""
    return _engine_type == 'NaiveEngine'


class _HostWorker:
    """Single background worker for host-side async tasks (the analog of the
    reference's CPU worker pool, threaded_engine_perdevice.cc:44)."""

    def __init__(self):
        self._q = None
        self._thread = None
        self._lock = threading.Lock()

    def _ensure(self):
        with self._lock:
            if self._thread is None:
                self._q = queue.Queue()
                self._thread = threading.Thread(target=self._loop, daemon=True)
                self._thread.start()

    def _loop(self):
        while True:
            fn, done = self._q.get()
            try:
                fn()
            finally:
                done.set()

    def push(self, fn):
        if naive():
            fn()
            ev = threading.Event()
            ev.set()
            return ev
        self._ensure()
        done = threading.Event()
        self._q.put((fn, done))
        return done


_worker = _HostWorker()


def push(fn, sync=False):
    """Push a host-side task; returns an Event completing when done."""
    ev = _worker.push(fn)
    if sync:
        ev.wait()
    return ev


def wait_for_var(arr):
    """Engine::WaitForVar ≙ block on the array's buffer."""
    arr.wait_to_read()


def wait_for_all():
    """Engine::WaitForAll (engine.h:180)."""
    from .ndarray.ndarray import waitall
    waitall()


_bulk_size = int(os.environ.get('MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN', 15))


def set_bulk_size(size):
    """API compat: XLA fuses the whole graph; bulk segments are moot."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = size
    return prev
