"""Per-operator docstring addenda for the symbol namespace (reference
python/mxnet/symbol_doc.py): subclass SymbolDoc with the operator's
name to append examples to the generated wrapper's docstring."""
from .base import build_param_doc as _build_param_doc  # noqa: F401

__all__ = ['SymbolDoc']


class SymbolDoc(object):
    """Base class: subclasses named ``<op>Doc`` contribute their
    docstring to the generated ``sym.<op>`` wrapper. Also hosts the
    doc-test helpers the reference exposed here."""

    @staticmethod
    def get_output_shape(sym, **input_shapes):
        """Infer and return output shapes keyed by output name."""
        _, s_outputs, _ = sym.infer_shape(**input_shapes)
        return dict(zip(sym.list_outputs(), s_outputs))


class ActivationDoc(SymbolDoc):
    """
    Examples
    --------
    >>> relu = mx.sym.Activation(data, act_type='relu')
    """


class DropoutDoc(SymbolDoc):
    """
    Examples
    --------
    >>> out = mx.sym.Dropout(data, p=0.5)
    """


class EmbeddingDoc(SymbolDoc):
    """
    Examples
    --------
    >>> emb = mx.sym.Embedding(data, input_dim=1000, output_dim=16)
    """


class FlattenDoc(SymbolDoc):
    """
    Examples
    --------
    >>> flat = mx.sym.Flatten(data)
    """


class FullyConnectedDoc(SymbolDoc):
    """
    Examples
    --------
    >>> fc = mx.sym.FullyConnected(data, num_hidden=128)
    """


class ConcatDoc(SymbolDoc):
    """
    Examples
    --------
    >>> out = mx.sym.Concat(a, b, dim=1)
    """


class BroadcastPlusDoc(SymbolDoc):
    """
    Examples
    --------
    >>> c = mx.sym.broadcast_plus(a, b)
    """


def _build_doc(func_name, desc, arg_names, arg_types, arg_desc,
               key_var_num_args=None, ret_type=None):
    """Assemble a generated-wrapper docstring (reference
    symbol_doc.py:_build_doc)."""
    doc_str = desc + '\n\n' + _build_param_doc(arg_names, arg_types,
                                               arg_desc)
    if key_var_num_args:
        doc_str += '\nThis function supports variable length of '
        doc_str += 'positional input.\n'
    if ret_type:
        doc_str += '\nReturns\n-------\n%s\n    The result.' % ret_type
    hook = globals().get('%sDoc' % func_name)
    if hook and hook.__doc__:
        doc_str += hook.__doc__
    return doc_str
