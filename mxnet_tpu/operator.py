"""Custom operators defined in Python.

Reference: python/mxnet/operator.py (880 LoC): CustomOp/CustomOpProp
registered via MXCustomOpRegister (src/operator/custom/custom.cc runs the
python callbacks on a dedicated thread). Here custom ops run on the host
directly — they receive/return NDArrays and participate in the imperative
tape and the symbolic executor's staged mode.
"""
import numpy as np

from .ndarray import NDArray, array, zeros
from .ops import registry as _reg

__all__ = ['CustomOp', 'CustomOpProp', 'register', 'get_all_registered_operators']

_CUSTOM_OPS = {}


class CustomOp:
    """Base class for custom python operators (reference operator.py:508)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError()

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError()

    def assign(self, dst, req, src):
        if req == 'null':
            return
        if req in ('write', 'inplace'):
            dst[:] = src
        elif req == 'add':
            dst[:] = dst + src


class CustomOpProp:
    """Reference operator.py:667 — declares shapes/types and creates the op."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        """Default: every output shaped like input 0, no aux shapes —
        a prop declaring auxiliary states must override this (the
        reference's default also cannot derive aux shapes,
        operator.py:108)."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def list_outputs(self):
        return ['output']

    def list_arguments(self):
        return ['data']

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError()


def register(reg_name):
    """Reference operator.py register decorator: makes the op callable as
    mx.nd.Custom(..., op_type=reg_name) / mx.sym.Custom(...)."""
    def do_register(prop_cls):
        _CUSTOM_OPS[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_all_registered_operators():
    return list(_CUSTOM_OPS)


def invoke_custom(op, inputs, out_shapes, out_dtypes=None, aux=None):
    """Run a CustomOp instance eagerly on NDArrays, recording it on the
    imperative tape when autograd is active (reference custom.cc runs
    the python callbacks outside the graph with ExecType::kLocal and
    registers a backward entry; here the backward entry is a TapeNode
    whose vjp calls op.backward). ``is_train`` follows the training
    mode flag (reference contract), not the recording flag."""
    from . import autograd as _ag
    from .ndarray.ndarray import _parent_entry

    if out_dtypes is None:
        out_dtypes = ['float32'] * len(out_shapes)
    if aux is None:
        aux = []
    out_nd = [zeros(tuple(s), dtype=t)
              for s, t in zip(out_shapes, out_dtypes)]
    recording = _ag.is_recording() and any(
        i._node is not None or i._leaf is not None for i in inputs)
    op.forward(is_train=_ag.is_training(),
               req=['write'] * len(out_nd), in_data=list(inputs),
               out_data=out_nd, aux=aux)
    if recording:
        def vjp_fn(cots):
            if len(out_nd) == 1:
                cots = (cots,)
            in_grads = [zeros(i.shape, dtype=i.dtype) for i in inputs]
            op.backward(req=['write'] * len(inputs),
                        out_grad=[NDArray(c, None) for c in cots],
                        in_data=list(inputs), out_data=out_nd,
                        in_grad=in_grads, aux=aux)
            return tuple(g._data for g in in_grads)

        node = _ag.record_op(vjp_fn, [_parent_entry(i) for i in inputs],
                             len(out_nd), len(inputs))
        node.head_ids = [(tuple(o.shape), o._data.dtype) for o in out_nd]
        for i, o in enumerate(out_nd):
            o._node = node
            o._out_idx = i
    return out_nd[0] if len(out_nd) == 1 else out_nd


_CUSTOM_RESERVED = ('op_type', 'num_args', '__is_train__', 'name',
                    '__op_instance__')


def _split_aux(prop, arrays):
    """Reference custom.cc appends aux states after the regular inputs;
    when the caller passed them, split them off so they persist (the
    caller owns the buffers and sees the mutations)."""
    n_aux = len(prop.list_auxiliary_states())
    n_args = len(prop.list_arguments())
    if n_aux and len(arrays) == n_args + n_aux:
        return list(arrays[:n_args]), list(arrays[n_args:])
    return list(arrays), None


def _infer_and_alloc(prop, inputs, aux_nd):
    """Shared shape/type inference + buffer allocation for the eager
    and symbolic Custom paths. Returns (out_shapes, out_types, aux)."""
    shapes = [list(a.shape) for a in inputs]
    _, out_shapes, aux_shapes = prop.infer_shape(shapes)
    in_types = [a.dtype for a in inputs]
    _, out_types, aux_types = prop.infer_type(in_types)
    if aux_nd is None:
        # no caller-provided aux: allocate fresh (stateless per call)
        aux_nd = [zeros(tuple(s), dtype=t)
                  for s, t in zip(aux_shapes or [], aux_types or [])]
    return out_shapes, out_types, aux_nd, in_types, shapes


def custom_eager(*args, **kwargs):
    """Eager nd.Custom: host execution + tape recording (installed over
    the registry-generated wrapper in ndarray/__init__.py). Trailing
    positional NDArrays beyond list_arguments() are auxiliary states
    (reference custom.cc input layout) — caller-owned, mutated in
    place, persistent across calls."""
    op_type = kwargs.pop('op_type')
    kwargs.pop('name', None)
    arrays = [a for a in args if isinstance(a, NDArray)]
    prop = _CUSTOM_OPS[op_type](**kwargs)
    inputs, aux_nd = _split_aux(prop, arrays)
    out_shapes, out_types, aux_nd, in_types, shapes = \
        _infer_and_alloc(prop, inputs, aux_nd)
    op = prop.create_operator(None, [tuple(s) for s in shapes], in_types)
    return invoke_custom(op, inputs, out_shapes, out_dtypes=out_types,
                         aux=aux_nd)


def _make_prop(attrs):
    prop_kwargs = {k: v for k, v in attrs.items()
                   if k not in _CUSTOM_RESERVED}
    return _CUSTOM_OPS[attrs['op_type']](**prop_kwargs)


def _custom_shape(attrs, in_shapes):
    """shape_fn for the traced executor path (host_bridge): delegate to
    the prop's infer_shape callback (the reference routes
    CustomOpProp::InferShape to the same python callbacks,
    custom.cc:160-220). Trailing aux-state inputs are split off first,
    mirroring _split_aux — infer_shape sees argument shapes only.
    Output dtypes are reported as None ("same as input 0", the
    CustomOpProp.infer_type default): shape_fn has no dtype
    information, so props whose outputs change dtype relative to input
    0 are only supported imperatively."""
    prop = _make_prop(attrs)
    shapes, _ = _split_aux(prop, list(in_shapes))
    _, out_shapes, _ = prop.infer_shape([list(s) for s in shapes])
    return [tuple(s) for s in out_shapes], [None] * len(out_shapes)


def _node_operator(attrs, prop, shapes, in_types):
    """One CustomOp instance per executor node: host_bridge passes the
    same (executor-copied) attrs dict to forward and backward, so the
    instance is stashed on it — ops commonly cache forward state on
    ``self`` for backward, and the reference binds one operator per
    executor the same way (custom.cc CreateOperatorEx). Lifetime is the
    executor's, not the process's."""
    op = attrs.get('__op_instance__')
    if op is None:
        op = prop.create_operator(None, [tuple(s) for s in shapes],
                                  in_types)
        attrs['__op_instance__'] = op
    return op


@_reg.register('Custom', variadic=True, key_var_num_args='num_args',
               host=True, shape_fn=_custom_shape, train_aware=True)
def _custom_fn(attrs, *arrays):
    """Host-python bridge: under a traced executor this runs inside
    jax.pure_callback (host_bridge — the reference's ExecType::kLocal,
    custom.cc:380-405 runs the python callbacks on a dedicated thread
    the same way). Aux states here are per-call buffers (trailing inputs
    persist only as executor-bound arrays; true in-place aux mutation
    needs the eager path)."""
    import jax.numpy as jnp
    prop = _make_prop(attrs)
    in_all = [NDArray(jnp.asarray(a)) for a in arrays]
    inputs, aux_nd = _split_aux(prop, in_all)
    out_shapes, out_types, aux_nd, in_types, shapes = \
        _infer_and_alloc(prop, inputs, aux_nd)
    out_nd = [zeros(tuple(s), dtype=t)
              for s, t in zip(out_shapes, out_types)]
    op = _node_operator(attrs, prop, shapes, in_types)
    op.forward(is_train=attrs.get('__is_train__', False),
               req=['write'] * len(out_nd), in_data=inputs, out_data=out_nd,
               aux=aux_nd)
    if len(out_nd) == 1:
        return out_nd[0]._data
    return tuple(o._data for o in out_nd)


def _custom_backward(attrs, gouts, ins, outs):
    """legacy_backward hook (host_bridge custom_vjp): routes cotangents
    through the user's CustomOp.backward (reference custom.cc backward
    entry)."""
    import jax.numpy as jnp
    prop = _make_prop(attrs)
    in_all = [NDArray(jnp.asarray(a)) for a in ins]
    inputs, aux_nd = _split_aux(prop, in_all)
    if aux_nd is None:
        aux_nd = []
    out_nd = [NDArray(jnp.asarray(o)) for o in outs]
    gout_nd = [NDArray(jnp.asarray(g)) for g in gouts]
    in_grad = [zeros(tuple(a.shape), dtype=a.dtype) for a in inputs]
    op = _node_operator(attrs, prop, [tuple(a.shape) for a in inputs],
                        [a.dtype for a in inputs])
    op.backward(req=['write'] * len(in_grad), out_grad=gout_nd,
                in_data=inputs, out_data=out_nd, in_grad=in_grad,
                aux=aux_nd)
    grads = [np.asarray(g.asnumpy(), dtype=np.asarray(i).dtype)
             for g, i in zip(in_grad, ins)]
    # aux inputs (if bound as trailing executor inputs) get zero grads
    for extra in ins[len(grads):]:
        grads.append(np.zeros_like(np.asarray(extra)))
    return tuple(grads)


_reg.get('Custom').legacy_backward = _custom_backward


# ---------------------------------------------------------------------------
# Legacy pre-CustomOp python operator API (reference operator.py:36-242
# PythonOp/NumpyOp and :243-380 NDArrayOp, bridged by src/operator/
# native_op.cc and ndarray_op.cc). get_symbol() builds a `_Native` /
# `_NDArray` symbol whose `info` attr keys the live instance (the
# reference passes a callback-struct pointer the same way).
# ---------------------------------------------------------------------------

class PythonOp:
    """Base class for legacy python operators (reference operator.py:36)."""

    _op_name = '_Native'
    _ref_holder = []  # keep instances alive, like the reference

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def __call__(self, *args, **kwargs):
        return self.get_symbol(*args, **kwargs)

    def get_symbol(self, *args, **kwargs):
        from .ops.legacy_ops import register_legacy_callback
        # the callback table holds the only (permanent) strong reference
        kwargs['info'] = register_legacy_callback(self)
        from . import symbol as _sym_mod
        make = getattr(_sym_mod._internal, self._op_name)
        return make(*args, **kwargs)

    def forward(self, in_data, out_data):
        out_data[0][:] = in_data[0]

    def backward(self, out_grad, in_data, out_data, in_grad):
        in_grad[0][:] = 1.0

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_outputs(self):
        return ['output']

    def list_arguments(self):
        return ['data']

    def need_top_grad(self):
        return self.need_top_grad_


class NumpyOp(PythonOp):
    """Legacy numpy operator (reference operator.py:158 NumpyOp)."""
    _op_name = '_Native'


class NDArrayOp(PythonOp):
    """Legacy NDArray operator (reference operator.py:243): callbacks
    receive NDArrays rather than numpy buffers."""
    _op_name = '_NDArray'

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


__all__ += ['PythonOp', 'NumpyOp', 'NDArrayOp']
