"""Weight initializers.

Reference: python/mxnet/initializer.py (726 LoC): Initializer base with
pattern dispatch, Uniform/Normal/Orthogonal/Xavier/MSRAPrelu/Bilinear/
One/Zero/Constant/FusedRNN, InitDesc, registry + Mixed.
"""
import json
import re

import numpy as np

from .base import string_types
from . import ndarray as nd
from . import random as _random

__all__ = ['InitDesc', 'Initializer', 'Uniform', 'Normal', 'Orthogonal',
           'LSTMBias',
           'Xavier', 'MSRAPrelu', 'Bilinear', 'One', 'Zero', 'Constant',
           'Load', 'Mixed', 'register', 'init']

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


class InitDesc(str):
    """Name + attrs descriptor (reference initializer.py:36)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base class; __call__ dispatches on name pattern (reference :95)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, string_types):
            raise TypeError('desc must be a string or InitDesc')
        if isinstance(desc, InitDesc) and desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get('__init__', '') if isinstance(desc, InitDesc) else ''
        if init:
            klass, kwargs = json.loads(init)
            _INIT_REGISTRY[klass.lower()](**kwargs)._init_weight(desc, arr)
            return
        name = str(desc)
        if name.endswith('weight') or name.endswith('parameters'):
            # 'parameters' = fused-RNN flat vector (ops/rnn_ops.py layout)
            self._init_weight(name, arr)
        elif name.endswith('bias'):
            self._init_bias(name, arr)
        elif name.endswith('gamma'):
            self._init_gamma(name, arr)
        elif name.endswith('beta'):
            self._init_beta(name, arr)
        elif name.endswith('moving_mean') or name.endswith('running_mean'):
            self._init_zero(name, arr)
        elif name.endswith('moving_var') or name.endswith('running_var'):
            self._init_one(name, arr)
        elif name.endswith('moving_inv_var'):
            self._init_zero(name, arr)
        elif name.endswith('moving_avg'):
            self._init_zero(name, arr)
        elif name.endswith('min') or name.endswith('max'):
            self._init_zero(name, arr)
        else:
            self._init_default(name, arr)

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError()

    def _init_default(self, name, arr):
        raise ValueError(
            'Unknown initialization pattern for %s. Default initialization '
            'is limited to "weight", "bias", "gamma" (1.0), and "beta" (0.0).'
            % name)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0

    _init_default = _init_weight


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0

    _init_default = _init_weight


@register
class LSTMBias(Initializer):
    """All LSTM biases 0 except the forget gate at ``forget_bias``
    (reference initializer.py:653, Jozefowicz et al. 2015)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = int(arr.shape[0] / 4)
        arr[num_hidden:2 * num_hidden] = self.forget_bias

    # our dispatch routes '*_bias' names here (reference reaches its
    # _init_weight through per-param __init__ attrs instead)
    _init_bias = _init_weight
    _init_default = _init_weight


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value

    _init_default = _init_weight


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = _random.host_rng().uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = _random.host_rng().normal(0, self.sigma, arr.shape)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type='uniform'):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == 'uniform':
            tmp = _random.host_rng().uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _random.host_rng().normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = self.scale * q.reshape(arr.shape)


@register
class Xavier(Initializer):
    """Reference initializer.py Xavier (gaussian/uniform, avg/in/out)."""

    def __init__(self, rnd_type='uniform', factor_type='avg', magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.
        if len(shape) < 2:
            raise ValueError('Xavier initializer cannot be applied to vector '
                             '%s. This may be due to missing shape info' % name)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = fan_in
        if self.factor_type == 'avg':
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == 'out':
            factor = fan_out
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == 'uniform':
            arr[:] = _random.host_rng().uniform(-scale, scale, arr.shape)
        else:
            arr[:] = _random.host_rng().normal(0, scale, arr.shape)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type='avg', slope=0.25):
        magnitude = 2. / (1 + slope ** 2)
        super().__init__('gaussian', factor_type, magnitude)
        self._kwargs = {'factor_type': factor_type, 'slope': slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = np.zeros(arr.size, dtype='float32')
        shape = arr.shape
        f = np.ceil(shape[3] / 2.)
        c = (2 * f - 1 - f % 2) / (2. * f)
        for i in range(arr.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


class Load:
    """Init from saved dict, fall back to default_init (reference :516)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = nd.load(param)
        self.param = {}
        for name, arr in param.items():
            if name.startswith('arg:') or name.startswith('aux:'):
                self.param[name[4:]] = arr
            else:
                self.param[name] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if arr.shape != self.param[name].shape:
                raise ValueError('Parameter %s cannot be initialized from '
                                 'loading. Shape mismatch, target %s vs loaded %s'
                                 % (name, str(arr.shape), str(self.param[name].shape)))
            arr[:] = self.param[name]
        else:
            if self.default_init is None:
                raise ValueError('Cannot Initialize parameter: %s' % name)
            self.default_init(name, arr)


class Mixed:
    """Patterns → initializers (reference :560)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError('patterns and initializers must have same length')
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, i in self.map:
            if prog.match(name):
                i(name, arr)
                return
        raise ValueError('Parameter name %s did not match any pattern' % name)


# FusedRNN initializer (reference :600) — fills the flat RNN parameter vector
@register
class FusedRNN(Initializer):
    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = _INIT_REGISTRY[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        arr[:] = _random.host_rng().uniform(-0.07, 0.07, arr.shape) \
            if self._init is None else arr.asnumpy()
        if self._init is not None:
            a = np.zeros(arr.shape, dtype='float32')
            tmp = nd.array(a)
            self._init(InitDesc('weight'), tmp)
            arr[:] = tmp
        if self._mode == 'lstm':
            # set forget-gate biases: locate bias region and f-gate slice
            from .ops.rnn_ops import rnn_param_size, _gates
            H = self._num_hidden
            L = self._num_layers
            dirs = 2 if self._bidirectional else 1
            g = _gates(self._mode)
            a = arr.asnumpy().copy()
            bias_start = arr.size - L * dirs * g * H * 2
            for ld in range(L * dirs):
                for which in range(2):  # bW, bR
                    base = bias_start + ld * g * H * 2 + which * g * H
                    a[base + H: base + 2 * H] = self._forget_bias / 2.0
            arr[:] = a


def init(name):
    return _INIT_REGISTRY[name.lower()]


_STRING_ALIASES = {'zeros': 'zero', 'ones': 'one'}


def create(spec):
    """Resolve an initializer spec: an Initializer passes through; a
    string ('normal', 'xavier', 'zeros', ...) resolves via the registry
    with the common plural aliases (the single resolution point used by
    gluon Parameters and layers)."""
    if spec is None or not isinstance(spec, str):
        return spec
    key = _STRING_ALIASES.get(spec.lower(), spec.lower())
    try:
        return _INIT_REGISTRY[key]()
    except KeyError:
        raise ValueError('unknown initializer %r (known: %s)'
                         % (spec, ', '.join(sorted(_INIT_REGISTRY))))
