"""Experimental contrib namespace (reference python/mxnet/contrib/).

Submodules: autograd (the older experimental autograd API surface),
ndarray/symbol (contrib-op namespaces), tensorboard (metric logging).
"""
from . import autograd  # noqa: F401
from . import ndarray  # noqa: F401
from . import symbol  # noqa: F401
from . import tensorboard  # noqa: F401
