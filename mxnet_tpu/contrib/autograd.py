"""Experimental autograd API (reference contrib/autograd.py) — thin
adapter over mxnet_tpu.autograd, kept for ported code. The modern API
is mx.autograd.
"""
from .. import autograd as _ag

__all__ = ['set_is_training', 'train_section', 'test_section',
           'backward', 'grad_and_loss', 'grad', 'mark_variables',
           'TrainingStateScope', 'compute_gradient']


def set_is_training(is_train):
    """Returns the previous state (reference contrib/autograd.py:31)."""
    prev = _ag.is_training()
    _ag.set_training(is_train)
    _ag.set_recording(is_train)
    return prev


class _Section:
    def __init__(self, train):
        self._train = train

    def __enter__(self):
        self._prev_t = _ag.is_training()
        self._prev_r = _ag.is_recording()
        _ag.set_training(self._train)
        _ag.set_recording(self._train)

    def __exit__(self, *args):
        _ag.set_training(self._prev_t)
        _ag.set_recording(self._prev_r)


def train_section():
    """``with train_section():`` — record + train mode (reference :56)."""
    return _Section(True)


def test_section():
    return _Section(False)


def backward(outputs, out_grads=None, retain_graph=False):
    return _ag.backward(outputs, out_grads, retain_graph=retain_graph)


grad_and_loss = _ag.grad_and_loss
grad = _ag.grad
mark_variables = _ag.mark_variables


# reference contrib/autograd.py:53 exports the scope class itself and a
# compute_gradient helper
TrainingStateScope = _Section


def compute_gradient(outputs):
    """Compute gradients of outputs w.r.t. marked variables
    (reference contrib/autograd.py:105)."""
    _ag.backward(outputs)
