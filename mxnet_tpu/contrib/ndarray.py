"""Contrib NDArray ops (reference contrib/ndarray.py) — the same
namespace as mx.nd.contrib."""
from ..ndarray.contrib import *  # noqa: F401,F403
from ..ndarray import contrib as _c

__all__ = getattr(_c, '__all__', [])


def __getattr__(name):
    return getattr(_c, name)
