"""TensorBoard logging callback (reference contrib/tensorboard.py
LogMetricsCallback over the optional tensorboard SummaryWriter).
"""
__all__ = ['LogMetricsCallback']


class LogMetricsCallback:
    """Log metric values as tensorboard scalars each batch.

    Needs a SummaryWriter provider (`tensorboardX` or `torch.utils.
    tensorboard`); raises a clear ImportError otherwise (the reference
    requires the standalone `tensorboard` python package the same way).
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        try:
            from tensorboardX import SummaryWriter
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter
            except ImportError:
                raise ImportError(
                    'LogMetricsCallback needs tensorboardX or torch '
                    'with tensorboard support installed')
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in zip(*_as_lists(param.eval_metric.get())):
            if self.prefix is not None:
                name = '%s-%s' % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)


def _as_lists(name_value):
    name, value = name_value
    if isinstance(name, str):
        return [name], [value]
    return name, value
