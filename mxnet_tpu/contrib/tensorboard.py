"""TensorBoard logging callback (reference contrib/tensorboard.py
LogMetricsCallback over the optional tensorboard SummaryWriter).
"""
__all__ = ['LogMetricsCallback']


class LogMetricsCallback:
    """Log metric values as tensorboard scalars each batch.

    Uses a SummaryWriter provider when one is installed (`tensorboardX`
    or `torch.utils.tensorboard`, in that order — the reference
    behavior); otherwise falls back to the framework's own
    dependency-free tfevents writer
    (:class:`mxnet_tpu.telemetry.ledger.TfEventsWriter`), so
    ``tensorboard --logdir`` works without either package installed.
    The callback API is unchanged either way.
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        SummaryWriter = None
        try:
            from tensorboardX import SummaryWriter
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter
            except ImportError:
                SummaryWriter = None
        if SummaryWriter is None:
            # native fallback: the hand-rolled TFRecord/Event encoder
            # (golden-bytes tested) — add_scalar is the only method the
            # callback needs
            from ..telemetry.ledger import TfEventsWriter
            SummaryWriter = TfEventsWriter
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in zip(*_as_lists(param.eval_metric.get())):
            if self.prefix is not None:
                name = '%s-%s' % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)


def _as_lists(name_value):
    name, value = name_value
    if isinstance(name, str):
        return [name], [value]
    return name, value
