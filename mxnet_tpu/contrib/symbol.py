"""Contrib Symbol ops (reference contrib/symbol.py) — the same
namespace as mx.sym.contrib."""
from ..symbol.contrib import *  # noqa: F401,F403
from ..symbol import contrib as _c

__all__ = getattr(_c, '__all__', [])


def __getattr__(name):
    return getattr(_c, name)
