"""Optimizers.

Reference: python/mxnet/optimizer.py (1,040 LoC): Optimizer base with
registry + lr/wd multipliers, SGD (+momentum, multi-precision master
weights :338), NAG, SGLD, DCASGD, Adam, AdaGrad, RMSProp, AdaDelta, Ftrl,
Adamax, Nadam, Test, Updater (:974) and get_updater (:1027).

Fast paths call the fused update ops (ops/optimizer_ops.py ≙
src/operator/optimizer_op.cc) — under jit each update is one fused
HBM-bound kernel.
"""
import math
import pickle
import logging

import numpy as np

from . import ndarray as nd
from .ndarray import NDArray, zeros
from .base import normalize_value


def _is_half(dtype):
    """True for the half-precision dtypes multi_precision applies to —
    float16 (reference optimizer.py:338) and bfloat16, the TPU half
    type the bench's mp path trains in."""
    return str(dtype) in ('float16', 'bfloat16')


__all__ = ['Optimizer', 'SGD', 'NAG', 'SGLD', 'DCASGD', 'ccSGD', 'Adam',
           'AdaGrad', 'RMSProp', 'AdaDelta', 'Ftrl', 'Adamax', 'Nadam',
           'Test', 'Updater', 'get_updater', 'register', 'create']


class Optimizer:
    """Base optimizer (reference optimizer.py:33)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError('Cannot find optimizer %s' % name)

    def __init__(self, rescale_grad=1., param_idx2name=None, wd=0.,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            'param_idx2name should be a dict of param indexes to names.'
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None else ()
        self.param_dict = param_dict or {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and _is_half(weight.dtype):
            weight_master_copy = weight.astype('float32')
            return (weight_master_copy, self.create_state(index, weight_master_copy))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _is_half(weight.dtype):
            weight_master, orig_state = state
            grad32 = grad.astype('float32')
            self.update(index, weight_master, grad32, orig_state)
            weight._data = weight_master._data.astype(weight._data.dtype)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning('LRScheduler of the optimizer has already been '
                              'defined. Note that set_learning_rate can mutate '
                              'the value of the learning rate of the optimizer '
                              'only when the LRScheduler of the optimizer is '
                              'undefined.')
        self.lr = lr

    def set_lr_scale(self, args_lrscale):
        raise DeprecationWarning('Use set_lr_mult instead.')

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and '__lr_mult__' in attr[name]:
                    self.lr_mult[name] = float(attr[name]['__lr_mult__'])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith('_weight') or n.endswith('_gamma')):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and '__wd_mult__' in attr[name]:
                    self.wd_mult[name] = float(attr[name]['__wd_mult__'])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def __getstate__(self):
        ret = self.__dict__.copy()
        return ret


register = Optimizer.register
create = Optimizer.create_optimizer


def _as_clip(v):
    return -1.0 if v is None else float(v)


@register
class SGD(Optimizer):
    """SGD with momentum and optional fp16 master weights (reference :338)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype=str(weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_as_clip(self.clip_gradient))
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=weight,
                              momentum=self.momentum, **kwargs)
        else:
            nd.sgd_update(weight, grad, out=weight, **kwargs)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _is_half(weight.dtype):
            self._update_count(index)
            lr = self._get_lr(index)
            wd = self._get_wd(index)
            weight32, mom = state
            kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                          clip_gradient=_as_clip(self.clip_gradient))
            if mom is not None:
                nd.mp_sgd_mom_update(weight, grad, mom, weight32, out=weight,
                                     momentum=self.momentum, **kwargs)
            else:
                nd.mp_sgd_update(weight, grad, weight32, out=weight, **kwargs)
        else:
            self.update(index, weight, grad, state)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference :410) via the fused
    nag_mom_update op — one HBM pass per param under jit, and the same
    lowering the fused fit window uses, so the two paths agree
    bit-for-bit."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_as_clip(self.clip_gradient))
        if state is not None:
            nd.nag_mom_update(weight, grad, state, out=weight,
                              momentum=self.momentum, **kwargs)
        else:
            nd.sgd_update(weight, grad, out=weight, **kwargs)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference :451)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        weight += -lr / 2 * (grad + wd * weight) + \
            nd.random.normal(0, math.sqrt(lr), weight.shape,
                             dtype=str(weight._data.dtype))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference :480)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, weight.context), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        mon, previous_weight = state
        if mon:
            mon *= self.momentum
            mon += -lr * (grad + wd * weight + self.lamda *
                          grad * grad * (weight - previous_weight))
        else:
            mon = -lr * (grad + wd * weight + self.lamda *
                         grad * grad * (weight - previous_weight))
            state = (mon, previous_weight)
        previous_weight._data = weight._data
        weight += mon


@register
class ccSGD(SGD):
    """Deprecated alias of SGD (reference :545)."""


@register
class Adam(Optimizer):
    """Reference optimizer.py Adam (fused adam_update op)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=str(weight._data.dtype)),
                zeros(weight.shape, weight.context, dtype=str(weight._data.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        nd.adam_update(weight, grad, mean, var, out=weight, lr=lr, wd=wd,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, rescale_grad=self.rescale_grad,
                       clip_gradient=_as_clip(self.clip_gradient))


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        history = state
        history += grad * grad
        weight += -lr * (grad / nd.sqrt(history + self.float_stable_eps) +
                         wd * weight)


@register
class RMSProp(Optimizer):
    """Reference RMSProp (centered=False → rmsprop_update; True → alex)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context))
        return (zeros(weight.shape, weight.context),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      gamma1=self.gamma1, epsilon=self.epsilon,
                      clip_gradient=_as_clip(self.clip_gradient),
                      clip_weights=_as_clip(self.clip_weights))
        if not self.centered:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=weight, **kwargs)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta, out=weight,
                                  gamma2=self.gamma2, **kwargs)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._data = (self.rho * acc_g + (1. - self.rho) * grad * grad)._data
        current_delta = (nd.sqrt(acc_delta + self.epsilon) /
                         nd.sqrt(acc_g + self.epsilon)) * grad
        acc_delta._data = (self.rho * acc_delta +
                           (1. - self.rho) * current_delta * current_delta)._data
        weight._data = (weight - current_delta - wd * weight)._data


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),   # z
                zeros(weight.shape, weight.context))   # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        lr = self._get_lr(index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, out=weight, lr=lr,
                       lamda1=self.lamda1, beta=self.beta, wd=wd,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=_as_clip(self.clip_gradient))


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        m_t._data = (self.beta1 * m_t + (1. - self.beta1) * grad)._data
        u_t._data = nd.maximum(self.beta2 * u_t, nd.abs(grad))._data
        weight += -lr * m_t / u_t


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1. - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1. - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t._data = (self.beta1 * m_t + (1. - self.beta1) * grad)._data
        v_t._data = (self.beta2 * v_t + (1. - self.beta2) * grad * grad)._data
        grad_prime = grad / (1. - self.m_schedule)
        m_t_prime = m_t / (1. - m_schedule_next)
        v_t_prime = v_t / (1. - self.beta2 ** t)
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        weight += -lr * m_t_bar / (nd.sqrt(v_t_prime) + self.epsilon)


@register
class Test(Optimizer):
    """Deterministic test optimizer (reference :957) — used by the
    distributed kvstore tests for exact-arithmetic checks."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state._data = weight._data


class Updater:
    """Wraps an optimizer for kvstore use (reference :974)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        return pickle.dumps((self.states, self.optimizer) if dump_optimizer
                            else self.states)


def get_updater(optimizer):
    return Updater(optimizer)
