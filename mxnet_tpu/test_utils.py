"""Testing utilities — the assertion core the whole test suite builds on.

Reference: python/mxnet/test_utils.py (1,317 LoC): assert_almost_equal with
per-dtype tolerances, check_numeric_gradient (finite differences vs symbolic
backward), check_symbolic_forward/backward, check_consistency (one symbol run
on several ctx/dtype combos, outputs & grads cross-compared — the CPU-vs-GPU
test became CPU-vs-TPU here), rand_ndarray, simple_forward helpers.
"""
import numpy as np

from . import ndarray as nd
from . import symbol as sym
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = ['default_context', 'set_default_context', 'rand_shape_2d',
           'rand_shape_3d', 'rand_ndarray', 'rand_sparse_ndarray',
           'assert_almost_equal', 'almost_equal', 'same',
           'get_rtol', 'get_atol', 'check_numeric_gradient',
           'check_symbolic_forward', 'check_symbolic_backward',
           'check_consistency', 'simple_forward', 'rand_np']

_default_ctx = None


def default_context():
    return _default_ctx if _default_ctx is not None else current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def default_dtype():
    return np.float32


def rand_np(*shape):
    return np.random.randn(*shape).astype(np.float32)


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_ndarray(shape, stype='default', density=None, dtype=None):
    if stype == 'default':
        return array(np.random.uniform(-1, 1, shape), dtype=dtype)
    return rand_sparse_ndarray(shape, stype, density=density,
                               dtype=dtype)[0]


def rand_sparse_ndarray(shape, stype, density=None, dtype=None,
                        data_init=None, rsp_indices=None,
                        distribution=None):
    """(sparse NDArray, (data, idx...)) pair — reference
    test_utils.py:rand_sparse_ndarray. Explicit ``rsp_indices`` pins the
    stored rows of a row_sparse array; ``data_init`` fills values;
    csr ``distribution`` is 'uniform' (default) or 'powerlaw'."""
    from .ndarray.sparse import row_sparse_array, csr_matrix
    density = 0.5 if density is None else density
    dtype = np.float32 if dtype is None else np.dtype(dtype)
    if stype == 'row_sparse':
        if rsp_indices is not None:
            idx = np.asarray(sorted(set(int(i) for i in rsp_indices)),
                             np.int64)
        else:
            mask = np.random.uniform(0, 1, shape[0]) < density
            idx = np.nonzero(mask)[0].astype(np.int64)
        vals = np.random.uniform(-1, 1, (len(idx),) + tuple(shape[1:]))
        if data_init is not None:
            vals[:] = data_init
        arr = row_sparse_array((vals.astype(dtype), idx), shape=shape,
                               dtype=dtype)
        return arr, (vals.astype(dtype), idx)
    if stype == 'csr':
        if distribution == 'powerlaw':
            dense = _get_powerlaw_dataset_csr(shape[0], shape[1], density)
        elif distribution in (None, 'uniform'):
            dense = _get_uniform_dataset_csr(shape[0], shape[1], density)
        else:
            raise ValueError('unknown csr distribution %r' % distribution)
        if data_init is not None:
            dense[dense != 0] = data_init
        arr = csr_matrix(dense.astype(dtype), dtype=dtype)
        return arr, (arr.data.asnumpy(), arr.indptr.asnumpy(),
                     arr.indices.asnumpy())
    raise ValueError(stype)


def _validate_csr_generation_inputs(num_rows, num_cols, density):
    """Shared sanity checks for the csr dataset generators (reference
    test_utils.py has the same guard for its uniform/powerlaw csr
    factories)."""
    if num_rows <= 0 or num_cols <= 0:
        raise ValueError('csr shape must be positive, got (%d, %d)'
                         % (num_rows, num_cols))
    if not 0 <= density <= 1:
        raise ValueError('density must be in [0, 1], got %s' % density)


def _get_uniform_dataset_csr(num_rows, num_cols, density=0.1):
    """Dense ndarray whose nonzeros are uniformly scattered — the
    reference's uniform csr dataset distribution."""
    _validate_csr_generation_inputs(num_rows, num_cols, density)
    dense = np.random.uniform(-1, 1, (num_rows, num_cols))
    dense *= np.random.uniform(0, 1, (num_rows, num_cols)) < density
    return dense


def _get_powerlaw_dataset_csr(num_rows, num_cols, density=0.1):
    """Dense ndarray whose per-row nonzero count doubles row to row
    until the density budget is spent — the reference's powerlaw csr
    distribution, modeling the skewed feature popularity real CTR/LibSVM
    datasets have (a few hot rows, a long sparse tail)."""
    _validate_csr_generation_inputs(num_rows, num_cols, density)
    budget = int(num_rows * num_cols * density)
    dense = np.zeros((num_rows, num_cols))
    nnz_row = 1
    for i in range(num_rows):
        take = min(nnz_row, num_cols, budget)
        if take <= 0:
            break
        cols = np.random.choice(num_cols, size=take, replace=False)
        dense[i, cols] = np.random.uniform(-1, 1, take)
        budget -= take
        nnz_row *= 2
    return dense


# per-dtype default tolerances (reference test_utils.py:62 default_rtols).
# Only HALF types loosen the defaults; fp32/fp64/int keep the historical
# 1e-5/1e-20 so existing call sites are unchanged.
_DTYPE_RTOL = {np.dtype(np.float16): 1e-2, 'bfloat16': 1e-2}
_DTYPE_ATOL = {np.dtype(np.float16): 1e-3, 'bfloat16': 1e-2}


def _tol_key(x):
    name = getattr(getattr(x, 'dtype', None), 'name', None)
    if name == 'bfloat16':
        return 'bfloat16'
    try:
        return np.dtype(getattr(x, 'dtype', np.float32))
    except TypeError:
        return np.dtype(np.float32)


def get_rtol(a=None, b=None, rtol=None):
    """Dtype-aware default rtol: the loosest of the operand dtypes."""
    if rtol is not None:
        return rtol
    return max(_DTYPE_RTOL.get(_tol_key(a), 1e-5),
               _DTYPE_RTOL.get(_tol_key(b), 1e-5))


def get_atol(a=None, b=None, atol=None):
    if atol is not None:
        return atol
    return max(_DTYPE_ATOL.get(_tol_key(a), 1e-20),
               _DTYPE_ATOL.get(_tol_key(b), 1e-20))


def same(a, b):
    return np.array_equal(a, b)


def almost_equal(a, b, rtol=None, atol=None):
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    return np.allclose(a, b, rtol=rtol, atol=atol)


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def assert_almost_equal(a, b, rtol=None, atol=None, names=('a', 'b')):
    rtol = get_rtol(a, b, rtol)
    atol = get_atol(a, b, atol)
    a, b = _as_np(a), _as_np(b)
    if almost_equal(a, b, rtol, atol):
        return
    index = np.unravel_index(np.argmax(np.abs(a - b)), a.shape) \
        if a.shape else ()
    rel = np.abs(a - b) / (np.abs(b) + atol)
    raise AssertionError(
        'Items are not equal:\nError %f exceeds tolerance rtol=%f, atol=%f.'
        ' Location of maximum error: %s, %s=%f, %s=%f'
        % (float(rel.max()), rtol, atol, str(index), names[0],
           float(a[index]) if a.shape else float(a), names[1],
           float(b[index]) if b.shape else float(b)))


def simple_forward(sym_, ctx=None, is_train=False, **inputs):
    ctx = ctx or default_context()
    inputs = {k: array(v) for k, v in inputs.items()}
    exe = sym_.bind(ctx, args=inputs)
    exe.forward(is_train=is_train)
    outputs = [x.asnumpy() for x in exe.outputs]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def _parse_location(symbol, location, ctx):
    assert isinstance(location, (dict, list, tuple))
    if isinstance(location, dict):
        if set(location.keys()) != set(symbol.list_arguments()):
            raise ValueError('Symbol arguments and keys of the given location '
                             'do not match. symbol args:%s, location.keys():%s'
                             % (str(set(symbol.list_arguments())),
                                str(set(location.keys()))))
    else:
        location = {k: v for k, v in zip(symbol.list_arguments(), location)}
    return {k: array(v, ctx=ctx) if isinstance(v, np.ndarray) else
            (v.copyto(ctx) if isinstance(v, NDArray) else v)
            for k, v in location.items()}


def _parse_aux_states(symbol, aux_states, ctx):
    if aux_states is None:
        return {}
    if isinstance(aux_states, dict):
        return {k: array(v, ctx=ctx) if isinstance(v, np.ndarray) else v
                for k, v in aux_states.items()}
    return {k: array(v, ctx=ctx) for k, v in
            zip(symbol.list_auxiliary_states(), aux_states)}


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Central finite differences (reference test_utils.py numeric_grad)."""
    approx_grads = {k: np.zeros(v.shape, dtype=np.float32)
                    for k, v in location.items()}
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k in location:
        old_value = location[k].copy()
        for i in range(int(np.prod(old_value.shape))):
            idx = np.unravel_index(i, old_value.shape) if old_value.shape else ()
            # +eps
            pert = old_value.copy()
            pert[idx] += eps
            executor.arg_dict[k][:] = pert
            executor.forward(is_train=use_forward_train)
            f_peps = executor.outputs[0].asnumpy().sum()
            # -eps
            pert[idx] -= 2 * eps
            executor.arg_dict[k][:] = pert
            executor.forward(is_train=use_forward_train)
            f_neps = executor.outputs[0].asnumpy().sum()
            approx_grads[k][idx] = (f_peps - f_neps) / (2 * eps)
        executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(symbol, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, use_forward_train=True, ctx=None):
    """Finite differences vs the executor's backward (reference
    test_utils.py check_numeric_gradient)."""
    ctx = ctx or default_context()
    location = _parse_location(symbol, location, ctx)
    location_np = {k: v.asnumpy() for k, v in location.items()}
    aux = _parse_aux_states(symbol, aux_states, ctx)

    if grad_nodes is None:
        grad_nodes = [k for k in symbol.list_arguments()
                      if not k.endswith('label')]
    grad_req = {k: ('write' if k in grad_nodes else 'null')
                for k in symbol.list_arguments()}

    input_shapes = {k: v.shape for k, v in location.items()}
    executor = symbol.simple_bind(ctx, grad_req=grad_req, **input_shapes)
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k, v in aux.items():
        executor.aux_dict[k][:] = v

    executor.forward(is_train=True)
    assert len(executor.outputs) == 1, \
        'check_numeric_gradient only supports single-output symbols'
    executor.backward(out_grads=[nd.ones(executor.outputs[0].shape, ctx=ctx)])
    symbolic_grads = {k: executor.grad_dict[k].asnumpy() for k in grad_nodes}

    numeric_gradients = numeric_grad(
        executor, {k: location_np[k] for k in grad_nodes},
        eps=numeric_eps, use_forward_train=use_forward_train)

    for name in grad_nodes:
        fd_grad = numeric_gradients[name]
        sym_grad = symbolic_grads[name]
        assert_almost_equal(fd_grad, sym_grad, rtol, atol or 1e-4,
                            ('NUMERICAL_%s' % name, 'BACKWARD_%s' % name))


def check_symbolic_forward(symbol, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    ctx = ctx or default_context()
    location = _parse_location(symbol, location, ctx)
    aux = _parse_aux_states(symbol, aux_states, ctx)
    input_shapes = {k: v.shape for k, v in location.items()}
    executor = symbol.simple_bind(ctx, grad_req='null', **input_shapes)
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k, v in aux.items():
        executor.aux_dict[k][:] = v
    executor.forward(is_train=False)
    outputs = [x.asnumpy() for x in executor.outputs]
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, rtol, atol or 1e-20,
                            ('EXPECTED', 'FORWARD'))
    return outputs


def check_symbolic_backward(symbol, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req='write',
                            ctx=None):
    ctx = ctx or default_context()
    location = _parse_location(symbol, location, ctx)
    aux = _parse_aux_states(symbol, aux_states, ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(symbol.list_arguments(), expected)}
    input_shapes = {k: v.shape for k, v in location.items()}
    executor = symbol.simple_bind(ctx, grad_req=grad_req, **input_shapes)
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k, v in aux.items():
        executor.aux_dict[k][:] = v
    executor.forward(is_train=True)
    out_grads = [array(v, ctx=ctx) if isinstance(v, np.ndarray) else v
                 for v in out_grads]
    executor.backward(out_grads)
    grads = {k: v.asnumpy() for k, v in executor.grad_dict.items()
             if v is not None}
    for name in expected:
        if name in grads:
            assert_almost_equal(grads[name], expected[name], rtol,
                                atol or 1e-20,
                                ('BACKWARD_%s' % name, 'EXPECTED_%s' % name))
    return grads


def check_consistency(sym_, ctx_list, scale=1.0, grad_req='write',
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None):
    """Run one symbol on several ctx/dtype combos and cross-compare outputs
    and gradients (reference test_utils.py check_consistency — the CPU-vs-GPU
    test pattern, here CPU-vs-TPU / dtype-vs-dtype)."""
    if tol is None:
        tol = {np.dtype(np.float16): 1e-1, 'bfloat16': 1e-1,
               np.dtype(np.float32): 1e-3,
               np.dtype(np.float64): 1e-5, np.dtype(np.uint8): 0,
               np.dtype(np.int32): 0}
    else:
        # normalize caller keys to _tol_key's convention (bf16 np.dtype
        # keys == 'bfloat16' but do not HASH-match the string)
        tol = {('bfloat16' if getattr(k, 'name', None) == 'bfloat16'
                or k == 'bfloat16' else np.dtype(k)): v
               for k, v in tol.items()}

    assert len(ctx_list) > 1
    if isinstance(sym_, sym.Symbol):
        sym_ = [sym_] * len(ctx_list)
    else:
        assert len(sym_) == len(ctx_list)

    output_points = None
    exe_list = []
    for s, ctx in zip(sym_, ctx_list):
        ctx = dict(ctx)
        the_ctx = ctx.pop('ctx')
        type_dict = ctx.pop('type_dict', {})
        exe = s.simple_bind(the_ctx, grad_req=grad_req, type_dict=type_dict,
                            **ctx)
        exe_list.append(exe)

    # shared random init
    arg_params = arg_params or {}
    np.random.seed(0)
    args0 = exe_list[0].arg_dict
    init = {k: (arg_params[k] if k in arg_params else
                np.random.normal(size=v.shape, scale=scale))
            for k, v in args0.items()}
    for exe in exe_list:
        for k, v in init.items():
            exe.arg_dict[k][:] = v
        if aux_params:
            for k, v in aux_params.items():
                exe.aux_dict[k][:] = v

    # key by the executor's REAL output dtype: asnumpy() widens bf16 to
    # fp32 and would silently pick the fp32 tolerance
    dtypes = [_tol_key(exe.outputs[0]) for exe in exe_list]
    max_idx = int(np.argmax([2 if d == 'bfloat16' else np.dtype(d).itemsize
                             for d in dtypes]))

    for exe in exe_list:
        exe.forward(is_train=(grad_req != 'null'))
        if grad_req != 'null':
            exe.backward(exe.outputs)

    gt = ground_truth
    if gt is None:
        gt = {'outputs': [o.asnumpy() for o in exe_list[max_idx].outputs]}
        if grad_req != 'null':
            gt['grads'] = {k: v.asnumpy() for k, v in
                           exe_list[max_idx].grad_dict.items() if v is not None}

    for i, exe in enumerate(exe_list):
        if i == max_idx:
            continue
        t = max(tol[dtypes[i]], tol[dtypes[max_idx]])
        for o, o_gt in zip(exe.outputs, gt['outputs']):
            assert_almost_equal(o.asnumpy(), o_gt, rtol=t, atol=t)
        if grad_req != 'null':
            for name, g in exe.grad_dict.items():
                if g is not None and name in gt['grads']:
                    assert_almost_equal(g.asnumpy(), gt['grads'][name],
                                        rtol=t, atol=t)
    return gt


# ---------------------------------------------------------------------------
# remaining reference test_utils surface (reference test_utils.py): nan-
# tolerant comparisons, reduction/compat helpers, env/system utilities.
# ---------------------------------------------------------------------------

def rand_shape_nd(num_dim, dim=10):
    """Random shape with ``num_dim`` dims, each in [1, dim]."""
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Reduce with per-axis looping (reference test_utils.py:np_reduce —
    the oracle used against symbolic reduce ops)."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """Element-wise closeness, ignoring positions where either side is
    NaN."""
    a = np.copy(np.asarray(a))
    b = np.copy(np.asarray(b))
    nan_mask = np.logical_or(np.isnan(a), np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return almost_equal(a, b, rtol, atol)


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None,
                                   names=('a', 'b')):
    a = np.copy(np.asarray(a))
    b = np.copy(np.asarray(b))
    nan_mask = np.logical_or(np.isnan(a), np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    assert_almost_equal(a, b, rtol, atol, names)


def find_max_violation(a, b, rtol=None, atol=None):
    """Location and value of the maximum relative error."""
    a, b = np.asarray(a), np.asarray(b)
    rtol = get_rtol(a, b, rtol)
    atol = get_atol(a, b, atol)
    diff = np.abs(a - b)
    tol = atol + rtol * np.abs(b)
    violation = diff / (tol + 1e-20)
    loc = np.unravel_index(np.argmax(violation), violation.shape)
    return loc, float(violation[loc])


def same_array(array1, array2):
    """Whether two NDArrays share one memory block (reference
    test_utils.py:same_array — mutate-and-compare probe)."""
    array1[:] += 1
    if not same(array1.asnumpy(), array2.asnumpy()):
        array1[:] -= 1
        return False
    array1[:] -= 1
    return same(array1.asnumpy(), array2.asnumpy())


def random_arrays(*shapes):
    """One random fp32 ndarray per shape (scalars for ())."""
    arrays = [np.random.randn(*s).astype(np.float32)
              if len(s) else np.float32(np.random.randn()) for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


def random_sample(population, k):
    """k elements sampled without replacement, order randomized."""
    assert 0 <= k <= len(population)
    population_copy = population[:]
    np.random.shuffle(population_copy)
    return population_copy[0:k]


def retry(n):
    """Test decorator: retry flaky (randomized) tests up to n times."""
    assert n > 0

    def decorate(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError as e:
                    if i == n - 1:
                        raise e
        return wrapper
    return decorate


def discard_stderr():
    """Context manager silencing C-level stderr (reference
    test_utils.py:discard_stderr)."""
    import contextlib
    import os as _os

    @contextlib.contextmanager
    def _ctx():
        stderr_fileno = 2
        old_stderr = _os.dup(stderr_fileno)
        try:
            with open(_os.devnull, 'w') as bit_bucket:
                _os.dup2(bit_bucket.fileno(), stderr_fileno)
                yield
        finally:
            _os.dup2(old_stderr, stderr_fileno)
            _os.close(old_stderr)
    return _ctx()


def set_env_var(key, val, default_val=''):
    """Set an env var, returning the previous value."""
    import os as _os
    prev_val = _os.environ.get(key, default_val)
    _os.environ[key] = val
    return prev_val


def list_gpus():
    """Indices of visible accelerator devices (the reference shelled out
    to nvidia-smi; here: jax's non-cpu devices)."""
    import jax
    try:
        return [d.id for d in jax.devices() if d.platform != 'cpu']
    except RuntimeError:
        return []


def download(url, fname=None, dirname=None, overwrite=False):
    """Reference test_utils.py:download. This environment has no
    network egress: local file:// paths (or existing local files) are
    copied; anything else raises with that explanation."""
    import os as _os
    import shutil
    src = url[7:] if url.startswith('file://') else url
    if fname is None:
        fname = url.split('/')[-1]
    if dirname is not None:
        fname = _os.path.join(dirname, fname)
        _os.makedirs(dirname, exist_ok=True)
    if _os.path.exists(fname) and not overwrite:
        return fname
    if _os.path.exists(src):
        if _os.path.abspath(src) != _os.path.abspath(fname):
            shutil.copyfile(src, fname)
        return fname
    raise IOError('download(%r): no network egress in this environment; '
                  'place the file locally and pass its path' % url)


def get_mnist():
    """MNIST-format dict (train_data/label, test_data/label). Real idx
    files are used when present in ./data; otherwise the io tier's
    synthetic class-separable MNIST stands in (hermetic CI)."""
    from .io import MNISTIter
    out = {}
    for split, image, label, n in (
            ('train', 'data/train-images-idx3-ubyte',
             'data/train-labels-idx1-ubyte', 2048),
            ('test', 'data/t10k-images-idx3-ubyte',
             'data/t10k-labels-idx1-ubyte', 512)):
        it = MNISTIter(image=image, label=label, batch_size=n,
                       shuffle=False, flat=False)
        batch = next(iter(it))
        out['%s_data' % split] = batch.data[0].asnumpy()
        out['%s_label' % split] = batch.label[0].asnumpy()
    return out


def check_speed(sym, location=None, ctx=None, N=20, grad_req=None,
                typ='whole', **kwargs):
    """Time forward (typ='forward') or forward+backward (typ='whole')
    executions per second (reference test_utils.py:check_speed)."""
    import time as _time
    ctx = ctx or default_context()
    if grad_req is None:
        grad_req = 'write' if typ == 'whole' else 'null'
    if location is None:
        exe = sym.simple_bind(grad_req=grad_req, ctx=ctx, **kwargs)
        location = {k: np.random.normal(size=arr.shape, scale=1.0)
                    for k, arr in exe.arg_dict.items()}
    else:
        exe = sym.simple_bind(grad_req=grad_req, ctx=ctx,
                              **{k: v.shape for k, v in location.items()})
    for name, iarr in location.items():
        exe.arg_dict[name][:] = iarr.astype(exe.arg_dict[name].dtype)

    if typ == 'whole':
        def run():
            exe.forward(is_train=True)
            exe.backward(out_grads=exe.outputs)
    elif typ == 'forward':
        def run():
            exe.forward(is_train=False)
    else:
        raise ValueError('typ can only be "whole" or "forward"')
    def barrier():
        # fetch outputs AND grads: the final backward program is
        # enqueued after the forward output, so an output fetch alone
        # would leave one backward untimed
        exe.outputs[0].asnumpy()
        for g in (exe.grad_arrays or []):
            if g is not None:
                g.asnumpy()

    run()                      # warmup + compile
    barrier()
    tic = _time.time()
    for _ in range(N):
        run()
    barrier()
    return (_time.time() - tic) / N
