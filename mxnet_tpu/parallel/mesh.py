"""Device mesh — named topology every parallel strategy hangs off.

The reference enumerates flat device lists (``ctx=[mx.gpu(0)..]``,
module/executor_group.py decide_slices); on TPU the topology is a named
N-D mesh and the strategy is expressed per-axis. Axis-name conventions
used across this package:

- ``dp``: data parallel (batch dimension)
- ``tp``: tensor parallel (weight matrices split)
- ``pp``: pipeline parallel (layer stages)
- ``sp``: sequence/context parallel (ring attention)
- ``ep``: expert parallel (MoE)

Any subset may be present; missing axes just mean size 1.
"""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ['DeviceMesh', 'make_mesh', 'local_mesh']

AXIS_ORDER = ('pp', 'dp', 'ep', 'sp', 'tp')  # outer→inner: put tp on the
# fastest (innermost/ICI-nearest) axis, pp on the slowest — matches how
# XLA lays device ids out so tp collectives ride nearest-neighbour ICI.


class DeviceMesh:
    """A named mesh of devices plus helpers to build shardings on it.

    Thin, picklable-metadata wrapper over ``jax.sharding.Mesh``; all
    sharded compilation in this package goes through one of these.
    """

    def __init__(self, mesh):
        self.mesh = mesh

    @property
    def axis_names(self):
        return tuple(self.mesh.axis_names)

    @property
    def shape(self):
        return dict(self.mesh.shape)

    @property
    def size(self):
        return int(np.prod(list(self.mesh.shape.values()))) if self.mesh.shape else 1

    def axis_size(self, name):
        return int(self.mesh.shape.get(name, 1))

    def has_axis(self, name):
        return name in self.mesh.axis_names and self.axis_size(name) > 1

    def sharding(self, *spec):
        """NamedSharding from a PartitionSpec-style tuple.

        ``mesh.sharding('dp', None)`` shards dim0 on dp, replicates dim1."""
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self):
        return NamedSharding(self.mesh, PartitionSpec())

    def __enter__(self):
        self._cm = self.mesh
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

    def __repr__(self):
        return 'DeviceMesh(%s)' % (', '.join('%s=%d' % kv for kv in self.mesh.shape.items()))


def make_mesh(axes, devices=None):
    """Build a DeviceMesh from ``{'dp': 4, 'tp': 2}``-style axis sizes.

    Axes are laid out in AXIS_ORDER (pp outermost, tp innermost) so that
    the highest-bandwidth (most frequent) collectives map to adjacent
    devices. Total size must divide the device count; remaining devices
    are an error (be explicit about what you use).
    """
    # size-1 axes are kept: a topology-agnostic ShardingPlan naming 'tp'
    # must degrade to replicated on a tp=1 mesh, not crash on a missing axis
    if any(int(v) < 1 for v in axes.values()):
        raise ValueError('mesh axis sizes must be >= 1, got %s' % (axes,))
    axes = {k: int(v) for k, v in axes.items()} or {'dp': 1}
    names = tuple(sorted(axes, key=lambda n: AXIS_ORDER.index(n) if n in AXIS_ORDER else 99))
    sizes = tuple(axes[n] for n in names)
    total = int(np.prod(sizes))
    if devices is None:
        devices = jax.devices()
    if total > len(devices):
        raise ValueError('mesh %s needs %d devices, have %d' % (axes, total, len(devices)))
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return DeviceMesh(Mesh(dev_array, names))


def local_mesh(n=None, axis='dp'):
    """1-D mesh over the first n local devices (all by default)."""
    devices = jax.devices()
    if n is None:
        n = len(devices)
    return make_mesh({axis: n}, devices)
