"""SPMD train step — the TPU form of DataParallelExecutorGroup + KVStore.

In the reference one training step is: slice batch over devices
(executor_group.py:233 decide_slices), run per-device executors, push
grads to KVStore (reduce on merge GPU, comm.h:460), run the updater,
pull weights back (§3.3 call stack). Here ALL of that is one jitted XLA
computation over the mesh:

- the global batch is sharded on the ``dp`` (and ``sp``) axes,
- the loss is averaged over the *global* batch, so jax's autodiff
  emits the gradient ``psum`` exactly where the KVStore push was —
  compiled into the step, overlapped with backprop by XLA's scheduler
  (the reference got this overlap from engine priorities,
  kvstore.py:139 ``priority=-index``),
- the optimizer update runs sharded in the same computation
  ("update_on_kvstore" fused, SURVEY.md §7 step 6),
- parameter buffers are donated, so weights are updated in place in
  device memory (the reference's kWriteInplace).
"""
import numpy as np

import jax
import jax.numpy as jnp

from .mesh import DeviceMesh
from .sharding import ShardingPlan, data_parallel_plan, shard_params

__all__ = ['make_train_step', 'ShardedTrainer', 'sgd_rule', 'adam_rule']


# ---------------------------------------------------------------------------
# Functional optimizer rules: (param, grad, state, step) -> (param, state).
# Pure-jnp counterparts of the fused update ops (ops/optimizer_ops.py,
# reference src/operator/optimizer_op.cc) usable inside one jitted step.
# ---------------------------------------------------------------------------

def sgd_rule(lr=0.01, momentum=0.0, wd=0.0):
    def init(param):
        return jnp.zeros_like(param) if momentum else ()

    def update(param, grad, state, step):
        grad = grad + wd * param
        if momentum:
            state = momentum * state - lr * grad
            return param + state, state
        return param - lr * grad, state
    return init, update


def adam_rule(lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0):
    def init(param):
        return (jnp.zeros_like(param), jnp.zeros_like(param))

    def update(param, grad, state, step):
        grad = grad + wd * param
        m, v = state
        m = beta1 * m + (1 - beta1) * grad
        v = beta2 * v + (1 - beta2) * jnp.square(grad)
        t = step + 1
        mhat = m / (1 - beta1 ** t)
        vhat = v / (1 - beta2 ** t)
        return param - lr * mhat / (jnp.sqrt(vhat) + epsilon), (m, v)
    return init, update


def make_train_step(loss_fn, mesh, optimizer=None, plan=None,
                    batch_spec=('dp',), donate=True, shard_updates=None):
    """Compile ``loss_fn`` into a sharded step over the mesh.

    loss_fn(params, batch, key) -> scalar loss (mean over the batch), or
    (loss, aux) pytree. Returns (init_state, step) where
    step(state, batch, key) -> (state, loss[, aux]) runs as ONE XLA
    computation with grads synced by construction.

    ``shard_updates=True`` shards the optimizer states (and therefore
    the weight-update computation) over the ``dp`` axis — the
    cross-replica weight-update sharding of arXiv:2004.13336 (ZeRO-2
    style): GSPMD turns the gradient psum into a reduce-scatter, each
    replica updates only its 1/dp slice, and the fresh params
    all-gather back. Optimizer memory per device drops by ~dp×.
    Default (None) follows MXTPU_SHARDED_UPDATE — the same switch that
    governs the production fused-fit window (module/fused_fit.py),
    which additionally flat-pads every leaf so non-dividing shapes
    shard too; this functional prototype shards only leaves with a
    dp-divisible free dimension.
    """
    if shard_updates is None:
        from ..config import flags
        flags.reload('MXTPU_SHARDED_UPDATE')
        shard_updates = bool(flags.get('MXTPU_SHARDED_UPDATE'))
    plan = plan or data_parallel_plan()
    opt_init, opt_update = optimizer if optimizer is not None else sgd_rule()

    has_aux = getattr(loss_fn, 'has_aux', False)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)

    batch_sharding = mesh.sharding(*batch_spec)
    repl = mesh.replicated()
    dp = mesh.axis_size('dp')
    shard_updates = shard_updates and dp > 1

    def _param_spec(k, v):
        return tuple(plan.spec_for(k, getattr(v, 'shape', None), mesh))

    def _opt_sharding(k, v):
        """dp-shard a state tensor along its first divisible dim that
        the plan leaves free, keeping the plan's axes (so tp-sharded
        params keep tp-sharded states and only a free dim picks up
        dp)."""
        if not hasattr(v, 'shape'):
            return repl
        base = list(_param_spec(k, v))
        base += [None] * (getattr(v, 'ndim', 0) - len(base))
        for d in range(getattr(v, 'ndim', 0)):
            if base[d] is None and v.shape[d] and v.shape[d] % dp == 0:
                base[d] = 'dp'
                return mesh.sharding(*base)
        return mesh.sharding(*base) if any(base) else repl

    def _constrain(states, sharding_of):
        return {k: jax.tree_util.tree_map(
                    lambda v: jax.lax.with_sharding_constraint(
                        v, sharding_of(k, v)), sub)
                for k, sub in states.items()}

    def init_state(params):
        params = shard_params(params, mesh, plan)
        opt_states = {k: opt_init(v) for k, v in params.items()}
        if shard_updates:
            opt_states = {k: jax.tree_util.tree_map(
                              lambda v: jax.device_put(
                                  v, _opt_sharding(k, v)), sub)
                          for k, sub in opt_states.items()}
        return {'params': params, 'opt': opt_states,
                'step': jnp.zeros((), jnp.int32)}

    def step(state, batch, key):
        out, grads = grad_fn(state['params'], batch, key)
        loss, aux = out if has_aux else (out, None)
        t = state['step']
        new_params, new_opt = {}, {}
        for k, p in state['params'].items():
            new_params[k], new_opt[k] = opt_update(p, grads[k], state['opt'][k], t)
        if shard_updates:
            new_opt = _constrain(new_opt, _opt_sharding)
            # pin fresh params back to the plan's layout (the ZeRO-2
            # all-gather); otherwise GSPMD could propagate the dp
            # sharding into state['params'] and recompile on step 2
            new_params = {
                k: jax.lax.with_sharding_constraint(
                    v, mesh.sharding(*_param_spec(k, v)))
                for k, v in new_params.items()}
        new_state = {'params': new_params, 'opt': new_opt, 'step': t + 1}
        return (new_state, loss, aux) if has_aux else (new_state, loss)

    jstep = jax.jit(step,
                    in_shardings=(None, batch_sharding, repl),
                    donate_argnums=(0,) if donate else ())
    return init_state, jstep


class ShardedTrainer:
    """Mesh-wide trainer: the gluon.Trainer / Module.fit step on SPMD.

    >>> trainer = ShardedTrainer(loss_fn, params, mesh, adam_rule(1e-3))
    >>> loss = trainer.step(batch)          # one fused XLA computation
    """

    def __init__(self, loss_fn, params, mesh, optimizer=None, plan=None,
                 batch_spec=('dp',), seed=0):
        if not isinstance(mesh, DeviceMesh):
            mesh = DeviceMesh(mesh)
        self.mesh = mesh
        self._init, self._step = make_train_step(
            loss_fn, mesh, optimizer=optimizer, plan=plan,
            batch_spec=batch_spec)
        self.state = self._init(params)
        self._key = jax.random.PRNGKey(seed)
        self._has_aux = getattr(loss_fn, 'has_aux', False)

    def step(self, batch):
        self._key, sub = jax.random.split(self._key)
        out = self._step(self.state, batch, sub)
        if self._has_aux:
            self.state, loss, aux = out
            return loss, aux
        self.state, loss = out
        return loss

    @property
    def params(self):
        return self.state['params']
