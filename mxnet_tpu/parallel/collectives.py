"""Collectives — the TPU replacement for the reference's Comm/ps-lite tiers.

Reference mapping (SURVEY.md §5.8):
- ``CommDevice::Reduce`` + ``Broadcast`` (src/kvstore/comm.h:460-540,
  reduce-to-one-GPU then copy back)  →  :func:`allreduce` = ``lax.psum``,
  compiled by XLA into a ring/tree over ICI.
- ``KVStoreDist`` ZPush/ZPull striping over servers
  (src/kvstore/kvstore_dist.h:430-468)  →  :func:`reduce_scatter` +
  :func:`allgather` (the two halves of a sharded allreduce).
- There is no analog of ``ppermute`` in the reference — it is the TPU
  primitive behind ring attention and pipeline transfer.

All functions must be called inside a mesh-axis context (shard_map /
pjit with named axes); ``axis`` is the mesh axis name.
"""
import jax
from jax import lax

__all__ = ['allreduce', 'allgather', 'reduce_scatter', 'ring_permute',
           'alltoall', 'axis_index', 'axis_size', 'pbroadcast']


def allreduce(x, axis, op='sum'):
    """Allreduce over a mesh axis. op in {sum, mean, max, min}."""
    if op == 'sum':
        return lax.psum(x, axis)
    if op == 'mean':
        return lax.pmean(x, axis)
    if op == 'max':
        return lax.pmax(x, axis)
    if op == 'min':
        return lax.pmin(x, axis)
    raise ValueError('unknown reduce op %r' % (op,))


def allgather(x, axis, concat_dim=0, tiled=True):
    """Gather shards from every device along `axis`, concatenated on
    ``concat_dim`` (tiled=True) or stacked on a new leading dim."""
    return lax.all_gather(x, axis, axis=concat_dim, tiled=tiled)


def reduce_scatter(x, axis, scatter_dim=0):
    """Sum over the axis, leaving each device its own shard — the
    bandwidth-optimal half of an allreduce (allreduce = reduce_scatter
    + allgather). Grad sync for sharded optimizers (ZeRO-style)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def ring_permute(x, axis, shift=1):
    """Send this device's value to its neighbour `shift` steps around the
    ring; receive from the opposite neighbour. The transport under ring
    attention and pipeline stage hand-off."""
    n = lax.psum(1, axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def alltoall(x, axis, split_dim, concat_dim):
    """Transpose data across the axis: split `split_dim` n ways, exchange,
    concat on `concat_dim`. The Ulysses attention primitive (heads↔seq)."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def pbroadcast(x, axis, src=0):
    """Broadcast from `src` device along the axis (select + psum)."""
    idx = lax.axis_index(axis)
    masked = jax.tree_util.tree_map(
        lambda v: jax.numpy.where(idx == src, v, jax.numpy.zeros_like(v)), x)
    return jax.tree_util.tree_map(lambda v: lax.psum(v, axis), masked)


def axis_index(axis):
    """This device's coordinate along the mesh axis (≙ kvstore rank)."""
    return lax.axis_index(axis)


def axis_size(axis):
    """Number of devices along the mesh axis (≙ kvstore num_workers)."""
    return lax.psum(1, axis)
