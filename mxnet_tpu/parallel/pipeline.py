"""Pipeline parallelism — a real micro-batch schedule over the ``pp`` axis.

The reference only has *manual layer placement* (AttrScope(ctx_group) +
group2ctx, symbol.py:1250; example/model-parallel-lstm) — devices idle
while their stage is inactive, and overlap is whatever the async engine
happens to find. This module implements an explicit GPipe-style schedule
as ONE compiled computation: every device runs the same scanned program
(SPMD), activations hop stages via ``lax.ppermute``, and the bubble is
the schedule's (stages-1)/(microbatches+stages-1) — not luck.

Layout contract: each stage's parameters are stacked on a leading
``n_stages`` dim and sharded over ``pp``; micro-batches are a leading
``n_micro`` dim, replicated.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from ._compat import shard_map

__all__ = ['PipelineStage', 'pipeline_apply', 'stack_stage_params']


class PipelineStage:
    """A (fn, params) pair; helper for building homogeneous stage stacks."""

    def __init__(self, fn, params):
        self.fn = fn
        self.params = params


def stack_stage_params(stage_params_list):
    """[{name: arr}, ...] per stage → {name: arr[n_stages, ...]} stacked.

    All stages must share one parameter structure (homogeneous pipeline —
    the transformer-block case)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params_list)


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, axis='pp'):
    """Run the GPipe schedule; returns outputs [n_micro, ...].

    stage_fn(params, x) -> y with y.shape == x.shape (homogeneous
    stages). ``microbatches``: [n_micro, micro_batch, ...]. One
    shard_map + lax.scan; n_micro + n_stages - 1 ticks.
    """
    n_micro = microbatches.shape[0]
    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)

    @functools.partial(
        shard_map, mesh=mesh.mesh,
        in_specs=(pspec, P()), out_specs=P(),
        check_vma=False)
    def run(params, mbs):
        params = jax.tree_util.tree_map(lambda a: a[0], params)  # [1,...]→[...]
        n = lax.psum(1, axis)
        idx = lax.axis_index(axis)
        steps = n_micro + n - 1
        fwd = [(i, i + 1) for i in range(n - 1)]      # stage i → i+1

        x_shape = mbs.shape[1:]

        def body(carry, t):
            buf_in, outs = carry
            # stage 0 injects microbatch t (clamped; masked out when t ≥ n_micro)
            feed = lax.dynamic_index_in_dim(mbs, jnp.minimum(t, n_micro - 1),
                                            axis=0, keepdims=False)
            x = jnp.where(idx == 0, feed, buf_in)
            y = stage_fn(params, x)
            # the tick at which the LAST stage finishes microbatch m is
            # t = m + n - 1 → write slot t-(n-1) when we are that stage
            slot = jnp.clip(t - (n - 1), 0, n_micro - 1)
            valid = (idx == n - 1) & (t >= n - 1)
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(valid, y, lax.dynamic_index_in_dim(outs, slot, 0, keepdims=False)),
                slot, 0)
            buf_next = lax.ppermute(y, axis, fwd)     # non-receivers get 0
            return (buf_next, outs), None

        init = (jnp.zeros(x_shape, mbs.dtype),
                jnp.zeros((n_micro,) + x_shape, mbs.dtype))
        (_, outs), _ = lax.scan(body, init, jnp.arange(steps))
        # only the last stage holds real outputs; share them with every
        # device so out_specs can be replicated
        outs = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
        return lax.psum(outs, axis)

    return run(stacked_params, microbatches)
