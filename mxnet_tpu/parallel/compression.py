"""Quantized gradient collectives with error feedback.

Block-wise int8 (and bf16) quantization for gradient traffic, following
the EQuARX recipe (arxiv 2506.17615): per-block scales (``amax/127``),
round-to-nearest with clipping, and a persistent error-feedback residual
so the quantization error of step *t* is re-injected at step *t+1*
instead of being lost. Convergence is gated, not assumed — the chaos
lane trains int8-with-error-feedback against fp32 same-seed and
``tools/run_compare.py`` must exit 0.

Three consumers, two kinds of honesty about bytes:

* The fused window (ZeRO update path) applies quantize→dequantize with
  error feedback to the flat, dp-sharded gradient *inside* the jitted
  program. The partitioner still moves the reduced values itself, so
  the published ``comm.bytes_on_wire_per_step`` gauge there is a wire
  *model* (``comm.bytes_src = 'modeled'``) — the numerics change is
  real, the byte count is arithmetic.
* ``kvstore_dist`` push/pull sends genuinely compressed payloads over
  TCP (``comm.bytes_src = 'measured'``), version-tagged so a mixed
  old/new gang fails loudly on the first push instead of silently
  misparsing.
* ``compressed_psum`` is the honest collective form for shard_map
  contexts: all-gather the int8 payload + scales, dequantize and sum
  locally.

Mode resolution: ``MXTPU_GRAD_COMPRESS={off,int8,bf16,auto}``. In
``auto`` the run starts uncompressed; when a cluster sync round
classifies the run ``communication_bound`` (telemetry.cluster), every
host flips to int8 deterministically (the verdict is computed from the
identical gathered matrix on all hosts — no extra collective). The
resolved mode is part of the fused-window build signature, so the flip
rebuilds the window program at the next dispatch and the loop emits a
one-shot ``{'type': 'compression'}`` JSONL record with the before/after
step-time delta.
"""
import logging
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

logger = logging.getLogger(__name__)

__all__ = ['MODES', 'WIRE_VERSION', 'quantize', 'dequantize',
           'ef_roundtrip', 'compressed_psum', 'wire_bytes',
           'compression_ratio', 'resolved_mode', 'note_round_verdict',
           'publish_gauges', 'encode_wire', 'decode_wire']

MODES = ('off', 'int8', 'bf16', 'auto')

# Bump when the push_c/pull_c payload layout changes. decode_wire
# refuses other versions, and an old server answers the unknown
# message kind with an ('error', ...) reply — either way a mixed gang
# dies on the first compressed push, never silently misparses.
WIRE_VERSION = 1

_INT8_MAX = 127.0


def _flag_mode():
    from ..config import flags
    flags.reload('MXTPU_GRAD_COMPRESS')
    return flags.get('MXTPU_GRAD_COMPRESS')


def block_size():
    from ..config import flags
    flags.reload('MXTPU_GRAD_COMPRESS_BLOCK')
    return int(flags.get('MXTPU_GRAD_COMPRESS_BLOCK'))


# ---------------------------------------------------------------------------
# quantize / dequantize (jnp; works on tracers and concrete arrays)
# ---------------------------------------------------------------------------

def _blockify(x, block):
    """1-D ``x`` -> (nblocks, block), zero-padded at the tail."""
    n = x.shape[0]
    pad = (-n) % block
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    return x.reshape(-1, block)


def quantize(x, mode, block=None):
    """Quantize a 1-D array. Returns ``(payload, scales)``.

    int8: payload is int8 of the zero-padded length, scales is one
    float32 per block (``amax/127``; 1.0 for all-zero blocks so the
    dequant is exact-zero rather than 0/0). bf16: payload is the bf16
    cast, scales is None. Non-finite inputs are NOT laundered: a
    NaN/Inf anywhere in a block makes the block's scale non-finite, and
    dequantize pins the whole block to NaN so the health sentinel trips
    exactly as it would on the raw gradient.
    """
    if mode == 'bf16':
        return x.astype(jnp.bfloat16), None
    if mode != 'int8':
        raise ValueError('quantize: bad mode %r' % (mode,))
    block = block_size() if block is None else int(block)
    xb = _blockify(x.astype(jnp.float32), block)
    amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    # NaN compares False against 0, so a plain where would hand a NaN
    # block the all-zero scale of 1.0 and launder the NaN into q=0;
    # propagate non-finite amax into the scale so dequantize pins the
    # block to NaN instead.
    safe = jnp.where(amax > 0, amax / _INT8_MAX, jnp.ones_like(amax))
    scales = jnp.where(jnp.isfinite(amax), safe, amax)
    q = jnp.clip(jnp.round(xb / scales), -_INT8_MAX, _INT8_MAX)
    return q.astype(jnp.int8).reshape(-1), scales.reshape(-1)


def dequantize(payload, scales, length, dtype, mode, block=None):
    """Inverse of :func:`quantize`; returns a 1-D array of ``length``."""
    if mode == 'bf16':
        return payload.astype(dtype)[:length]
    if mode != 'int8':
        raise ValueError('dequantize: bad mode %r' % (mode,))
    block = block_size() if block is None else int(block)
    qb = payload.reshape(-1, block).astype(jnp.float32)
    sb = scales.reshape(-1, 1)
    deq = qb * sb
    # 0 * inf == nan covers Inf blocks implicitly, but pin the whole
    # block deterministically so a poisoned gradient never round-trips
    # to something finite.
    bad = ~jnp.isfinite(sb)
    deq = jnp.where(bad, jnp.full_like(deq, jnp.nan), deq)
    return deq.reshape(-1)[:length].astype(dtype)


def ef_roundtrip(x, resid, mode, block=None):
    """Error-feedback quantize→dequantize of a 1-D gradient.

    ``carry = x + resid`` is quantized; the new residual is what the
    quantizer dropped (``carry - dequant``). Returns ``(xq, new_resid)``
    in ``x.dtype``. The residual is sanitized to zero where non-finite
    so a single NaN step (which the health sentinel halts on anyway via
    ``xq``) cannot poison the carried state forever.
    """
    n = x.shape[0]
    carry = x + resid.astype(x.dtype)
    payload, scales = quantize(carry, mode, block)
    xq = dequantize(payload, scales, n, x.dtype, mode, block)
    new_resid = carry - xq
    new_resid = jnp.where(jnp.isfinite(new_resid), new_resid,
                          jnp.zeros_like(new_resid))
    return xq, new_resid


def compressed_psum(x, axis_name, mode=None, block=None):
    """psum over ``axis_name`` with quantized traffic (shard_map body).

    Each participant quantizes its contribution, the int8 payload (+
    per-block scales) is all-gathered, and every participant
    dequantizes and sums locally — the large tensor crosses the wire at
    int8/bf16 width. ``mode`` defaults to the resolved flag mode; 'off'
    falls back to a plain ``lax.psum``.
    """
    mode = resolved_mode() if mode is None else mode
    if mode == 'off':
        return lax.psum(x, axis_name)
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    n = flat.shape[0]
    payload, scales = quantize(flat, mode, block)
    pg = lax.all_gather(payload, axis_name)
    if scales is None:
        total = jnp.sum(pg.astype(jnp.float32), axis=0)[:n]
    else:
        sg = lax.all_gather(scales, axis_name)
        deq = jax.vmap(
            lambda p, s: dequantize(p, s, n, jnp.float32, mode, block)
        )(pg, sg)
        total = jnp.sum(deq, axis=0)
    return total.astype(dtype).reshape(shape)


# ---------------------------------------------------------------------------
# wire-byte model
# ---------------------------------------------------------------------------

def wire_bytes(n_elems, mode, block=None, itemsize=4):
    """Bytes a length-``n_elems`` gradient occupies on the wire."""
    n = int(n_elems)
    if mode == 'off':
        return n * itemsize
    if mode == 'bf16':
        return n * 2
    if mode == 'int8':
        block = block_size() if block is None else int(block)
        return n + -(-n // block) * 4          # payload + fp32 scales
    raise ValueError('wire_bytes: bad mode %r' % (mode,))


def compression_ratio(n_elems, mode, block=None, itemsize=4):
    """uncompressed/compressed byte ratio (>= 1.0; 1.0 when off)."""
    if n_elems <= 0:
        return 1.0
    return (wire_bytes(n_elems, 'off', block, itemsize)
            / float(wire_bytes(n_elems, mode, block, itemsize)))


# ---------------------------------------------------------------------------
# mode resolution + the auto trigger
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_auto_engaged = False
_warned = set()


def _warn_once(key, msg, *args):
    with _lock:
        if key in _warned:
            return
        _warned.add(key)
    logger.warning(msg, *args)


def resolved_mode():
    """The mode the next window build should use: off/int8/bf16.

    'auto' resolves to 'off' until a cluster sync round has classified
    the run communication_bound, then to 'int8' for the rest of the
    run. Part of the fused-window build signature, so a flip rebuilds
    the program at the next dispatch.
    """
    mode = _flag_mode()
    if mode == 'auto':
        return 'int8' if _auto_engaged else 'off'
    return mode


def auto_engaged():
    return _auto_engaged


def note_round_verdict(verdict):
    """Called from telemetry.cluster.sync_now on every host.

    Every host sees the identical gathered matrix, so the flip decision
    is deterministic across the gang without an extra collective.
    """
    global _auto_engaged
    if _flag_mode() != 'auto' or _auto_engaged:
        return
    if verdict == 'communication_bound':
        _auto_engaged = True
        _warn_once('auto-flip',
                   'MXTPU_GRAD_COMPRESS=auto: cluster round classified '
                   'the run communication_bound; engaging int8 gradient '
                   'quantization (window program rebuilds at next '
                   'dispatch)')


def publish_gauges(n_elems, mode, src, block=None, itemsize=4):
    """Publish the comm.* gauges bench banks and bench_diff gates.

    ``src`` is the provenance: 'measured' (real bytes counted on the
    kvstore TCP wire) or 'modeled' (wire_bytes arithmetic for the
    SPMD window, where the partitioner moves the data itself).
    """
    import mxnet_tpu.telemetry as _tele
    if not _tele.enabled():
        return
    bts = wire_bytes(n_elems, mode, block, itemsize)
    _tele.gauge('comm.bytes_on_wire_per_step').set(int(bts))
    _tele.gauge('comm.compression_ratio').set(
        round(compression_ratio(n_elems, mode, block, itemsize), 3))
    _tele.gauge('comm.mode').set(mode)
    _tele.gauge('comm.bytes_src').set(src)


def emit_record(**fields):
    """Append a {'type': 'compression'} JSONL record (one per flip)."""
    import mxnet_tpu.telemetry as _tele
    st = _tele._state
    if not _tele.enabled() or st.sink is None:
        return
    rec = {'type': 'compression'}
    rec.update(fields)
    st.sink.emit(rec)


# ---------------------------------------------------------------------------
# kvstore wire codec (numpy, host-side)
# ---------------------------------------------------------------------------

def encode_wire(arr, mode, block=None):
    """Encode a 1-D numpy float array for the push_c/pull_c messages.

    Returns a picklable tuple
    ``(WIRE_VERSION, mode, block, length, dtype_str, payload, scales)``
    with payload/scales as raw bytes. The version field is checked by
    decode_wire; an old server never gets this far — it rejects the
    unknown 'push_c' message kind outright.
    """
    arr = np.ascontiguousarray(arr).reshape(-1)
    n = arr.shape[0]
    block = block_size() if block is None else int(block)
    if mode == 'bf16':
        payload = np.asarray(jnp.asarray(arr).astype(jnp.bfloat16))
        return (WIRE_VERSION, mode, block, n, arr.dtype.str,
                payload.tobytes(), b'')
    if mode != 'int8':
        raise ValueError('encode_wire: bad mode %r' % (mode,))
    x = arr.astype(np.float32)
    pad = (-n) % block
    if pad:
        x = np.concatenate([x, np.zeros((pad,), np.float32)])
    xb = x.reshape(-1, block)
    with np.errstate(invalid='ignore', divide='ignore'):
        amax = np.max(np.abs(xb), axis=1, keepdims=True)
        safe = np.where(amax > 0, amax / _INT8_MAX, np.ones_like(amax))
        # keep non-finite amax in the scale (NaN > 0 is False and would
        # otherwise pick the all-zero scale, laundering the NaN)
        scales = np.where(np.isfinite(amax), safe, amax).astype(np.float32)
        q = np.clip(np.round(xb / scales), -_INT8_MAX, _INT8_MAX)
        q = np.where(np.isfinite(q), q, 0.0)
    # the zero-pad tail quantizes to exact zeros — trim it so measured
    # bytes match the wire model (decode re-pads)
    payload = q.astype(np.int8).reshape(-1)[:n]
    return (WIRE_VERSION, mode, block, n, arr.dtype.str,
            payload.tobytes(), scales.tobytes())


def decode_wire(msg):
    """Inverse of :func:`encode_wire`; raises on version/mode skew."""
    version, mode, block, n, dtype_str, payload, scales = msg
    if version != WIRE_VERSION:
        raise RuntimeError(
            'compressed kvstore wire version mismatch: peer sent v%s, '
            'this build speaks v%s — mixed old/new gang, refusing to '
            'guess at the payload layout' % (version, WIRE_VERSION))
    if mode == 'bf16':
        flat = np.frombuffer(payload, dtype=jnp.bfloat16)[:n]
        return np.asarray(flat, dtype=np.dtype(dtype_str))
    if mode != 'int8':
        raise RuntimeError('compressed kvstore wire: unknown mode %r'
                           % (mode,))
    q = np.frombuffer(payload, dtype=np.int8).astype(np.float32)
    pad = (-q.size) % block
    if pad:
        q = np.concatenate([q, np.zeros((pad,), np.float32)])
    sb = np.frombuffer(scales, dtype=np.float32).reshape(-1, 1)
    deq = q.reshape(-1, block) * sb
    bad = ~np.isfinite(sb)
    if bad.any():
        deq = np.where(bad, np.nan, deq)
    return deq.reshape(-1)[:n].astype(np.dtype(dtype_str))


def wire_message_bytes(msg):
    """Actual payload bytes in an encoded wire tuple (measured side)."""
    return len(msg[5]) + len(msg[6])


def _reset_for_tests():
    global _auto_engaged
    with _lock:
        _warned.clear()
    _auto_engaged = False
