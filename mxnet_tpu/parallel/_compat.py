"""jax version-compatibility shims for the parallel package.

The container's jax (0.4.x line) exposes ``shard_map`` under
``jax.experimental.shard_map`` with a ``check_rep`` kwarg; newer jax
moved it to the top level and renamed the kwarg ``check_vma``. Code in
this package (and the parallel examples/tests) writes the new spelling
and imports ``shard_map`` from here, which translates as needed.
"""
import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:     # pre-0.6 jax exposes it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = ['shard_map']

_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        try:
            _PARAMS = frozenset(inspect.signature(_shard_map).parameters)
        except (TypeError, ValueError):   # C-accelerated/odd signature
            _PARAMS = frozenset()
    return _PARAMS


def shard_map(f, *args, **kwargs):
    # The old (experimental) shard_map spells the flag check_rep. Known
    # residue on 0.4.37: its check_rep=False transpose mis-specs scalar
    # cotangents, so the 5-D pipeline loss (five_d.py) still needs a
    # newer jax — but ring attention, the GPipe schedule, and the
    # collectives tests all run correctly under this translation.
    if 'check_vma' in kwargs and 'check_vma' not in _params() \
            and 'check_rep' in _params():
        kwargs['check_rep'] = kwargs.pop('check_vma')
    return _shard_map(f, *args, **kwargs)
