"""5-axis parallel transformer training step — dp/tp/pp/sp/ep in ONE program.

The reference's parallelism inventory (SURVEY.md §2.3) stops at data
parallelism (module/executor_group.py decide_slices + kvstore reduce)
and manual layer placement (AttrScope(ctx_group), symbol.py group2ctx).
This module is the TPU-native superset: a decoder-only transformer LM
whose full training step — forward, GPipe pipeline schedule, ring
attention, Megatron tensor-parallel matmuls, expert-parallel MoE,
backward, gradient sync, SGD update — compiles to ONE XLA computation
over a named 5-axis mesh:

- ``dp``: batch sharded; grad psum inserted by the shard_map transpose.
- ``tp``: attention heads + MoE hidden dim sharded (column-parallel
  w_up / row-parallel w_down with a single psum, Megatron-style).
- ``pp``: layers stacked on a leading stage dim; GPipe micro-batch
  schedule via lax.scan + lax.ppermute stage hand-off (pipeline.py).
- ``sp``: sequence sharded; ring attention streams K/V chunks around
  the ring with ppermute (ring_attention.py).
- ``ep``: experts sharded; every shard evaluates its local experts on
  all tokens (dense dispatch), combined with one psum over ``ep``.

Any axis may have size 1 — the same program degrades gracefully, so one
code path covers 1 chip through a v5e-64 pod. This file is also what
``__graft_entry__.dryrun_multichip`` compiles.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ._compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DeviceMesh
from .ring_attention import ring_attention

__all__ = ['TransformerConfig', 'param_specs', 'init_params',
           'make_loss_fn', 'make_5d_train_step']


class TransformerConfig:
    """Tiny bag of hyperparameters for the 5-axis LM.

    Divisibility contract (checked in init_params): n_heads and ffn by
    the tp axis, experts by ep, vocab/d_model free.
    """

    def __init__(self, vocab=256, d_model=64, n_heads=4, head_dim=None,
                 ffn=128, experts=2, n_layers=2, dtype=jnp.float32):
        self.vocab = vocab
        self.d_model = d_model
        self.n_heads = n_heads
        self.head_dim = head_dim or d_model // n_heads
        self.ffn = ffn
        self.experts = experts
        self.n_layers = n_layers
        self.dtype = dtype


def param_specs(cfg=None):
    """PartitionSpec per parameter. Layer-stacked tensors lead with a
    [n_layers] dim sharded over pp — each stage owns n_layers/pp blocks."""
    return {
        'embed':  P(),                              # [V, D]
        'ln1':    P('pp', None),                    # [L, D]
        'ln2':    P('pp', None),                    # [L, D]
        'wqkv':   P('pp', None, None, 'tp', None),  # [L, D, 3, H, Dh]
        'wo':     P('pp', 'tp', None, None),        # [L, H, Dh, D]
        'gate':   P('pp', None, None),              # [L, D, E] (replicated/ep)
        'w_up':   P('pp', 'ep', None, 'tp'),        # [L, E, D, F]
        'w_down': P('pp', 'ep', 'tp', None),        # [L, E, F, D]
        'head':   P(),                              # [D, V]
    }


def param_shapes(cfg):
    """Shape per parameter (single source of truth with init_params)."""
    D, H, Dh = cfg.d_model, cfg.n_heads, cfg.head_dim
    F, E, V, L = cfg.ffn, cfg.experts, cfg.vocab, cfg.n_layers
    return {
        'embed':  (V, D),
        'ln1':    (L, D),
        'ln2':    (L, D),
        'wqkv':   (L, D, 3, H, Dh),
        'wo':     (L, H, Dh, D),
        'gate':   (L, D, E),
        'w_up':   (L, E, D, F),
        'w_down': (L, E, F, D),
        'head':   (D, V),
    }


def _zero_spec(spec, shape, dp):
    """ZeRO layout for optimizer state / weight update over the dp axis
    (arXiv:2004.13336): place 'dp' on the first spec-free dim it
    divides, so each replica owns 1/dp of the momentum and update math.
    The grad all-reduce + shard slice is the form XLA's TPU
    reduce-scatter-creation rewrites into one reduce-scatter; on
    backends without that pass the program carries the all-reduce plus
    a param all-gather (memory/compute win intact, comm neutral at
    best). No free dividing dim (or dp=1) → unchanged."""
    if dp <= 1:
        return spec
    s = list(tuple(spec)) + [None] * (len(shape) - len(tuple(spec)))
    for i, ax in enumerate(s):
        if ax is None and shape[i] % dp == 0:
            s[i] = 'dp'
            return P(*s)
    return spec


AXES = ('pp', 'dp', 'ep', 'sp', 'tp')


def full_mesh(axes=None, devices=None):
    """A mesh naming all five axes; unspecified ones get size 1 (the same
    program then runs anywhere from 1 chip to a pod)."""
    from .mesh import make_mesh
    axes = dict(axes or {})
    for ax in AXES:
        axes.setdefault(ax, 1)
    return make_mesh(axes, devices)


def _check_mesh(mesh):
    missing = [ax for ax in AXES if ax not in mesh.axis_names]
    if missing:
        raise ValueError(
            'five_d needs all of %s on the mesh (size 1 is fine; use '
            'full_mesh()); missing %s' % (AXES, missing))


def init_params(cfg, mesh, seed=0):
    """Host-init then device_put onto the mesh per param_specs."""
    _check_mesh(mesh)
    S = mesh.axis_size('pp')
    tp, ep = mesh.axis_size('tp'), mesh.axis_size('ep')
    if cfg.n_heads % tp or cfg.ffn % tp:
        raise ValueError('tp=%d must divide n_heads and ffn' % tp)
    if cfg.experts % ep:
        raise ValueError('ep=%d must divide experts' % ep)
    if cfg.n_layers % S:
        raise ValueError('pp=%d must divide n_layers' % S)
    rng = np.random.RandomState(seed)
    D, H, Dh, F = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.ffn

    def mk(shape, scale):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    shapes = param_shapes(cfg)
    scales = {'embed': 0.02, 'wqkv': D ** -0.5, 'wo': (H * Dh) ** -0.5,
              'gate': D ** -0.5, 'w_up': D ** -0.5, 'w_down': F ** -0.5,
              'head': D ** -0.5}
    host = {k: (np.ones(shapes[k], np.float32) if k in ('ln1', 'ln2')
                else mk(shapes[k], scales[k])) for k in shapes}
    specs = param_specs(cfg)
    return {k: jax.device_put(v.astype(cfg.dtype),
                              NamedSharding(mesh.mesh, specs[k]))
            for k, v in host.items()}


def make_loss_fn(cfg, mesh):
    """shard_map'ed loss(params, tokens, targets) -> scalar mean CE.

    tokens/targets: int32 [n_micro, batch, seq], batch sharded dp, seq
    sharded sp, micro-batch dim replicated (it is the pipeline schedule).
    Differentiable from outside; the shard_map transpose plants the dp/sp
    grad psums exactly where the reference pushed grads to the KVStore
    (§3.3) — compiled, overlapped collectives instead.
    """
    _check_mesh(mesh)
    specs = param_specs(cfg)
    data_spec = P(None, 'dp', 'sp')

    @functools.partial(shard_map, mesh=mesh.mesh,
                       in_specs=(specs, data_spec, data_spec),
                       out_specs=P(), check_vma=False)
    def loss_fn(params, tokens, targets):
        S = lax.psum(1, 'pp')               # static axis sizes
        dp = lax.psum(1, 'dp')
        sp = lax.psum(1, 'sp')
        stage = lax.axis_index('pp')
        ep_rank = lax.axis_index('ep')
        n_micro, b, t = tokens.shape
        embed, head = params['embed'], params['head']
        # local layer stack: leading [n_layers/pp] slice per stage
        stk = {k: v for k, v in params.items()
               if k not in ('embed', 'head')}
        L_local = stk['ln1'].shape[0]
        E_local = stk['w_up'].shape[1]

        def rms(x, g):
            return x * lax.rsqrt(
                jnp.mean(jnp.square(x), -1, keepdims=True) + 1e-6) * g

        def block(x, stg):                   # x: [b, t_local, D]
            h = rms(x, stg['ln1'])
            qkv = jnp.einsum('btd,dchk->cbthk', h, stg['wqkv'])
            att = ring_attention(qkv[0], qkv[1], qkv[2],
                                 axis='sp', causal=True)
            o = jnp.einsum('bthk,hkd->btd', att, stg['wo'])
            x = x + lax.psum(o, 'tp')        # row-parallel wo
            h2 = rms(x, stg['ln2'])
            glog = jnp.einsum('btd,de->bte', h2, stg['gate'])
            probs = jax.nn.softmax(glog, -1)
            assign = jnp.argmax(glog, -1)    # top-1 routing, dense dispatch
            y = jnp.zeros_like(h2)
            for e in range(E_local):
                ge = ep_rank * E_local + e
                w = probs[..., ge] * (assign == ge)
                u = jax.nn.gelu(jnp.einsum('btd,df->btf', h2, stg['w_up'][e]))
                y = y + w[..., None] * jnp.einsum('btf,fd->btd',
                                                  u, stg['w_down'][e])
            return x + lax.psum(y, ('tp', 'ep'))

        def stage_fn(x):                     # all this stage's layers
            for i in range(L_local):
                x = block(x, {k: v[i] for k, v in stk.items()})
            return x

        def ce_sum(logits, tgt):
            lse = jax.nn.logsumexp(logits, -1)
            gold = jnp.take_along_axis(logits, tgt[..., None], -1)[..., 0]
            return jnp.sum(lse - gold)

        # GPipe: n_micro + S - 1 ticks; stage 0 injects, last stage scores
        steps = n_micro + S - 1
        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, tt):
            buf, acc = carry
            mb = jnp.minimum(tt, n_micro - 1)
            feed = embed[lax.dynamic_index_in_dim(tokens, mb, 0,
                                                  keepdims=False)]
            x = jnp.where(stage == 0, feed, buf)
            y = stage_fn(x)
            slot = jnp.clip(tt - (S - 1), 0, n_micro - 1)
            logits = jnp.einsum('btd,dv->btv', y, head)
            tgt = lax.dynamic_index_in_dim(targets, slot, 0, keepdims=False)
            valid = (stage == S - 1) & (tt >= S - 1)
            acc = acc + jnp.where(valid, ce_sum(logits, tgt),
                                  jnp.zeros((), logits.dtype))
            buf = lax.ppermute(y, 'pp', fwd_perm)
            return (buf, acc), None

        init = (jnp.zeros((b, t, cfg.d_model), embed.dtype),
                jnp.zeros((), embed.dtype))
        (_, acc), _ = lax.scan(tick, init, jnp.arange(steps))
        total = n_micro * b * t * dp * sp    # global token count
        return lax.psum(acc, ('pp', 'dp', 'sp')) / total

    return loss_fn


def make_5d_train_step(cfg, mesh, lr=0.1, momentum=0.9):
    """(init_state, step): the full fused train step, jitted over the mesh.

    step(state, tokens, targets) -> (state, loss). State (params +
    momentum) is donated so weights update in place in HBM — the
    functional form of the reference's kWriteInplace optimizer ops.
    """
    loss_fn = make_loss_fn(cfg, mesh)
    specs = param_specs(cfg)
    shapes = param_shapes(cfg)
    dp = mesh.axis_size('dp')
    shardings = {k: NamedSharding(mesh.mesh, s) for k, s in specs.items()}
    # ZeRO over dp (arXiv:2004.13336): momentum lives dp-sharded at
    # rest, grads are constrained to the same layout, the update runs
    # on 1/dp shards, and only the params re-gather (their
    # out_shardings) for the next forward. See _zero_spec for the
    # backend-dependent comm story.
    vel_shardings = {k: NamedSharding(mesh.mesh,
                                      _zero_spec(specs[k], shapes[k], dp))
                     for k in specs}
    state_sh = {'params': shardings, 'vel': vel_shardings}
    data_sh = NamedSharding(mesh.mesh, P(None, 'dp', 'sp'))

    def init_state(seed=0):
        params = init_params(cfg, mesh, seed)
        # allocate vel DIRECTLY into its sharded layout — a dense
        # zeros-then-reshard would spike full-size buffers on one device
        vel = {k: jnp.zeros(shapes[k], v.dtype, device=vel_shardings[k])
               for k, v in params.items()}
        return {'params': params, 'vel': vel}

    def step(state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(state['params'],
                                                  tokens, targets)
        grads = {k: jax.lax.with_sharding_constraint(g, vel_shardings[k])
                 for k, g in grads.items()}
        vel = {k: momentum * state['vel'][k] - lr * grads[k]
               for k in grads}
        params = {k: jax.lax.with_sharding_constraint(
                      state['params'][k], vel_shardings[k]) + vel[k]
                  for k in grads}
        return {'params': params, 'vel': vel}, loss

    jstep = jax.jit(step, in_shardings=(state_sh, data_sh, data_sh),
                    out_shardings=(state_sh, None), donate_argnums=(0,))
    return init_state, jstep
