"""Sharding plans — name-pattern rules mapping parameters to mesh axes.

Replaces the reference's per-device parameter replicas
(gluon/parameter.py list_data — one full copy per GPU) and manual
``ctx_group`` placement (attribute.py AttrScope) with declarative rules:
a plan is an ordered list of (regex, PartitionSpec) pairs; first match
wins; no match ⇒ replicated.

Megatron-style tensor parallelism for Dense layers is two rules:
    ('.*_up_weight',   P('tp', None))   # column split: output features
    ('.*_down_weight', P(None, 'tp'))   # row split: input features
XLA then inserts the single psum after the row-split matmul.
"""
import re

import jax
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ['ShardingPlan', 'data_parallel_plan', 'constrain',
           'shard_params', 'replicate_params']

P = PartitionSpec


class ShardingPlan:
    """Ordered (pattern → PartitionSpec) rules for a parameter pytree."""

    def __init__(self, rules=(), default=P()):
        self.rules = [(re.compile(pat), spec if isinstance(spec, PartitionSpec)
                       else P(*spec)) for pat, spec in rules]
        self.default = default

    def spec_for(self, name, shape=None, mesh=None):
        for pat, spec in self.rules:
            if pat.fullmatch(name):
                return self._fit(spec, shape, mesh)
        return self._fit(self.default, shape, mesh)

    @staticmethod
    def _fit(spec, shape, mesh=None):
        # Best-effort fit: trim the spec to the array rank (one rule covers
        # e.g. both the weight and its 1-d bias) and drop axes that don't
        # divide the dimension (a (64, 1) head weight under P(None, 'tp')
        # stays replicated on dim 1 instead of erroring in device_put).
        if shape is None:
            return spec
        t = list(spec)[:len(shape)]
        if mesh is not None:
            for i, ax in enumerate(t):
                if ax is None:
                    continue
                axes = ax if isinstance(ax, tuple) else (ax,)
                n = 1
                for a in axes:
                    n *= mesh.axis_size(a) if hasattr(mesh, 'axis_size') else int(mesh.shape[a])
                if shape[i] % n:
                    t[i] = None
        return P(*t)

    def shardings(self, mesh, params):
        """{name: array-like} → {name: NamedSharding}."""
        return {k: NamedSharding(mesh.mesh, self.spec_for(k, getattr(v, 'shape', None), mesh))
                for k, v in params.items()}

    def extended(self, rules):
        plan = ShardingPlan(default=self.default)
        plan.rules = [(re.compile(p), s if isinstance(s, PartitionSpec) else P(*s))
                      for p, s in rules] + list(self.rules)
        return plan


def data_parallel_plan():
    """Pure DP: every parameter replicated; only the batch is sharded."""
    return ShardingPlan()


def constrain(x, mesh, *spec):
    """In-jit sharding annotation (lax.with_sharding_constraint) — how a
    traced step pins activations to mesh axes."""
    return jax.lax.with_sharding_constraint(x, mesh.sharding(*spec))


def shard_params(params, mesh, plan=None):
    """Place a {name: jax.Array} dict onto the mesh per the plan."""
    plan = plan or data_parallel_plan()
    out = {}
    for k, v in params.items():
        out[k] = jax.device_put(
            v, NamedSharding(mesh.mesh,
                             plan.spec_for(k, getattr(v, 'shape', None), mesh)))
    return out


def replicate_params(params, mesh):
    return shard_params(params, mesh, ShardingPlan())
