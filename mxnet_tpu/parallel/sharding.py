"""Sharding plans — name-pattern rules mapping parameters to mesh axes.

Replaces the reference's per-device parameter replicas
(gluon/parameter.py list_data — one full copy per GPU) and manual
``ctx_group`` placement (attribute.py AttrScope) with declarative rules:
a plan is an ordered list of (regex, PartitionSpec) pairs; first match
wins; no match ⇒ replicated.

Megatron-style tensor parallelism for Dense layers is two rules:
    ('.*_up_weight',   P('tp', None))   # column split: output features
    ('.*_down_weight', P(None, 'tp'))   # row split: input features
XLA then inserts the single psum after the row-split matmul.
"""
import re

import jax
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ['ShardingPlan', 'data_parallel_plan', 'constrain',
           'shard_params', 'replicate_params', 'zero_pad_len',
           'zero_flatten', 'zero_unflatten', 'zero_sharded_bytes']

P = PartitionSpec


class ShardingPlan:
    """Ordered (pattern → PartitionSpec) rules for a parameter pytree."""

    def __init__(self, rules=(), default=P()):
        self.rules = [(re.compile(pat), spec if isinstance(spec, PartitionSpec)
                       else P(*spec)) for pat, spec in rules]
        self.default = default

    def spec_for(self, name, shape=None, mesh=None):
        for pat, spec in self.rules:
            if pat.fullmatch(name):
                return self._fit(spec, shape, mesh)
        return self._fit(self.default, shape, mesh)

    @staticmethod
    def _fit(spec, shape, mesh=None):
        # Best-effort fit: trim the spec to the array rank (one rule covers
        # e.g. both the weight and its 1-d bias) and drop axes that don't
        # divide the dimension (a (64, 1) head weight under P(None, 'tp')
        # stays replicated on dim 1 instead of erroring in device_put).
        if shape is None:
            return spec
        t = list(spec)[:len(shape)]
        if mesh is not None:
            for i, ax in enumerate(t):
                if ax is None:
                    continue
                axes = ax if isinstance(ax, tuple) else (ax,)
                n = 1
                for a in axes:
                    n *= mesh.axis_size(a) if hasattr(mesh, 'axis_size') else int(mesh.shape[a])
                if shape[i] % n:
                    t[i] = None
        return P(*t)

    def shardings(self, mesh, params):
        """{name: array-like} → {name: NamedSharding}."""
        return {k: NamedSharding(mesh.mesh, self.spec_for(k, getattr(v, 'shape', None), mesh))
                for k, v in params.items()}

    def extended(self, rules):
        plan = ShardingPlan(default=self.default)
        plan.rules = [(re.compile(p), s if isinstance(s, PartitionSpec) else P(*s))
                      for p, s in rules] + list(self.rules)
        return plan


def data_parallel_plan():
    """Pure DP: every parameter replicated; only the batch is sharded."""
    return ShardingPlan()


def constrain(x, mesh, *spec):
    """In-jit sharding annotation (lax.with_sharding_constraint) — how a
    traced step pins activations to mesh axes."""
    return jax.lax.with_sharding_constraint(x, mesh.sharding(*spec))


def shard_params(params, mesh, plan=None):
    """Place a {name: jax.Array} dict onto the mesh per the plan."""
    plan = plan or data_parallel_plan()
    out = {}
    for k, v in params.items():
        out[k] = jax.device_put(
            v, NamedSharding(mesh.mesh,
                             plan.spec_for(k, getattr(v, 'shape', None), mesh)))
    return out


def replicate_params(params, mesh):
    return shard_params(params, mesh, ShardingPlan())


# ---------------------------------------------------------------------------
# ZeRO-style update-phase leaf form (arXiv:2004.13336)
# ---------------------------------------------------------------------------
# The cross-replica weight-update sharding works on ONE canonical leaf
# layout: every tensor entering the sharded update is flattened to 1-D
# and zero-padded to a multiple of the dp axis, so EVERY leaf divides
# evenly — a (10, 7) head weight shards as cleanly as a (64, 3, 7, 7)
# conv kernel. Zero padding is an invariant of the framework's fused
# update ops (sgd/nag/adam/rmsprop/ftrl are all elementwise with
# update(0, grad=0, state=0) == (0, 0)), so the pad region never
# contaminates real elements and never drifts from zero.

def zero_pad_len(n, dp):
    """Smallest multiple of ``dp`` >= ``n`` (the padded flat length)."""
    return -(-int(n) // int(dp)) * int(dp)


def zero_flatten(x, dp):
    """A leaf in the update-phase form: 1-D, zero-padded to a multiple
    of ``dp``. Traceable (used inside the compiled window body) and
    valid eagerly (the host-side placement path)."""
    import jax.numpy as jnp
    flat = jnp.reshape(x, (-1,))
    pad = zero_pad_len(flat.shape[0], dp) - flat.shape[0]
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def zero_unflatten(flat, shape):
    """Invert :func:`zero_flatten`: drop the pad tail, restore the
    original shape."""
    import jax.numpy as jnp
    n = 1
    for d in shape:
        n *= int(d)
    if int(flat.shape[0]) != n:
        flat = flat[:n]
    return jnp.reshape(flat, tuple(shape))


def zero_sharded_bytes(shape, dtype, dp):
    """Per-DEVICE bytes of one leaf held in the update-phase form
    (flat, padded, 1/dp per device) — the honest number behind the
    ``update.opt_state_bytes_per_device`` gauge."""
    import numpy as np
    n = 1
    for d in shape:
        n *= int(d)
    return zero_pad_len(n, dp) // int(dp) * np.dtype(dtype).itemsize
