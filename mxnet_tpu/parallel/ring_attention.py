"""Ring / blockwise attention — sequence & context parallelism.

The reference's only long-sequence mechanisms are bucketing and truncated
BPTT (SURVEY.md §5.7: BucketingModule, docs/how_to/bucketing.md) — memory
still scales with full sequence length on one device. This module is the
greenfield TPU answer: shard the sequence axis across the ``sp`` mesh
axis and stream K/V blocks around the ring with ``lax.ppermute``, keeping
a numerically-stable running softmax (flash-attention style log-sum-exp
accumulation) so no device ever materialises the full [T, T] score matrix.

Three interchangeable kernels:
- :func:`blockwise_attention` — single-device, K/V blocked via lax.scan
  (memory-efficient attention; the intra-device half of ring attention).
- :func:`ring_attention`     — sp-sharded, ppermute ring (call inside
  shard_map over the ``sp`` axis).
- :func:`ulysses_attention`  — sp-sharded via two all_to_alls (heads↔seq
  transpose), exact and cheap when head count ≥ sp size.

Shapes follow [batch, seq, heads, head_dim] throughout.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['ring_attention', 'blockwise_attention', 'ulysses_attention',
           'striped_attention', 'stripe_layout', 'unstripe_layout',
           'make_ring_attention', 'attention_reference']

_NEG = -1e30


def attention_reference(q, k, v, causal=False, scale=None):
    """Plain softmax attention — the correctness oracle for the kernels."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum('bqhd,bkhd->bhqk', q * scale, k)
    if causal:
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)


def _block_accum(q, k, v, carry, scale, mask=None):
    """One flash step: fold a K/V block into (acc, running_max, denom).

    q: [B,Tq,H,D]; k,v: [B,Tk,H,D]; acc: [B,Tq,H,D]; m,l: [B,H,Tq]."""
    acc, m, l = carry
    s = jnp.einsum('bqhd,bkhd->bhqk', q * scale, k)
    if mask is not None:
        s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # renormalise previous accumulator to the new max
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])            # [B,H,Tq,Tk]
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum('bhqk,bkhd->bqhd', p, v)
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return acc_new, m_new, l_new


def _finalize(acc, l):
    l = jnp.maximum(l, 1e-30)                    # fully-masked rows → 0 output
    return acc / l.transpose(0, 2, 1)[..., None]


def blockwise_attention(q, k, v, block_size=512, causal=False, scale=None):
    """Memory-efficient attention: lax.scan over K/V blocks.

    Peak memory O(Tq·block) instead of O(Tq·Tk); same math as
    attention_reference to fp tolerance."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    block = min(block_size, Tk)
    if Tk % block:
        raise ValueError('Tk %d not divisible by block %d' % (Tk, block))
    nblk = Tk // block
    kb = k.reshape(B, nblk, block, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, nblk, block, H, D).transpose(1, 0, 2, 3, 4)

    # queries align to the END of the key sequence (decode convention),
    # matching attention_reference's tril(..., Tk - Tq)
    qpos = jnp.arange(Tq) + (Tk - Tq)

    def scan_fn(carry, inp):
        i, kblk, vblk = inp
        mask = None
        if causal:
            kpos = i * block + jnp.arange(block)
            mask = qpos[:, None] >= kpos[None, :]          # [Tq, block]
            mask = mask[None, None]                        # [1,1,Tq,block]
        return _block_accum(q, kblk, vblk, carry, scale, mask), None

    init = (jnp.zeros_like(q),
            jnp.full((B, H, Tq), _NEG, q.dtype),
            jnp.zeros((B, H, Tq), q.dtype))
    (acc, m, l), _ = lax.scan(scan_fn, init, (jnp.arange(nblk), kb, vb))
    return _finalize(acc, l)


def ring_attention(q, k, v, axis='sp', causal=False, scale=None,
                   use_flash=True, block_q=128, block_k=128):
    """Ring attention over the ``axis`` mesh axis (call under shard_map).

    Each device holds the local sequence chunk of q/k/v
    [B, T/sp, H, D]. K/V chunks rotate around the ring; after sp steps
    every q chunk has attended to the full sequence. Communication is
    sp-1 ppermutes of the local K/V — bandwidth-optimal and overlapped
    with compute by XLA (latency hiding via the ring schedule).

    The local q×chunk block runs on the Pallas flash kernel
    (ops/pallas_kernels.flash_attention_lse — online softmax in VMEM);
    per-chunk normalized outputs are merged exactly via the kernel's
    log-sum-exp. Pass ``use_flash=False`` for the plain-jnp accumulator
    (used as the cross-check oracle in tests).

    causal=True assumes chunks are laid out in sequence order along the
    axis (chunk c owns positions [c*T_local, (c+1)*T_local)).
    """
    B, Tl, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    qpos = jnp.arange(Tl)

    if use_flash:
        from ..ops.pallas_kernels import flash_attention_lse

        def body(step, carry):
            kk, vv, acc, m, l = carry
            src = (my - step) % n                 # whose chunk we hold now
            if causal:
                # diagonal chunk: causal flash; earlier chunks: full
                # attention; later chunks: computed then discarded (w=0)
                o, lse = lax.cond(
                    src == my,
                    lambda: flash_attention_lse(q, kk, vv, True, scale,
                                                block_q, block_k),
                    lambda: flash_attention_lse(q, kk, vv, False, scale,
                                                block_q, block_k))
                valid = src <= my
                lse = jnp.where(valid, lse, _NEG)
            else:
                valid = True
                o, lse = flash_attention_lse(q, kk, vv, False, scale,
                                             block_q, block_k)
            # exact merge of normalized chunk outputs via their lse
            m_new = jnp.maximum(m, lse)
            corr = jnp.exp(m - m_new)
            w = jnp.exp(lse - m_new)              # [B,H,Tl]
            # a discarded chunk meeting a still-empty accumulator gives
            # exp(_NEG - _NEG) = 1: force its weight to zero explicitly
            w = jnp.where(valid, w, 0.0)
            acc = (acc * corr.transpose(0, 2, 1)[..., None] +
                   o * w.transpose(0, 2, 1)[..., None])
            l = l * corr + w
            kk = lax.ppermute(kk, axis, perm)
            vv = lax.ppermute(vv, axis, perm)
            return kk, vv, acc, m_new, l
    else:
        def body(step, carry):
            kk, vv, acc, m, l = carry
            src = (my - step) % n                 # whose chunk we hold now
            if causal:
                # block-level causal: q chunk `my` vs k chunk `src`
                kpos = jnp.arange(Tl)
                gq = my * Tl + qpos               # global positions
                gk = src * Tl + kpos
                mask = (gq[:, None] >= gk[None, :])[None, None]
            else:
                mask = None
            acc, m, l = _block_accum(q, kk, vv, (acc, m, l), scale, mask)
            kk = lax.ppermute(kk, axis, perm)
            vv = lax.ppermute(vv, axis, perm)
            return kk, vv, acc, m, l

    init = (k, v,
            jnp.zeros_like(q),
            jnp.full((B, H, Tl), _NEG, q.dtype),
            jnp.zeros((B, H, Tl), q.dtype))
    _, _, acc, m, l = lax.fori_loop(0, n, body, init)
    return _finalize(acc, l)


def ulysses_attention(q, k, v, axis='sp', causal=False, scale=None):
    """DeepSpeed-Ulysses style: all_to_all seq↔heads so each device holds
    ALL positions for H/sp heads, runs plain attention, transposes back.
    Exact; needs H divisible by the axis size. Call under shard_map."""
    # [B, T/sp, H, D] -> [B, T, H/sp, D]
    q = lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    o = attention_reference(q, k, v, causal=causal, scale=scale)
    return lax.all_to_all(o, axis, split_axis=1, concat_axis=2, tiled=True)


def make_ring_attention(mesh, axis='sp', causal=False, impl='ring', scale=None):
    """shard_map-wrapped callable on full arrays: shards q/k/v on the
    sequence dim over `axis`, runs the chosen kernel, unshards nothing
    (output stays sequence-sharded, matching the input layout)."""
    from jax.sharding import PartitionSpec as P
    from ._compat import shard_map
    fn = {'ring': ring_attention, 'ulysses': ulysses_attention,
          'striped': striped_attention}[impl]
    spec = P(None, axis, None, None)

    @functools.partial(shard_map, mesh=mesh.mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def apply(q, k, v):
        return fn(q, k, v, axis=axis, causal=causal, scale=scale)
    return apply


def stripe_layout(x, sp, axis=1):
    """Reorder the sequence axis so CONTIGUOUS sharding over ``sp``
    devices yields the striped (round-robin) layout: shard s holds
    global positions s, s+sp, s+2sp, ... (Striped Attention, Brandon et
    al. 2023, arXiv:2311.09431). Apply before shard_map, invert with
    :func:`unstripe_layout`."""
    T = x.shape[axis]
    if T % sp != 0:
        raise ValueError('sp (%d) must divide the sequence length (%d)'
                         % (sp, T))
    shape = list(x.shape)
    # [..., T, ...] -> [..., T//sp, sp, ...] -> [..., sp, T//sp, ...]
    x = x.reshape(shape[:axis] + [T // sp, sp] + shape[axis + 1:])
    x = jnp.swapaxes(x, axis, axis + 1)
    return x.reshape(shape)


def unstripe_layout(x, sp, axis=1):
    """Inverse of :func:`stripe_layout`."""
    T = x.shape[axis]
    shape = list(x.shape)
    x = x.reshape(shape[:axis] + [sp, T // sp] + shape[axis + 1:])
    x = jnp.swapaxes(x, axis, axis + 1)
    return x.reshape(shape)


def striped_attention(q, k, v, axis='sp', causal=True, scale=None):
    """Striped ring attention (Brandon et al. 2023): with the
    round-robin token layout (:func:`stripe_layout`), every ring step
    computes a near-triangular block, so causal work is load-balanced
    across the ring — the contiguous-chunk schedule leaves early
    devices idle for late chunks and vice versa.

    Mask per step (device ``my`` holding k-chunk from ``src``): global
    positions are ``gq_i = my + sp*i``, ``gk_j = src + sp*j``, so
    ``gq_i >= gk_j`` reduces to ``i >= j`` when ``src <= my`` and
    ``i > j`` otherwise. Call under shard_map, inputs in striped
    layout."""
    B, Tl, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    i = jnp.arange(Tl)
    tri_incl = (i[:, None] >= i[None, :])[None, None]
    tri_strict = (i[:, None] > i[None, :])[None, None]

    def body(step, carry):
        kk, vv, acc, m, l = carry
        src = (my - step) % n
        mask = jnp.where(src <= my, tri_incl, tri_strict) if causal \
            else None
        acc, m, l = _block_accum(q, kk, vv, (acc, m, l), scale, mask)
        kk = lax.ppermute(kk, axis, perm)
        vv = lax.ppermute(vv, axis, perm)
        return kk, vv, acc, m, l

    init = (k, v,
            jnp.zeros_like(q),
            jnp.full((B, H, Tl), _NEG, q.dtype),
            jnp.zeros((B, H, Tl), q.dtype))
    _, _, acc, m, l = lax.fori_loop(0, n, body, init)
    return _finalize(acc, l)
