"""Multi-host SPMD initialization — XLA collectives over ICI/DCN.

Reference analog: the ps-lite bootstrap (`ps::Postoffice` role/rank
wiring, kvstore.h:257-301) that connects MXNet workers across machines.
The TPU-native transport is NOT a parameter server: every host joins
one jax.distributed job, `jax.devices()` becomes the GLOBAL device
list, and a `Mesh` laid out over it makes pjit/shard_map insert DCN/ICI
collectives automatically (psum replaces push/pull — SURVEY §5.8).

The dist kvstore tier (kvstore_dist.py) remains for reference-API
compatibility; this module is the idiomatic path for new code:

    mx.parallel.init_multihost()              # env-driven, launcher-set
    mesh = mx.parallel.global_mesh({'dp': -1})
    ... pjit/shard_map over mesh ...

`tools/launch.py` exports MXTPU_COORDINATOR / MXTPU_NUM_HOSTS /
MXTPU_HOST_ID for its workers, so the same launcher drives both the PS
tier and this one.
"""
import numpy as np

__all__ = ['init_multihost', 'global_mesh', 'process_index',
           'process_count', 'local_devices', 'is_multihost',
           'mesh_descriptor']

_initialized = False


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None):
    """Join (or create) a jax.distributed job.

    Arguments default from the launcher env protocol:
    ``MXTPU_COORDINATOR`` (host:port), ``MXTPU_NUM_HOSTS``,
    ``MXTPU_HOST_ID``. With one process (or no env), this is a no-op —
    single-host programs need no coordinator. Safe to call twice.
    """
    global _initialized
    if _initialized:
        return False
    from ..config import flags
    flags.reload('MXTPU_COORDINATOR')
    flags.reload('MXTPU_NUM_HOSTS')
    flags.reload('MXTPU_HOST_ID')
    coordinator_address = coordinator_address or \
        flags.get('MXTPU_COORDINATOR')
    num_processes = num_processes if num_processes is not None else \
        flags.get('MXTPU_NUM_HOSTS')
    process_id = process_id if process_id is not None else \
        flags.get('MXTPU_HOST_ID')
    if num_processes <= 1 or not coordinator_address:
        return False
    import jax
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    # telemetry plane: from here jax.process_index() is authoritative —
    # pin the host stamp (JSONL records, /metrics labels) and announce
    # the job size so cluster aggregation can name every host
    try:
        from .. import telemetry as _tele
        if _tele.enabled():
            _tele.cluster.set_host(jax.process_index())
            _tele.gauge('cluster.process_count').set(int(num_processes))
            _tele.event('multihost.init', host=int(jax.process_index()),
                        num_hosts=int(num_processes),
                        coordinator=coordinator_address)
    except Exception:  # noqa: BLE001 — observability must not block init
        pass
    return True


def process_index():
    import jax
    return jax.process_index()


def process_count():
    import jax
    return jax.process_count()


def local_devices():
    import jax
    return jax.local_devices()


def is_multihost():
    import jax
    return jax.process_count() > 1


def mesh_descriptor():
    """The live process/device set as a plain JSON-able dict —
    recorded into every checkpoint's meta sidecar
    (module/checkpointing.py) so a restore can tell "same mesh, plain
    resume" from "smaller/larger mesh, reshard-on-restore" and remap
    the io shard cursor accordingly. Requires the backend to be up
    (checkpointing only runs after bind, so it always is)."""
    import jax
    return {'devices': int(jax.device_count()),
            'local_devices': int(jax.local_device_count()),
            'processes': int(jax.process_count()),
            'process_index': int(jax.process_index())}


def global_mesh(axes):
    """Build a Mesh over the GLOBAL device list.

    ``axes``: ordered dict/list of (name, size); one size may be -1
    (inferred). Axis order should put the fastest-varying (ICI-local)
    axis last so DCN only carries the leading axes — the
    how-to-scale-your-model layout rule.
    """
    import jax
    from jax.sharding import Mesh

    if isinstance(axes, dict):
        items = list(axes.items())
    else:
        items = list(axes)
    names = [k for k, _ in items]
    sizes = [v for _, v in items]
    devs = jax.devices()
    n = len(devs)
    if sizes.count(-1) > 1:
        raise ValueError('at most one axis size may be -1')
    known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if -1 in sizes:
        if n % known:
            raise ValueError('device count %d not divisible by %d'
                             % (n, known))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError('mesh %r does not cover %d global devices'
                         % (dict(zip(names, sizes)), n))
    return Mesh(np.array(devs).reshape(sizes), tuple(names))
