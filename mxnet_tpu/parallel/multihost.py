"""Multi-host SPMD initialization — XLA collectives over ICI/DCN.

Reference analog: the ps-lite bootstrap (`ps::Postoffice` role/rank
wiring, kvstore.h:257-301) that connects MXNet workers across machines.
The TPU-native transport is NOT a parameter server: every host joins
one jax.distributed job, `jax.devices()` becomes the GLOBAL device
list, and a `Mesh` laid out over it makes pjit/shard_map insert DCN/ICI
collectives automatically (psum replaces push/pull — SURVEY §5.8).

The dist kvstore tier (kvstore_dist.py) remains for reference-API
compatibility; this module is the idiomatic path for new code:

    mx.parallel.init_multihost()              # env-driven, launcher-set
    mesh = mx.parallel.global_mesh({'dp': -1})
    ... pjit/shard_map over mesh ...

`tools/launch.py` exports MXTPU_COORDINATOR / MXTPU_NUM_HOSTS /
MXTPU_HOST_ID for its workers, so the same launcher drives both the PS
tier and this one.
"""
import logging
import time

import numpy as np

__all__ = ['init_multihost', 'global_mesh', 'process_index',
           'process_count', 'local_devices', 'is_multihost',
           'mesh_descriptor', 'is_primary', 'barrier', 'agree_min',
           'agree_any']

_initialized = False
_INIT_ATTEMPTS = 3


def _enable_cpu_collectives():
    """REAL multi-process jobs on the CPU backend need a cross-process
    collectives implementation: without one, the very first jitted
    collective dies with "Multiprocess computations aren't implemented
    on the CPU backend" — which is why every multi-host behavior was
    only ever simulated single-process before the gang tier. Gloo ships
    in jaxlib; selecting it must happen before the CPU client
    initializes (jax.distributed.initialize guarantees we are early
    enough). Non-CPU platforms ignore the setting."""
    import jax
    try:
        current = jax.config.values.get('jax_cpu_collectives_implementation')
    except AttributeError:      # much older jax: nothing to select
        return
    if current in (None, 'none'):
        try:
            jax.config.update('jax_cpu_collectives_implementation', 'gloo')
        except Exception as e:  # noqa: BLE001 — jaxlib without gloo
            logging.warning(
                'multihost: cannot select the gloo CPU collectives '
                'implementation (%s) — CPU multi-process collectives '
                'will fail', e)


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None):
    """Join (or create) a jax.distributed job.

    Arguments default from the launcher env protocol:
    ``MXTPU_COORDINATOR`` (host:port), ``MXTPU_NUM_HOSTS``,
    ``MXTPU_HOST_ID``. With one process (or no env), this is a no-op —
    single-host programs need no coordinator. Safe to call twice.

    Transient join failures retry with backoff: a relaunched gang can
    race a dying predecessor for the coordinator port, and workers can
    reach the coordinator before it listens. ``MXTPU_COORD_TIMEOUT``
    bounds each attempt (0 = jax's default, 5 minutes) so a gang
    relaunch against a never-arriving coordinator fails fast enough
    for the supervisor to tear it down and try a fresh port. (One
    failure mode is not recoverable in-process: on jax 0.4.x a
    coordinator whose port is already bound dies in grpc before Python
    can catch anything — tools/gang_supervisor.py treats that unclean
    exit like any other and relaunches the gang on a fresh port.)
    """
    global _initialized
    if _initialized:
        return False
    from ..config import flags
    flags.reload('MXTPU_COORDINATOR')
    flags.reload('MXTPU_NUM_HOSTS')
    flags.reload('MXTPU_HOST_ID')
    flags.reload('MXTPU_COORD_TIMEOUT')
    coordinator_address = coordinator_address or \
        flags.get('MXTPU_COORDINATOR')
    num_processes = num_processes if num_processes is not None else \
        flags.get('MXTPU_NUM_HOSTS')
    process_id = process_id if process_id is not None else \
        flags.get('MXTPU_HOST_ID')
    if num_processes <= 1 or not coordinator_address:
        return False
    import jax
    _enable_cpu_collectives()
    kwargs = {}
    timeout = flags.get('MXTPU_COORD_TIMEOUT')
    if timeout and timeout > 0:
        # jax takes whole seconds; a sub-second operator value must
        # round UP to 1, not truncate to an immediate 0s timeout
        kwargs['initialization_timeout'] = max(1, int(round(timeout)))
    for attempt in range(_INIT_ATTEMPTS):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id, **kwargs)
            break
        except Exception as e:  # noqa: BLE001 — connect timeout / bind race
            if attempt + 1 >= _INIT_ATTEMPTS:
                raise
            logging.warning(
                'multihost: jax.distributed join attempt %d/%d failed '
                '(%s) — retrying', attempt + 1, _INIT_ATTEMPTS, e)
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 — nothing to tear down
                pass
            time.sleep(0.5 * 2 ** attempt)
    _initialized = True
    # telemetry plane: from here jax.process_index() is authoritative —
    # pin the host stamp (JSONL records, /metrics labels) and announce
    # the job size so cluster aggregation can name every host
    try:
        from .. import telemetry as _tele
        if _tele.enabled():
            _tele.cluster.set_host(jax.process_index())
            _tele.gauge('cluster.process_count').set(int(num_processes))
            _tele.event('multihost.init', host=int(jax.process_index()),
                        num_hosts=int(num_processes),
                        coordinator=coordinator_address)
    except Exception:  # noqa: BLE001 — observability must not block init
        pass
    return True


def process_index():
    import jax
    return jax.process_index()


def process_count():
    import jax
    return jax.process_count()


def local_devices():
    import jax
    return jax.local_devices()


def is_multihost():
    import jax
    return jax.process_count() > 1


def mesh_descriptor():
    """The live process/device set as a plain JSON-able dict —
    recorded into every checkpoint's meta sidecar
    (module/checkpointing.py) so a restore can tell "same mesh, plain
    resume" from "smaller/larger mesh, reshard-on-restore" and remap
    the io shard cursor accordingly. Requires the backend to be up
    (checkpointing only runs after bind, so it always is)."""
    import jax
    return {'devices': int(jax.device_count()),
            'local_devices': int(jax.local_device_count()),
            'processes': int(jax.process_count()),
            'process_index': int(jax.process_index())}


# ---------------------------------------------------------------------------
# cross-host agreement over the jax.distributed coordination service
# ---------------------------------------------------------------------------
#
# The gang checkpoint tier (module/checkpointing.py) must make a few
# small decisions that every host of a job answers IDENTICALLY — "is
# any host's async writer still busy?", "what is the newest step every
# host has committed and health-cleared?" — or the per-host answers
# diverge and an orbax collective save wedges / a relaunched gang
# restores divergent steps. These ride the coordination service's KV
# store + named barrier (NOT device collectives): they are safe from
# any thread, independent of the XLA collective schedule, and every
# wait is bounded — a gang mid-death times out and returns None
# instead of wedging the anti-hang machinery itself.

_AGREE_TIMEOUT_S = 60.0


def _client():
    """The jax.distributed coordination-service client, or None when no
    multi-process job is up (single-process: every agreement is local)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # noqa: BLE001 — internal layout moved
        return None


def is_primary():
    """Whether this process writes shared-FS artifacts the whole job
    reads (the last_good pointer): process 0, or any single process."""
    if _client() is None:
        return True
    import jax
    return jax.process_index() == 0


def barrier(name, timeout_s=_AGREE_TIMEOUT_S):
    """Named barrier across every process of the job. True once all
    arrived; False on timeout/error (callers keep their safe behavior —
    never advance shared state on False). No-op True single-process."""
    c = _client()
    if c is None:
        return True
    try:
        c.wait_at_barrier('mxtpu_' + str(name), int(timeout_s * 1000))
        return True
    except Exception as e:  # noqa: BLE001 — peer died / timed out
        logging.warning('multihost: barrier %r failed (%s)', name, e)
        return False


def _exchange(name, value, timeout_s):
    """All-hosts value exchange through the coordination KV store:
    every process contributes ``value`` under a ``name``d round, waits
    for the rest, and reads everyone's. Returns the list of int values
    (all processes see the same list) or None on timeout/error.
    ``name`` must be unique per call (callers thread a round counter
    through) — coordination barriers are one-shot."""
    c = _client()
    if c is None:
        return [int(value)]
    import jax
    n = jax.process_count()
    prefix = 'mxtpu_agree/%s/' % name
    try:
        c.key_value_set(prefix + str(jax.process_index()), str(int(value)))
    except Exception as e:  # noqa: BLE001
        logging.warning('multihost: agreement %r failed to publish (%s)',
                        name, e)
        return None
    if not barrier(str(name) + '/gather', timeout_s):
        return None
    # the read phase retries once: it is the one step whose failure is
    # ASYMMETRIC (this host returns None while peers that read fine
    # proceed on the gathered values). The window cannot be closed
    # entirely — two-phase-commit impossibility — only shrunk; callers
    # therefore treat None as the conservative vote (skip the save,
    # freeze the pointer), and the per-step round naming self-heals at
    # the next lockstep point
    items = None
    for attempt in range(2):
        try:
            items = c.key_value_dir_get(prefix)
            break
        except Exception as e:  # noqa: BLE001
            if attempt:
                logging.warning(
                    'multihost: agreement %r failed to read (%s)',
                    name, e)
                return None
            time.sleep(0.2)
    if len(items) != n:
        logging.warning('multihost: agreement %r saw %d/%d contributions',
                        name, len(items), n)
        return None
    vals = []
    try:
        for _key, raw in items:
            vals.append(int(raw))
    except (TypeError, ValueError) as e:
        logging.warning('multihost: agreement %r garbled (%s)', name, e)
        return None
    # second barrier before cleanup: a host still inside dir_get must
    # not race the delete
    if barrier(str(name) + '/done', timeout_s) and jax.process_index() == 0:
        try:
            c.key_value_delete(prefix)
        except Exception:  # noqa: BLE001 — stale keys are harmless
            pass
    return vals


def agree_min(name, value, timeout_s=_AGREE_TIMEOUT_S):
    """The minimum of every host's ``value`` — the cross-host-agreed
    checkpoint step: a step is safe to restore only once EVERY host has
    committed and cleared it. None on timeout/error (no agreement)."""
    vals = _exchange(name, value, timeout_s)
    return min(vals) if vals else None


def agree_any(name, flag, timeout_s=_AGREE_TIMEOUT_S):
    """Whether ``flag`` is true on ANY host — the global busy-writer
    skip: an orbax save is a collective, so either every host of the
    gang initiates it or none does. None on timeout/error."""
    vals = _exchange(name, 1 if flag else 0, timeout_s)
    return any(vals) if vals is not None else None


def global_mesh(axes):
    """Build a Mesh over the GLOBAL device list.

    ``axes``: ordered dict/list of (name, size); one size may be -1
    (inferred). Axis order should put the fastest-varying (ICI-local)
    axis last so DCN only carries the leading axes — the
    how-to-scale-your-model layout rule.
    """
    import jax
    from jax.sharding import Mesh

    if isinstance(axes, dict):
        items = list(axes.items())
    else:
        items = list(axes)
    names = [k for k, _ in items]
    sizes = [v for _, v in items]
    devs = jax.devices()
    n = len(devs)
    if sizes.count(-1) > 1:
        raise ValueError('at most one axis size may be -1')
    known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if -1 in sizes:
        if n % known:
            raise ValueError('device count %d not divisible by %d'
                             % (n, known))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError('mesh %r does not cover %d global devices'
                         % (dict(zip(names, sizes)), n))
    return Mesh(np.array(devs).reshape(sizes), tuple(names))
