"""parallel — SPMD distribution over TPU device meshes.

This package is the TPU-native replacement for the reference's entire
distribution story (SURVEY.md §2.3, §5.8):

- ``src/kvstore/comm.h`` device-tier reduce/broadcast  → XLA collectives
  over the ICI mesh (:mod:`collectives`).
- ``DataParallelExecutorGroup`` (module/executor_group.py:99) batch
  slicing  → one pjit'd step with the batch sharded on the ``dp`` mesh
  axis (:mod:`data_parallel`).
- ``AttrScope(ctx_group)`` manual model parallelism  → sharding
  annotations (:mod:`sharding`) and a real micro-batch pipeline schedule
  (:mod:`pipeline`) — new capability, absent in the reference.
- Long sequences: the reference buckets (BucketingModule); here sequence/
  context parallelism via ring attention over ``ppermute``
  (:mod:`ring_attention`) — new capability.
"""
from ._compat import shard_map  # noqa: F401  (version-stable spelling)
from .mesh import DeviceMesh, make_mesh, local_mesh
from .collectives import (allreduce, allgather, reduce_scatter, ring_permute,
                          alltoall, axis_index, axis_size, pbroadcast)
from .sharding import (ShardingPlan, data_parallel_plan, constrain,
                       shard_params, replicate_params)
from .data_parallel import make_train_step, ShardedTrainer
from . import checkpoint  # noqa: F401  (sharded SPMD checkpointing)
from .ring_attention import (ring_attention, blockwise_attention,
                             ulysses_attention, striped_attention,
                             stripe_layout, unstripe_layout,
                             make_ring_attention,
                             attention_reference)
from .pipeline import PipelineStage, pipeline_apply, stack_stage_params
from .multihost import (init_multihost, global_mesh, process_index,
                        process_count, is_multihost)
from .five_d import (TransformerConfig, full_mesh, make_5d_train_step,
                     make_loss_fn as make_5d_loss_fn)
from . import compression  # noqa: F401  (quantized gradient collectives)
from .compression import compressed_psum

__all__ = [
    'DeviceMesh', 'make_mesh', 'local_mesh',
    'allreduce', 'allgather', 'reduce_scatter', 'ring_permute', 'alltoall',
    'axis_index', 'axis_size', 'pbroadcast',
    'ShardingPlan', 'data_parallel_plan', 'constrain', 'shard_params',
    'replicate_params',
    'make_train_step', 'ShardedTrainer',
    'checkpoint',
    'ring_attention', 'blockwise_attention', 'ulysses_attention',
    'striped_attention', 'stripe_layout', 'unstripe_layout',
    'make_ring_attention', 'attention_reference',
    'PipelineStage', 'pipeline_apply', 'stack_stage_params',
    'TransformerConfig', 'full_mesh', 'make_5d_train_step',
    'make_5d_loss_fn',
    'init_multihost', 'global_mesh', 'process_index', 'process_count',
    'is_multihost',
    'compression', 'compressed_psum',
]
