"""Sharded checkpoint / resume for SPMD training state.

The reference's checkpointing (model.py save_checkpoint, reference
model.py:340) gathers every parameter to one host — fine for one
machine, quadratically painful for a sharded multi-host run. This is
the TPU-native tier: orbax-checkpoint writes each host's shards of a
``jax.Array`` pytree in parallel and restores them onto the SAME mesh
sharding without ever materialising the full state anywhere.

    from mxnet_tpu.parallel import checkpoint as ckpt
    mngr = ckpt.manager('/path/ckpts', max_to_keep=3)
    ckpt.save(mngr, step, train_state)          # shard-parallel write
    state = ckpt.restore(mngr, template=train_state)   # same shardings
    step = mngr.latest_step()

Interop note: for reference-format `.params` files keep using
``mx.model.save_checkpoint`` / ``nd.save`` (docs/migration.md) — this
tier is for large sharded SPMD state, the two are complementary.
"""
import jax

__all__ = ['manager', 'save', 'restore', 'restore_with_meta', 'read_meta',
           'restore_state', 'latest_step', 'all_steps', 'delete_step',
           'wait', 'template_shapes', 'validate_shapes']


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


def _abstract(template):
    """ShapeDtypeStruct tree mirroring ``template``'s GLOBAL shapes,
    dtypes and shardings — what StandardRestore targets. The shapes are
    global by construction (jax.Array.shape is the global shape
    whatever the mesh), which is what makes a checkpoint saved on N
    devices restorable onto M: only the sharding differs, and orbax
    re-lays the shards out to the template's mesh."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, 'sharding',
                                                        None)),
        template)


def template_shapes(template):
    """{'/'-joined leaf path: list(global shape)} for a template tree —
    recorded into the checkpoint meta at save so a later restore can
    validate GLOBAL shapes (never per-host/per-device ones) against the
    live state and name the exact offending leaf."""
    flat = jax.tree_util.tree_flatten_with_path(template)[0]
    out = {}
    for path, leaf in flat:
        key = '/'.join(str(getattr(p, 'key', getattr(p, 'idx', p)))
                       for p in path)
        out[key] = list(getattr(leaf, 'shape', ()))
    return out


def validate_shapes(saved_shapes, template):
    """Raise ValueError naming every leaf whose GLOBAL shape differs
    between the checkpoint meta (``saved_shapes``, from
    :func:`template_shapes` at save time) and the live ``template`` —
    BEFORE orbax touches anything, with a message that says which leaf
    and both shapes instead of an opaque restore failure. Leaves added
    or removed count as mismatches too."""
    live = template_shapes(template)
    bad = []
    for key in sorted(set(saved_shapes) | set(live)):
        s = saved_shapes.get(key)
        l = live.get(key)
        if s is None:
            bad.append('%s: not in the checkpoint (live %s)'
                       % (key, tuple(l)))
        elif l is None:
            bad.append('%s: not in the live state (saved %s)'
                       % (key, tuple(s)))
        elif list(s) != list(l):
            bad.append('%s: saved global shape %s vs live %s'
                       % (key, tuple(s), tuple(l)))
    if bad:
        raise ValueError('checkpoint/live global-shape mismatch — '
                         + '; '.join(bad))


def manager(directory, max_to_keep=None, save_interval_steps=1):
    """A CheckpointManager rooted at ``directory`` (created if needed).

    In a multi-host run every process must call this with the same
    directory (a path visible to all hosts); orbax coordinates the
    barrier/commit protocol across processes."""
    import os
    ocp = _ocp()
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep, save_interval_steps=save_interval_steps)
    return ocp.CheckpointManager(os.path.abspath(str(directory)),
                                 options=options)


def save(mngr, step, state, wait=True, meta=None):
    """Write ``state`` (a pytree of jax.Arrays — sharded arrays are
    written shard-parallel) at ``step``. ``meta`` (optional) is a
    JSON-serializable dict saved as a sidecar item inside the same
    atomic commit — restore it with :func:`restore_with_meta`.

    In a multi-process job every process calls this with the same step
    (the save IS a collective: each host writes only its own shards,
    orbax's primary writes the commit marker). With ``wait`` the return
    is additionally barriered across hosts: a truthy return means the
    commit is visible to EVERY host and the caller may certify the step
    (advance a last-good pointer, fire the corrupt-injection seam);
    ``False`` means the confirmation barrier timed out — some host may
    still be mid-write, and the caller must NOT certify this step."""
    ocp = _ocp()
    if meta is None:
        args = ocp.args.StandardSave(state)
    else:
        args = ocp.args.Composite(state=ocp.args.StandardSave(state),
                                  meta=ocp.args.JsonSave(meta))
    saved = mngr.save(int(step), args=args)
    if wait:
        mngr.wait_until_finished()
        from . import multihost as _mh
        # the PER-STEP attempt counter keeps the barrier name unique
        # when the same step is re-saved (coordination barriers are
        # one-shot) while self-healing across failures: a host whose
        # save raised never increments, but the next save is a
        # DIFFERENT step whose counter starts equal on every host — a
        # lifetime counter would stay sheared forever and turn every
        # later commit barrier into a timeout
        n = _save_attempts.get(int(step), 0) + 1
        _save_attempts[int(step)] = n
        if not _mh.barrier('ckpt.commit.%d.%d' % (int(step), n)):
            return False
    return saved


_save_attempts = {}


def restore(mngr, template, step=None):
    """Restore onto the shardings/dtypes of ``template`` (typically the
    freshly-initialised train state — its NamedShardings tell orbax
    where every shard belongs). ``step=None`` = latest."""
    ocp = _ocp()
    if step is None:
        step = mngr.latest_step()
    if step is None:
        raise FileNotFoundError('no checkpoint found in %s'
                                % mngr.directory)
    return mngr.restore(int(step),
                        args=ocp.args.StandardRestore(_abstract(template)))


def restore_with_meta(mngr, template, step):
    """Restore a :func:`save`-with-``meta`` step: returns
    ``(state, meta)`` with every array of ``state`` landed on its
    template entry's sharding (the JSON item needs no template)."""
    ocp = _ocp()
    r = mngr.restore(int(step), args=ocp.args.Composite(
        state=ocp.args.StandardRestore(_abstract(template)),
        meta=ocp.args.JsonRestore()))
    return r['state'], r['meta']


def restore_state(mngr, template, step):
    """Restore ONLY the array state of a save-with-``meta`` step onto
    ``template``'s shardings — the companion of :func:`read_meta` for
    callers that already validated the sidecar (one restore round-trip
    each instead of re-reading the JSON with the arrays)."""
    ocp = _ocp()
    r = mngr.restore(int(step), args=ocp.args.Composite(
        state=ocp.args.StandardRestore(_abstract(template))))
    return r['state']


def read_meta(mngr, step):
    """The JSON meta sidecar of one committed step, WITHOUT restoring
    any array state — the reshard-on-restore path reads the saving
    mesh + recorded global shapes first, validates them against the
    live template, and only then pays for the array restore."""
    ocp = _ocp()
    r = mngr.restore(int(step),
                     args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
    return r['meta']


def latest_step(mngr):
    return mngr.latest_step()


def all_steps(mngr):
    """Committed step ids, ascending (a step dir appears only after the
    atomic commit rename, so a crashed half-written save never lists)."""
    return sorted(int(s) for s in mngr.all_steps())


def delete_step(mngr, step):
    """Remove one committed step (replay-overwrite and stale-future
    cleanup in module/checkpointing.py)."""
    mngr.delete(int(step))


def wait(mngr):
    """Block until every in-flight async save has committed."""
    mngr.wait_until_finished()
