"""Sharded checkpoint / resume for SPMD training state.

The reference's checkpointing (model.py save_checkpoint, reference
model.py:340) gathers every parameter to one host — fine for one
machine, quadratically painful for a sharded multi-host run. This is
the TPU-native tier: orbax-checkpoint writes each host's shards of a
``jax.Array`` pytree in parallel and restores them onto the SAME mesh
sharding without ever materialising the full state anywhere.

    from mxnet_tpu.parallel import checkpoint as ckpt
    mngr = ckpt.manager('/path/ckpts', max_to_keep=3)
    ckpt.save(mngr, step, train_state)          # shard-parallel write
    state = ckpt.restore(mngr, template=train_state)   # same shardings
    step = mngr.latest_step()

Interop note: for reference-format `.params` files keep using
``mx.model.save_checkpoint`` / ``nd.save`` (docs/migration.md) — this
tier is for large sharded SPMD state, the two are complementary.
"""
import jax

__all__ = ['manager', 'save', 'restore', 'restore_with_meta',
           'latest_step', 'all_steps', 'delete_step', 'wait']


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


def manager(directory, max_to_keep=None, save_interval_steps=1):
    """A CheckpointManager rooted at ``directory`` (created if needed).

    In a multi-host run every process must call this with the same
    directory (a path visible to all hosts); orbax coordinates the
    barrier/commit protocol across processes."""
    import os
    ocp = _ocp()
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep, save_interval_steps=save_interval_steps)
    return ocp.CheckpointManager(os.path.abspath(str(directory)),
                                 options=options)


def save(mngr, step, state, wait=True, meta=None):
    """Write ``state`` (a pytree of jax.Arrays — sharded arrays are
    written shard-parallel) at ``step``. ``meta`` (optional) is a
    JSON-serializable dict saved as a sidecar item inside the same
    atomic commit — restore it with :func:`restore_with_meta`."""
    ocp = _ocp()
    if meta is None:
        args = ocp.args.StandardSave(state)
    else:
        args = ocp.args.Composite(state=ocp.args.StandardSave(state),
                                  meta=ocp.args.JsonSave(meta))
    saved = mngr.save(int(step), args=args)
    if wait:
        mngr.wait_until_finished()
    return saved


def restore(mngr, template, step=None):
    """Restore onto the shardings/dtypes of ``template`` (typically the
    freshly-initialised train state — its NamedShardings tell orbax
    where every shard belongs). ``step=None`` = latest."""
    ocp = _ocp()
    if step is None:
        step = mngr.latest_step()
    if step is None:
        raise FileNotFoundError('no checkpoint found in %s'
                                % mngr.directory)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, 'sharding',
                                                        None)),
        template)
    return mngr.restore(int(step),
                        args=ocp.args.StandardRestore(abstract))


def restore_with_meta(mngr, template, step):
    """Restore a :func:`save`-with-``meta`` step: returns
    ``(state, meta)`` with every array of ``state`` landed on its
    template entry's sharding (the JSON item needs no template)."""
    ocp = _ocp()
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, 'sharding',
                                                        None)),
        template)
    r = mngr.restore(int(step), args=ocp.args.Composite(
        state=ocp.args.StandardRestore(abstract),
        meta=ocp.args.JsonRestore()))
    return r['state'], r['meta']


def latest_step(mngr):
    return mngr.latest_step()


def all_steps(mngr):
    """Committed step ids, ascending (a step dir appears only after the
    atomic commit rename, so a crashed half-written save never lists)."""
    return sorted(int(s) for s in mngr.all_steps())


def delete_step(mngr, step):
    """Remove one committed step (replay-overwrite and stale-future
    cleanup in module/checkpointing.py)."""
    mngr.delete(int(step))


def wait(mngr):
    """Block until every in-flight async save has committed."""
    mngr.wait_until_finished()
