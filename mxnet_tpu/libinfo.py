"""Library locator + version (reference python/mxnet/libinfo.py —
the single source of the version, imported by __init__).

find_lib_path() resolves the native runtime libraries this framework
builds: the C ABI `libmxnet_tpu.so` (lib/) and the runtime
`libmxtpu.so` (built on demand by _native.py next to the package).
"""
import os

__all__ = ['find_lib_path', '__version__']

__version__ = '0.1.0'


def find_lib_path():
    """Paths of the native libraries that exist on disk, C ABI first
    (reference returns the mxnet shared library path list; raises if
    nothing is found and MXTPU_LIBRARY_PATH doesn't point anywhere)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates = [
        os.environ.get('MXTPU_LIBRARY_PATH', ''),
        os.path.join(repo, 'lib', 'libmxnet_tpu.so'),
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     'libmxtpu.so'),  # _native.py's build target (_SO)
    ]
    found = [p for p in candidates if p and os.path.isfile(p)]
    if not found:
        raise RuntimeError(
            'no native library found; mxnet_tpu._native.get_lib() '
            'builds the runtime on demand, or set MXTPU_LIBRARY_PATH '
            '(searched: %s)' % [p for p in candidates if p])
    return found
