"""KVStore — key-value parameter store with device/dist tiers.

Reference: include/mxnet/kvstore.h:45-394 + src/kvstore/ (KVStoreLocal
kvstore_local.h:49, Comm device tier comm.h:40, KVStoreDist kvstore_dist.h:52)
+ python/mxnet/kvstore.py:76.

TPU-native mapping (SURVEY.md §5.8): the reference's device tier is
reduce-to-one-GPU + broadcast; here the aggregation happens as one fused XLA
computation on the merge device, and when the caller is inside a pjit'd step
the same API lowers to jax.lax.psum over the mesh (parallel/collectives.py).
The dist tier (multi-host parameter server over ZMQ in the reference) is
provided by kvstore_dist.py over TCP sockets with the same worker/server/
scheduler role split (DMLC_ROLE env protocol preserved).
"""
import os
import pickle

import numpy as np

import jax

from . import faults as _faults
from . import optimizer as opt
from . import telemetry as _tele
from .ndarray import NDArray, zeros
from .base import MXNetError

__all__ = ['KVStore', 'create']


def _tele_bytes(counter_name, values):
    """Account logical payload bytes for a push/pull value list (flat
    list or list-of-lists of NDArrays) into a telemetry counter.
    Returns the byte total (the dist tier derives its host-side
    throughput gauges from it)."""
    total = 0
    for v in values:
        for a in (v if isinstance(v, (list, tuple)) else [v]):
            try:
                total += int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
            except Exception:  # noqa: BLE001 — exotic sparse/host types
                pass
    _tele.counter(counter_name).inc(total)
    return total


def _ctx_group_key(arrs):
    return tuple(id(a) for a in arrs)


class KVStore:
    """Reference kvstore.py:76 — Init/Push/Pull over string or int keys."""

    def __init__(self, kv_type='local'):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._str_keys = {}

    # -- lifecycle --------------------------------------------------------
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            vv = v[0] if isinstance(v, (list, tuple)) else v
            self._store[k] = vv.copy() if isinstance(vv, NDArray) else vv

    def push(self, key, value, priority=0):
        """Reduce value(s) per key; run updater or store the merged grad
        (reference kvstore_local.h:149 PushImpl)."""
        with _tele.span('kvstore.push', 'kvstore'):
            if _faults.enabled():
                # dispatch-exception seam: the grad push that would
                # train the current step
                _faults.maybe_raise('kvstore')
            keys, values = _key_value(key, value)
            if _tele.enabled():
                _tele_bytes('kvstore.push_bytes', values)
            for k, vlist in zip(keys, values):
                if not isinstance(vlist, (list, tuple)):
                    vlist = [vlist]
                merged = self._reduce(vlist)
                if self._updater is not None:
                    self._updater(_updater_key(k), merged, self._store[k])
                else:
                    self._store[k] = merged

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value to out array(s) (kvstore_local.h:188)."""
        assert out is not None
        with _tele.span('kvstore.pull', 'kvstore'):
            keys, outs = _key_value(key, out)
            if _tele.enabled():
                _tele_bytes('kvstore.pull_bytes', outs)
            for k, olist in zip(keys, outs):
                if not isinstance(olist, (list, tuple)):
                    olist = [olist]
                src = self._store[k]
                for o in olist:
                    # cast to the destination's dtype (reference
                    # CopyFromTo): with multi-precision optimizers the
                    # store/updater holds fp32 masters while executors
                    # stay bound in bf16
                    o._data = jax.device_put(
                        src._data.astype(o._data.dtype),
                        o.context.jax_device())

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Reference kvstore_local.h:203 PullRowSparseImpl."""
        from .ndarray.sparse import RowSparseNDArray, row_sparse_array, retain
        assert out is not None and row_ids is not None
        keys, outs = _key_value(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, olist, rids in zip(keys, outs, row_ids if isinstance(row_ids, list) else [row_ids]):
            if not isinstance(olist, (list, tuple)):
                olist = [olist]
            src = self._store[k]
            if isinstance(src, RowSparseNDArray):
                res = retain(src, rids)
            else:
                rows = rids.asnumpy().astype(np.int64)
                res = row_sparse_array((src[rows], rows), shape=src.shape)
            for o in olist:
                if isinstance(o, RowSparseNDArray):
                    o.data, o.indices = res.data, res.indices
                else:
                    res.copyto(o)

    def _reduce(self, vlist):
        """Device-tier reduce (comm.h CommDevice::Reduce :477): gather the
        shards onto the merge device and let XLA sum them in one kernel."""
        from .ndarray.sparse import BaseSparseNDArray
        if isinstance(vlist[0], BaseSparseNDArray):
            dense = [v.tostype('default') for v in vlist]
            vlist = dense
        if len(vlist) == 1:
            return vlist[0].copy()
        dev = vlist[0].context.jax_device()
        import jax.numpy as jnp
        total = vlist[0]._data
        for v in vlist[1:]:
            total = total + jax.device_put(v._data, dev)
        out = NDArray(total, vlist[0].context)
        return out

    # -- optimizer plumbing ----------------------------------------------
    def set_updater(self, updater):
        """Reference kvstore.py:460 _set_updater."""
        self._updater = updater

    def set_optimizer(self, optimizer):
        """Reference kvstore.py:349 — on dist, ships the pickled optimizer to
        the servers; locally installs it as the updater."""
        self._optimizer = optimizer
        self.set_updater(opt.get_updater(optimizer))

    # -- cluster topology (single-process defaults) -----------------------
    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        pass

    def _barrier(self):
        self.barrier()

    def _send_command_to_servers(self, head, body):
        pass

    def num_dead_node(self, node_id=6, timeout=60):
        """Reference kvstore.h:321-330 get_num_dead_node — always 0 for
        single-process stores; the dist tier overrides with heartbeat
        tracking."""
        return 0

    # -- optimizer state checkpointing (reference kvstore.py:433) ---------
    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, 'Cannot save states for distributed training'
        with open(fname, 'wb') as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, 'Cannot load states for distributed training'
        with open(fname, 'rb') as fin:
            self._updater.set_states(fin.read())


def _updater_key(k):
    if isinstance(k, str) and k.isdigit():
        return int(k)
    return k


def _key_value(key, value):
    if isinstance(key, (str, int)):
        return [key], [value]
    assert len(key) == len(value)
    return list(key), list(value)


def create(name='local'):
    """Reference kvstore.cc:34-60 factory: local | device | dist_sync |
    dist_device_sync | dist_async."""
    if not isinstance(name, str):
        raise TypeError('name must be a string')
    if 'dist' in name:
        from .kvstore_dist import KVStoreDist
        return KVStoreDist(name)
    if name in ('local', 'device', 'local_allreduce_cpu',
                'local_allreduce_device'):
        return KVStore(name)
    raise MXNetError('unknown KVStore type %s' % name)
