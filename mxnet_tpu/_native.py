"""Loader for the native runtime library (src/*.cc → libmxtpu.so).

The reference ships its native core as libmxnet.so loaded by
python/mxnet/base.py (_load_lib); here the native layer is the host-side
runtime — dependency engine, pooled storage, RecordIO, profiler — and
this module finds or builds it, then exposes ctypes bindings. Pure-Python
fallbacks exist for every feature, so a missing compiler degrades
gracefully (LIB is None and callers check :func:`available`).
"""
import ctypes
import os
import subprocess
import threading

__all__ = ['get_lib', 'available', 'check_call', 'NativeError']

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(_DIR), 'src')
_SO = os.path.join(_DIR, 'libmxtpu.so')
_SOURCES = ('engine.cc', 'storage.cc', 'recordio.cc', 'profiler.cc')

_lock = threading.Lock()
_lib = None
_tried = False


class NativeError(RuntimeError):
    pass


def _stale():
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    for f in _SOURCES + ('mxtpu.h',):
        p = os.path.join(_SRC, f)
        if os.path.exists(p) and os.path.getmtime(p) > so_mtime:
            return True
    return False


def _build():
    srcs = [os.path.join(_SRC, f) for f in _SOURCES]
    cmd = ['g++', '-std=c++17', '-O2', '-fPIC', '-Wall', '-pthread',
           '-shared', '-o', _SO] + srcs
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _bind(lib):
    import ctypes as C
    lib.MXTGetLastError.restype = C.c_char_p
    lib.MXTNowUS.restype = C.c_int64
    protos = {
        'MXTEngineCreate': [C.c_int, C.POINTER(C.c_void_p)],
        'MXTEngineFree': [C.c_void_p],
        'MXTEngineNewVar': [C.c_void_p, C.POINTER(C.c_void_p)],
        'MXTEngineDeleteVar': [C.c_void_p, C.c_void_p],
        'MXTEnginePushSync': [C.c_void_p, C.c_void_p, C.c_void_p,
                              C.POINTER(C.c_void_p), C.c_int,
                              C.POINTER(C.c_void_p), C.c_int,
                              C.c_int, C.c_char_p],
        'MXTEnginePushAsync': [C.c_void_p, C.c_void_p, C.c_void_p,
                               C.POINTER(C.c_void_p), C.c_int,
                               C.POINTER(C.c_void_p), C.c_int,
                               C.c_int, C.c_char_p],
        'MXTEngineOprComplete': [C.c_void_p],
        'MXTEngineWaitForVar': [C.c_void_p, C.c_void_p],
        'MXTEngineWaitForAll': [C.c_void_p],
        'MXTEnginePendingOps': [C.c_void_p, C.POINTER(C.c_int64)],
        'MXTStorageAlloc': [C.c_size_t, C.POINTER(C.c_void_p)],
        'MXTStorageFree': [C.c_void_p],
        'MXTStorageDirectFree': [C.c_void_p],
        'MXTStorageReleaseAll': [],
        'MXTStorageStats': [C.POINTER(C.c_int64)],
        'MXTRecordIOWriterCreate': [C.c_char_p, C.POINTER(C.c_void_p)],
        'MXTRecordIOWriterWrite': [C.c_void_p, C.c_char_p, C.c_size_t],
        'MXTRecordIOWriterTell': [C.c_void_p, C.POINTER(C.c_size_t)],
        'MXTRecordIOWriterFree': [C.c_void_p],
        'MXTRecordIOReaderCreate': [C.c_char_p, C.POINTER(C.c_void_p)],
        'MXTRecordIOReaderNext': [C.c_void_p, C.POINTER(C.c_void_p),
                                  C.POINTER(C.c_size_t)],
        'MXTRecordIOReaderSeek': [C.c_void_p, C.c_size_t],
        'MXTRecordIOReaderTell': [C.c_void_p, C.POINTER(C.c_size_t)],
        'MXTRecordIOReaderFree': [C.c_void_p],
        'MXTProfilerSetState': [C.c_int],
        'MXTProfilerAddEvent': [C.c_char_p, C.c_char_p, C.c_int64, C.c_int64],
        'MXTProfilerDump': [C.c_char_p],
    }
    for name, argtypes in protos.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        if name not in ('MXTGetLastError', 'MXTNowUS'):
            fn.restype = C.c_int
    return lib


def get_lib():
    """The loaded CDLL, building it first if needed; None if unavailable.

    Disable with MXTPU_NO_NATIVE=1 (forces the pure-Python fallbacks)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        from .config import flags as _flags
        if _flags.get('MXTPU_NO_NATIVE'):
            return None
        try:
            if _stale():
                _build()
            _lib = _bind(ctypes.CDLL(_SO))
        except Exception:
            _lib = None
        return _lib


def available():
    return get_lib() is not None


def check_call(ret):
    """Raise NativeError with MXTGetLastError on nonzero return
    (reference base.py check_call)."""
    if ret != 0:
        lib = get_lib()
        msg = lib.MXTGetLastError().decode() if lib else 'native call failed'
        raise NativeError(msg)


# ctypes callback types matching src/mxtpu.h
SYNC_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
ASYNC_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)
