"""Dynamic batcher: queue, coalesce, dispatch, split — continuously.

Serving throughput on an accelerator comes from batch width, but
requests arrive one at a time. The batcher closes the gap the way
production inference servers do (continuous batching): every request
enters a thread-safe queue; the dispatcher holds the OLDEST request at
most ``MXTPU_SERVE_MAX_WAIT_MS`` while later arrivals coalesce behind
it, and fires as soon as the coalesced rows fill the engine's largest
warm bucket — whichever comes first. One padded device call serves the
whole batch; the outputs are split back per request, pad rows already
stripped by the engine.

Continuous, not lockstep: the device dispatch is asynchronous and the
blocking device->host fetch runs on a one-thread side pool (the same
pattern ``module/window_pipeline.py`` uses for the pipelined window
upload), so the dispatcher is back at the queue collecting the NEXT
batch while the current one is still computing on device — new
arrivals board the next dispatch mid-flight instead of waiting for the
previous one to land.

Metrics (through the existing telemetry registry, so they surface on
``/metrics`` and in ``tools/telemetry_watch.py``): the
``serve.request_latency`` histogram (enqueue -> answer, ms; p99
published as the ``serve.request_latency_p99_ms`` gauge, exemplar
trace ids attached), the ``serve.queue_wait`` histogram (enqueue ->
dispatcher pop, ms; p50 published as ``serve.queue_wait_p50_ms``),
``serve.queue_depth`` / ``serve.batch_size`` / ``serve.pad_fraction``
gauges, ``serve.batch_size_p50`` (recent-window), and the
``serve.requests`` / ``serve.errors`` / ``serve.dispatches`` /
``serve.rows`` / ``serve.pad_rows`` counters.

Tracing (telemetry/trace.py, rides MXTPU_TELEMETRY): every submitted
request carries a RequestTrace (client-supplied id or minted) that
accumulates the stage breakdown — queue_wait (per request), coalesce /
pad / dispatch / fetch / split (batch-shared) — and lands as a
``trace`` JSONL record; the N requests of one coalesced dispatch share
ONE dispatch span id. Completed requests also feed the SLO plane
(telemetry/slo.py): latency per request, and dispatch/fetch failures
as the 5xx the error budget measures (client-side rejects in submit
never burn budget). Telemetry off = no trace object, no SLO state —
the host-side queue_wait/stage logs (plain deques, like dispatch_log)
are the only unconditional bookkeeping, and the bench reads those.
"""
import collections
import logging
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from .. import telemetry as _tele
from ..telemetry import slo as _slo
from ..telemetry import trace as _trace

__all__ = ['DynamicBatcher']


def _serve_max_wait_s():
    from ..config import flags
    flags.reload('MXTPU_SERVE_MAX_WAIT_MS')
    return flags.get('MXTPU_SERVE_MAX_WAIT_MS') / 1e3


class _Request:
    __slots__ = ('arrays', 'rows', 'future', 't0', 'trace', 'queue_ms')

    def __init__(self, arrays, rows, trace=None):
        self.arrays = arrays
        self.rows = rows
        self.future = Future()
        self.t0 = time.monotonic()
        self.trace = trace       # RequestTrace or None (telemetry off)
        self.queue_ms = None     # stamped when the dispatcher pops it


class DynamicBatcher:
    """Coalescing request queue in front of one :class:`ServingEngine`.

    ``submit`` may be called before :meth:`start` (requests queue up
    and dispatch once the loop runs — how the deterministic coalescing
    tests drive it) and from any number of threads after.
    """

    def __init__(self, engine, max_wait_ms=None, logger=logging):
        self.engine = engine
        self.max_wait = (max_wait_ms / 1e3 if max_wait_ms is not None
                         else _serve_max_wait_s())
        self.max_rows = engine.buckets[-1]
        self.logger = logger
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._running = False
        self._closed = False
        self._thread = None
        # one worker keeps completions ordered; the blocking fetch of
        # dispatch k runs here while the dispatcher coalesces k+1
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix='mxtpu-serve-fetch')
        self._inflight = collections.deque()
        self._recent_batches = collections.deque(maxlen=256)
        # (rows, bucket_rows, n_requests) per dispatch — the test/debug
        # ledger proving requests actually coalesced
        self.dispatch_log = collections.deque(maxlen=1024)
        # per-request queue waits (ms) + per-dispatch stage timings —
        # host clock reads only, kept unconditionally like dispatch_log
        # so the bench can bank the breakdown without telemetry
        self.queue_wait_log = collections.deque(maxlen=4096)
        self.stage_log = collections.deque(maxlen=1024)

    # -- client API --------------------------------------------------------
    def submit(self, arrays, trace_id=None):
        """Enqueue one request (list of per-input arrays sharing a row
        count, or a single array). Returns a Future resolving to the
        list of output arrays for exactly those rows. ``trace_id``
        seeds the request's trace (client-supplied X-Request-Id /
        traceparent); with telemetry on and none given, one is minted —
        telemetry off mints nothing."""
        arrays, rows = self.engine._check_and_cast(arrays)
        req = _Request(arrays, rows, trace=_trace.start(trace_id,
                                                        rows=rows))
        with self._cond:
            if self._closed:
                # after close() no dispatcher will ever serve the queue
                # — fail fast instead of stranding the future forever
                # (an HTTP handler thread can race ServingServer.stop)
                raise RuntimeError('batcher closed')
            self._queue.append(req)
            _tele.gauge('serve.queue_depth').set(len(self._queue))
            self._cond.notify_all()
        return req.future

    def predict(self, arrays, timeout=None, trace_id=None):
        """submit + wait — the synchronous client call."""
        return self.submit(arrays,
                           trace_id=trace_id).result(timeout=timeout)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name='mxtpu-serve-batcher',
                                        daemon=True)
        self._thread.start()
        return self

    def close(self, drain=True):
        """Stop the dispatcher. ``drain=True`` (default) serves every
        request queued before the close; anything else — including a
        submit that raced past the dispatcher's exit — fails with
        RuntimeError instead of hanging its caller."""
        with self._cond:
            self._running = False
            if not drain:
                stranded, self._queue = list(self._queue), \
                    collections.deque()
            else:
                stranded = []
            self._cond.notify_all()
        for req in stranded:
            req.future.set_exception(RuntimeError('batcher closed'))
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        with self._cond:
            # seal the queue AFTER the dispatcher exits: later submits
            # raise, and whatever slipped in between the drain and the
            # thread's exit is failed here, never silently stranded
            self._closed = True
            stranded, self._queue = list(self._queue), \
                collections.deque()
        for req in stranded:
            req.future.set_exception(RuntimeError('batcher closed'))
        while self._inflight:
            try:
                self._inflight.popleft().result(timeout=30)
            except Exception:  # noqa: BLE001 — request futures carry it
                pass
        self._fetch_pool.shutdown(wait=True)

    # -- the dispatcher ----------------------------------------------------
    def _collect(self):
        """Block until a batch is ready (coalesce up to the largest
        bucket or max-wait from the OLDEST request), then pop it.
        Returns (requests, rows) or (None, 0) at shutdown."""
        with self._cond:
            while self._running and not self._queue:
                self._cond.wait(0.05)
            if not self._queue:
                return None, 0
            deadline = self._queue[0].t0 + self.max_wait
            while self._running:
                rows = sum(r.rows for r in self._queue)
                if rows >= self.max_rows:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch, rows = [], 0
            while self._queue:
                r = self._queue[0]
                if batch and rows + r.rows > self.max_rows:
                    break          # r boards the NEXT dispatch
                batch.append(self._queue.popleft())
                rows += r.rows
            _tele.gauge('serve.queue_depth').set(len(self._queue))
            return batch, rows

    def _loop(self):
        while True:
            batch, rows = self._collect()
            if batch is None:
                return
            self._dispatch(batch, rows)

    def _fail_batch(self, batch, e):
        """Answer every passenger of a failed dispatch: exception on
        the future, an error-status trace record, and one bad request
        against the SLO error budget (these are the 5xx the budget
        measures; client-side rejects never reach a batch)."""
        _tele.counter('serve.errors').inc(len(batch))
        now = time.monotonic()
        for r in batch:
            r.future.set_exception(e)
            _slo.note_request((now - r.t0) * 1e3, error=True)
            if r.trace is not None:
                r.trace.finish(status='error')

    def _dispatch(self, batch, rows):
        # queue_wait: enqueue -> the dispatcher owning the request
        # (includes the coalesce hold on the oldest passenger)
        t_pop = time.monotonic()
        for r in batch:
            r.queue_ms = (t_pop - r.t0) * 1e3
            self.queue_wait_log.append(r.queue_ms)
        timings = {}
        try:
            n_in = len(batch[0].arrays)
            t0 = time.perf_counter()
            arrays = [np.concatenate([r.arrays[i] for r in batch])
                      if len(batch) > 1 else batch[0].arrays[i]
                      for i in range(n_in)]
            timings['coalesce_ms'] = (time.perf_counter() - t0) * 1e3
            chunks = self.engine.dispatch_rows(arrays, timings=timings)
        except Exception as e:  # noqa: BLE001 — answer, don't die
            self._fail_batch(batch, e)
            return
        bucket_rows = sum(b for _, _, b in chunks)
        self.dispatch_log.append((rows, bucket_rows, len(batch)))
        self._recent_batches.append(rows)
        _tele.counter('serve.dispatches').inc()
        _tele.counter('serve.rows').inc(rows)
        _tele.counter('serve.pad_rows').inc(bucket_rows - rows)
        _tele.gauge('serve.batch_size').set(rows)
        rb = sorted(self._recent_batches)
        _tele.gauge('serve.batch_size_p50').set(rb[len(rb) // 2])
        _tele.gauge('serve.pad_fraction').set(
            round((bucket_rows - rows) / float(bucket_rows), 4))
        # ONE dispatch span id shared by every passenger's trace — the
        # coalescing structure survives into the per-request records
        if any(r.trace is not None for r in batch):
            timings['dispatch_span'] = _trace.new_span_id()
        # hand the blocking fetch to the side thread and go collect the
        # next batch — arrivals during device compute board dispatch k+1
        self._inflight.append(
            self._fetch_pool.submit(self._complete, batch, chunks,
                                    timings))
        while self._inflight and self._inflight[0].done():
            self._inflight.popleft()

    def _complete(self, batch, chunks, timings):
        try:
            outs = self.engine.fetch_chunks(chunks, timings=timings)
        except Exception as e:  # noqa: BLE001
            self._fail_batch(batch, e)
            return
        t0 = time.perf_counter()
        hist = _tele.histogram('serve.request_latency')
        qhist = _tele.histogram('serve.queue_wait')
        off = 0
        for r in batch:
            r.future.set_result([o[off:off + r.rows] for o in outs])
            off += r.rows
        timings['split_ms'] = (time.perf_counter() - t0) * 1e3
        self.stage_log.append(dict(timings, rows=sum(r.rows
                                                     for r in batch),
                                   requests=len(batch)))
        dispatch_span = timings.get('dispatch_span')
        now = time.monotonic()
        for r in batch:
            lat_ms = (now - r.t0) * 1e3
            hist.observe(lat_ms,
                         exemplar={'trace_id': r.trace.trace_id}
                         if r.trace is not None else None)
            if r.queue_ms is not None:
                qhist.observe(r.queue_ms)
            _slo.note_request(lat_ms, error=False)
            if r.trace is not None:
                # per-request queue wait + the batch-shared stages, all
                # pointing at the ONE dispatch span
                r.trace.add('queue_wait', r.queue_ms or 0.0)
                r.trace.add_shared(dispatch_span, timings)
                r.trace.finish(status='ok')
        _tele.counter('serve.requests').inc(len(batch))
        p99 = hist.percentile(99)
        if p99 is not None:
            _tele.gauge('serve.request_latency_p99_ms').set(round(p99, 3))
        q50 = qhist.percentile(50)
        if q50 is not None:
            _tele.gauge('serve.queue_wait_p50_ms').set(round(q50, 3))
        _tele.watchdog.note_progress('serve.dispatch')
