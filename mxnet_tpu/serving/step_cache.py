"""O(1) autoregressive serving: device-resident per-session state.

A recurrent decode served naively re-runs the whole prefix per token —
O(T) compute and, worse, a fresh program shape per prefix length.
Compiler-first autoregressive caching (arXiv:2603.09555) keeps the
carried state DEVICE-RESIDENT between steps instead, so serving
dispatches one fixed-shape step program per token batch: O(1) compute,
zero recompiles after warmup.

Two pieces:

- :class:`StepCache` — the session table: per-session hidden-state
  slots live in a ring of device arrays ((capacity,) + state_shape,
  ``MXTPU_SERVE_SESSIONS`` slots), mapped session-id -> slot on the
  host and evicted LRU. Slot ``capacity`` is a scratch row pad rows
  scatter into, so padding never corrupts a live session.
- :class:`DecodeEngine` — the step dispatcher: a bound Module whose
  graph is ONE recurrent step (state inputs among its data, new states
  as its trailing outputs — the ``mx.rnn`` cell ``__call__`` shape)
  compiles to one program per batch bucket that gathers the batch's
  state rows from the ring, runs the step, and scatters the new state
  back — the ring is DONATED to the program, so the update is in
  place. Fresh sessions (first token, or re-admitted after an LRU
  eviction) start from zero state via an in-graph mask; the host never
  touches state bytes.

The step-symbol contract: ``state_names`` are data inputs of the bound
module (build the reference module with
``data_names=('data', 'state_h', ...)``), and the graph's LAST
``len(state_names)`` outputs are the new states in the same order —
exactly what ``mx.sym.Group([out] + new_states)`` over an rnn/lstm
cell produces (docs/serving.md walks through it).
"""
import collections
import logging
import threading

import numpy as np

import jax.numpy as jnp

from .. import random as _random
from .. import telemetry as _tele
from .engine import _SingleExecutorEngine, bucket_ladder

__all__ = ['StepCache', 'DecodeEngine']


def _serve_sessions():
    from ..config import flags
    flags.reload('MXTPU_SERVE_SESSIONS')
    return flags.get('MXTPU_SERVE_SESSIONS')


class StepCache:
    """Session-id -> ring-slot table with LRU eviction.

    The device arrays themselves belong to :class:`DecodeEngine` (they
    are donated through the step program); this class owns only the
    host-side mapping, so it is cheap to test in isolation.
    """

    def __init__(self, capacity):
        self.capacity = int(capacity)
        assert self.capacity >= 1
        self._slots = collections.OrderedDict()   # session -> slot (LRU)
        self._free = list(range(self.capacity - 1, -1, -1))
        self.evictions = 0
        self._lock = threading.Lock()

    def lookup(self, session_ids):
        """(slots, fresh) for a batch of session ids: ``slots`` the
        int32 ring rows, ``fresh`` True where the session has no cached
        state (new, or LRU-evicted since its last step — it must
        restart from zero state). Touch order is LRU."""
        slots = np.empty(len(session_ids), np.int32)
        fresh = np.zeros(len(session_ids), bool)
        with self._lock:
            if len(set(session_ids)) != len(session_ids):
                raise ValueError('duplicate session ids in one batch')
            for i, sid in enumerate(session_ids):
                slot = self._slots.pop(sid, None)
                if slot is None:
                    fresh[i] = True
                    if self._free:
                        slot = self._free.pop()
                    else:
                        evicted, slot = self._slots.popitem(last=False)
                        _tele.counter('serve.session_evictions').inc()
                        self.evictions += 1
                self._slots[sid] = slot        # most-recently-used end
                slots[i] = slot
            _tele.gauge('serve.sessions_live').set(len(self._slots))
            # the memory plane's serving-pressure view: live sessions
            # and cumulative evictions as gauges (an eviction-heavy
            # cache under a flat session count reads as churn)
            _tele.gauge('serve.sessions').set(len(self._slots))
            _tele.gauge('serve.evictions').set(self.evictions)
        return slots, fresh

    def drop(self, session_id):
        """Explicitly end a session (its slot frees immediately)."""
        with self._lock:
            slot = self._slots.pop(session_id, None)
            if slot is not None:
                self._free.append(slot)
            _tele.gauge('serve.sessions_live').set(len(self._slots))
            _tele.gauge('serve.sessions').set(len(self._slots))
        return slot is not None

    def sessions(self):
        with self._lock:
            return list(self._slots)


class DecodeEngine(_SingleExecutorEngine):
    """Fixed-shape recurrent decode steps over a StepCache ring."""

    _default_name = 'decoder'

    def __init__(self, module, state_names, capacity=None, max_batch=None,
                 logger=logging, name=None):
        super().__init__(module, logger=logger, name=name)
        self.state_names = list(state_names)
        missing = [n for n in self.state_names if n not in self._descs]
        if missing:
            raise ValueError('state inputs %s are not data inputs of the '
                             'bound module' % missing)
        self._token_names = [n for n in module._data_names
                             if n not in self.state_names]
        n_out = len(module._output_names)
        if n_out <= len(self.state_names):
            raise ValueError('the step graph must output its payload '
                             'plus one new state per state input (last '
                             '%d outputs)' % len(self.state_names))
        self.n_payload = n_out - len(self.state_names)
        self.capacity = int(capacity) if capacity else _serve_sessions()
        max_b = int(max_batch) if max_batch else min(self.capacity, 32)
        self.buckets = [b for b in bucket_ladder(max_b)
                        if b <= self.capacity] or [1]
        self._reset_ring()
        self._lock = threading.Lock()    # decode serializes: the ring
                                         # is donated through each step

    def _reset_ring(self):
        """(Re)build the device state ring: one slot per session + a
        scratch row (index ``capacity``) that pad rows harmlessly
        scatter into. Also called after a failed step dispatch — the
        ring was DONATED into the failed program, so the old buffers
        may already be consumed; every session restarts from zero
        state, exactly the LRU-eviction semantics."""
        descs = self._descs
        self._store = [
            jnp.zeros((self.capacity + 1,) + tuple(descs[n].shape[1:]),
                      self._desc_dtype(n))
            for n in self.state_names]
        if self._mesh is not None:
            from ..module.window_pipeline import place_replicated
            (self._store,) = place_replicated(self._mesh, self._store)
            self._store = list(self._store)
        self.cache = StepCache(self.capacity)
        # the device-resident session ring's footprint — serving's
        # standing claim on HBM, next to mem.* in the memory plane
        _tele.gauge('serve.ring_bytes').set(
            int(sum(int(s.nbytes) for s in self._store)))

    # -- program -----------------------------------------------------------
    def _build_program(self, bucket):
        run = self._run
        arg_pos = {n: i for i, n in enumerate(self._arg_names)}
        token_names, state_names = self._token_names, self.state_names
        io_pos = set(arg_pos[n] for n in token_names + state_names)
        fixed_names = [n for i, n in enumerate(self._arg_names)
                       if i not in io_pos]
        n_payload = self.n_payload

        def step(fixed, aux, store, slots, fresh, tokens, key):
            states = []
            for s in store:
                st = s[slots]                       # gather (b, ...)
                mask = fresh.reshape((-1,) + (1,) * (st.ndim - 1))
                states.append(jnp.where(mask, jnp.zeros_like(st), st))
            full = [None] * len(arg_pos)
            for n, v in zip(fixed_names, fixed):
                full[arg_pos[n]] = v
            for n, v in zip(token_names, tokens):
                full[arg_pos[n]] = v
            for n, v in zip(state_names, states):
                full[arg_pos[n]] = v
            outs, _ = run(tuple(full), aux, key, False)
            payload, new_states = outs[:n_payload], outs[n_payload:]
            # scatter the new state back into the (donated) ring; pad
            # rows all target the scratch slot, whose value is dead
            store = tuple(s.at[slots].set(ns.astype(s.dtype))
                          for s, ns in zip(store, new_states))
            return tuple(payload), store

        from ..module.window_pipeline import registered_jit
        prog = registered_jit('serve.decode[%s][b%d]' % (self.name, bucket),
                              step, donate_argnums=(2,))
        return prog, fixed_names

    # -- the decode step ---------------------------------------------------
    def decode(self, session_ids, arrays, reset=False, timings=None):
        """One recurrent step for a batch of sessions: ``arrays`` are
        the token inputs (row i belongs to ``session_ids[i]``), the
        carried state comes from / returns to the device ring. Returns
        the payload outputs as host arrays, one row per session.
        ``reset=True`` restarts every named session from zero state.
        ``timings`` (a dict, optional) accumulates the host-measured
        ``pad_ms`` / ``dispatch_ms`` / ``fetch_ms`` the same way
        :meth:`ServingEngine.dispatch_rows` does, so a decode-serving
        driver can attach the breakdown to its request traces."""
        if not isinstance(arrays, (list, tuple)):
            arrays = [arrays]
        rows = len(session_ids)
        if rows == 0:
            raise ValueError('empty session batch')
        if rows > self.buckets[-1]:
            raise ValueError('decode batch %d exceeds the largest bucket '
                             '%d' % (rows, self.buckets[-1]))
        if len(arrays) != len(self._token_names):
            raise ValueError('expected %d token inputs (%s)'
                             % (len(self._token_names),
                                ', '.join(self._token_names)))
        # validate + stage the token arrays BEFORE the session table is
        # touched: a rejected call must not register/evict sessions (a
        # retry would otherwise find fresh=False and read a reused
        # slot's leftover state)
        import time as _time
        bucket = next(b for b in self.buckets if b >= rows)
        pad = bucket - rows
        t_pad0 = _time.perf_counter()
        host_tokens = []
        for n, a in zip(self._token_names, arrays):
            desc = self._descs[n]
            a = np.asarray(a, dtype=self._desc_dtype(n))
            if a.shape[0] != rows or \
                    tuple(a.shape[1:]) != tuple(desc.shape[1:]):
                raise ValueError('token input %r: shape %s does not '
                                 'match %d rows of %s'
                                 % (n, a.shape, rows,
                                    tuple(desc.shape[1:])))
            if pad:
                a = np.concatenate(
                    [a, np.zeros((pad,) + a.shape[1:], a.dtype)])
            host_tokens.append(a)
        if timings is not None:
            timings['pad_ms'] = timings.get('pad_ms', 0.0) \
                + (_time.perf_counter() - t_pad0) * 1e3
        with self._lock:
            slots, fresh = self.cache.lookup(session_ids)
            # everything past the lookup runs under the failure guard:
            # the table is mutated now, so ANY later failure (program
            # build, snapshot/placement transfer, the dispatch itself)
            # must rebuild ring + table together — otherwise a retried
            # session would find fresh=False and gather an evicted
            # session's leftover state from its reused slot
            try:
                if reset:
                    fresh[:] = True
                prog, fixed_names = self._program(bucket)
                fixed, aux = self._snapshot(fixed_names)
                slots_b = np.concatenate(
                    [slots, np.full(pad, self.capacity, np.int32)]) \
                    if pad else slots
                fresh_b = np.concatenate([fresh, np.ones(pad, bool)]) \
                    if pad else fresh
                # device_put takes the host arrays directly — one
                # transfer, not a default-device stage + re-place
                tokens = tuple(self._place(a) for a in host_tokens)
                t_disp0 = _time.perf_counter()
                with _tele.span('serve.decode', 'serve'):
                    payload, store = prog(fixed, aux, tuple(self._store),
                                          self._place(slots_b),
                                          self._place(fresh_b),
                                          tokens, _random.next_key())
                if timings is not None:
                    timings['dispatch_ms'] = \
                        timings.get('dispatch_ms', 0.0) \
                        + (_time.perf_counter() - t_disp0) * 1e3
            except Exception:
                # the ring may have been DONATED into the failed
                # dispatch — its buffers may be consumed. Rebuild ring
                # + session table (every session restarts from zero
                # state, the eviction semantics) instead of leaving
                # self._store on deleted arrays, where ONE transient
                # device error would brick every later decode.
                self._reset_ring()
                _tele.counter('serve.errors').inc()
                self.logger.warning(
                    'decode step failed — session state ring reset '
                    '(all sessions restart from zero state)')
                raise
            self._store = list(store)
            _tele.counter('serve.decode_steps').inc()
        t_fetch0 = _time.perf_counter()
        outs = [np.asarray(p)[:rows] for p in payload]
        if timings is not None:
            timings['fetch_ms'] = timings.get('fetch_ms', 0.0) \
                + (_time.perf_counter() - t_fetch0) * 1e3
        return outs

    def warmup(self):
        """Compile every bucket's step program (against throwaway
        sessions, dropped afterwards so the table starts empty)."""
        for b in self.buckets:
            sids = ['__warmup_%d_%d' % (b, i) for i in range(b)]
            tokens = [np.zeros((b,) + tuple(self._descs[n].shape[1:]),
                               self._desc_dtype(n))
                      for n in self._token_names]
            self.decode(sids, tokens)
            for s in sids:
                self.cache.drop(s)
        self.logger.info('decode engine %s: %d step programs warm '
                         '(buckets %s, %d sessions)',
                         self.name, len(self.buckets), self.buckets,
                         self.capacity)
        return len(self.buckets)
