"""Inference serving plane: continuous batching over the fused-eval path.

The north star says the framework "serves heavy traffic from millions
of users"; everything before this package could train, observe and
survive, but there was no path from a checkpoint to a request/response
loop. This subsystem is that path, built almost entirely from pieces
that already exist:

- :mod:`.engine` — :class:`ServingEngine`: load a checkpoint via
  ``Module.load``, bind for inference, and pre-compile a ladder of
  bucketed batch shapes (pad-to-bucket, powers of two up to
  ``MXTPU_SERVE_MAX_BATCH``). Programs register through
  ``telemetry/programs.register`` and are signature-cached, so
  steady-state serving does ZERO recompiles — assertable via the
  existing ``xla.compiles`` counter;
- :mod:`.batcher` — :class:`DynamicBatcher`: a thread-safe request
  queue that coalesces waiting requests up to the largest warm bucket
  or ``MXTPU_SERVE_MAX_WAIT_MS`` (whichever first), dispatches one
  padded device call, and splits/strips pad rows back per request.
  Continuous, not lockstep: the device fetch runs on a side thread
  (the ``window_pipeline`` pipelined-upload pattern), so new arrivals
  board the next dispatch while the current one is in flight;
- :mod:`.step_cache` — :class:`StepCache` / :class:`DecodeEngine`: an
  O(1) carried-state decode step for recurrent (rnn/lstm) graphs —
  per-session hidden state lives in a device-resident ring (LRU
  evicted), so autoregressive serving dispatches one fixed-shape step
  program per token instead of re-running the prefix
  (arXiv:2603.09555);
- :mod:`.http` — ``/predict`` + ``/models`` on the same
  ThreadingHTTPServer pattern as ``telemetry/serve.py``, fronted by
  ``tools/serve_model.py``.

Observability comes for free: ``serve.request_latency`` histograms
(p50 via the registry ring, p99 published as the
``serve.request_latency_p99_ms`` gauge), ``serve.queue_depth`` /
``serve.batch_size`` / ``serve.pad_fraction`` gauges and
``serve.requests`` / ``serve.errors`` counters all flow through the
existing telemetry registry onto ``/metrics`` (docs/serving.md).
"""
from .engine import ServingEngine
from .batcher import DynamicBatcher
from .step_cache import StepCache, DecodeEngine

__all__ = ['ServingEngine', 'DynamicBatcher', 'StepCache', 'DecodeEngine']
