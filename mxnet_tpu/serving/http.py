"""HTTP frontend for the serving plane: /predict, /models, /metrics.

Same transport discipline as ``telemetry/serve.py`` (stdlib
ThreadingHTTPServer on a daemon thread, loopback bind by default via
``MXTPU_SERVE_BIND``, handler errors answer 5xx instead of killing the
process) — but where the telemetry endpoint only READS state, this one
does the actual work: every ``POST /predict`` submits into the
:class:`~.batcher.DynamicBatcher`, so concurrent HTTP clients coalesce
into shared padded device dispatches automatically (each handler runs
on its own thread; the batcher queue is the meeting point).

Endpoints:

- ``POST /predict`` — body either JSON (``{"data": [[...], ...]}`` for
  the single-input case, or ``{"inputs": {"<name>": [[...]], ...}}``)
  or a raw ``.npy`` payload (Content-Type ``application/x-npy`` or
  ``application/octet-stream``, single input). Answers JSON
  ``{"outputs": [...], "rows": N}`` with one nested list per graph
  output, pad rows already stripped — or, with ``Accept:
  application/x-npy``, the FIRST graph output as a raw ``.npy`` body
  (headers ``X-Rows`` and ``X-Outputs`` carry the row/output counts),
  so an npy-in client round-trips without JSON re-encoding. A
  client-supplied ``X-Request-Id`` (or W3C ``traceparent``) becomes
  the request's trace id (telemetry/trace.py) and is echoed back as
  ``X-Request-Id``; with telemetry on and no client id, a minted id is
  echoed instead — either way the id names the request's ``trace``
  JSONL record;
- ``GET /models`` — the engine description (name, bucket ladder,
  input/output signature, warm state);
- ``GET /metrics`` — Prometheus text exposition of the telemetry
  registry (``telemetry/serve.py``'s renderer), so the ``serve.*``
  family is scrapeable from the serving port even when the telemetry
  endpoint is off;
- ``GET /healthz`` — a small JSON digest (requests served, queue
  depth, SLO state): 200 while healthy, 503 with status
  ``slo_degraded`` while the SLO plane (telemetry/slo.py) reports the
  error budget burning — the load balancer probe.
"""
import io
import json
import logging
import threading

import numpy as np

__all__ = ['start_server', 'ServingServer']

_NPY_TYPES = ('application/x-npy', 'application/octet-stream')


def _bind_address():
    from ..config import flags
    try:
        flags.reload('MXTPU_SERVE_BIND')
        addr = flags.get('MXTPU_SERVE_BIND')
    except Exception:  # noqa: BLE001 — stripped builds without the flag
        addr = '127.0.0.1'
    if addr is None:
        return '127.0.0.1'
    addr = addr.strip()
    return '' if addr == '0.0.0.0' else addr


def _parse_predict_body(body, ctype, data_names):
    """The request's input arrays, in the engine's data-name order."""
    if (ctype or '').split(';', 1)[0].strip().lower() in _NPY_TYPES:
        return [np.load(io.BytesIO(body), allow_pickle=False)]
    payload = json.loads(body.decode('utf-8'))
    if not isinstance(payload, dict):
        raise ValueError('JSON body must be an object')
    if 'inputs' in payload:
        inputs = payload['inputs']
        missing = [n for n in data_names if n not in inputs]
        if missing:
            raise ValueError('missing inputs: %s' % missing)
        return [np.asarray(inputs[n]) for n in data_names]
    if 'data' in payload:
        if len(data_names) != 1:
            raise ValueError('model takes %d inputs (%s) — use the '
                             '"inputs" form'
                             % (len(data_names), ', '.join(data_names)))
        return [np.asarray(payload['data'])]
    raise ValueError('JSON body needs a "data" or "inputs" key')


class ServingServer:
    """One engine + batcher behind a ThreadingHTTPServer."""

    def __init__(self, engine, batcher, logger=logging):
        self.engine = engine
        self.batcher = batcher
        self.logger = logger
        self._server = None
        self._thread = None

    # -- request handling (pure-ish: tested without sockets too) -----------
    def predict_arrays(self, body, ctype, trace_id=None):
        """(code, output-arrays-or-error-dict): the parse + batcher
        round, shared by the JSON and npy answer paths. Client-side
        rejects answer 400 (counted, but NOT against the SLO error
        budget — the service was fine); server-side failures propagate
        to the handler's 500 (and the batcher already charged them to
        the budget)."""
        from .. import telemetry as _tele
        try:
            arrays = _parse_predict_body(body, ctype,
                                         self.engine._data_names)
            outs = self.batcher.predict(arrays, trace_id=trace_id)
        except (ValueError, json.JSONDecodeError) as e:
            _tele.counter('serve.errors').inc()
            return 400, {'error': str(e)}
        return 200, outs

    def predict_payload(self, body, ctype, trace_id=None):
        code, res = self.predict_arrays(body, ctype, trace_id=trace_id)
        if code != 200:
            return code, res
        payload = {'outputs': [o.tolist() for o in res],
                   'rows': int(res[0].shape[0])}
        if trace_id:
            payload['trace_id'] = trace_id
        return 200, payload

    def healthz_payload(self):
        from .. import telemetry as _tele
        from ..telemetry import slo as _slo
        snap = _tele.snapshot() if _tele.enabled() else {}
        c = snap.get('counters', {})
        g = snap.get('gauges', {})
        slo_bad = _slo.degraded()
        body = {'status': 'slo_degraded' if slo_bad is not None
                else 'ok',
                'model': self.engine.name,
                'warmed': bool(self.engine.warmed),
                'requests': int(c.get('serve.requests', 0)),
                'errors': int(c.get('serve.errors', 0)),
                'queue_depth': int(g.get('serve.queue_depth', 0) or 0)}
        slo_snap = _slo.snapshot_slo()
        if slo_snap is not None:
            body['slo'] = slo_snap
        return body

    def _make_handler(self):
        from http.server import BaseHTTPRequestHandler
        outer = self

        class Handler(BaseHTTPRequestHandler):
            server_version = 'mxtpu-serving'

            def log_message(self, fmt, *args):
                logging.debug('serving.http: ' + fmt, *args)

            def _send(self, code, body, ctype='application/json',
                      headers=None):
                data = body if isinstance(body, bytes) \
                    else body.encode('utf-8')
                self.send_response(code)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _guarded(self, fn):
                try:
                    fn()
                except Exception as e:  # noqa: BLE001 — a request must
                    logging.debug('serving.http: handler failed: %s', e)
                    try:                # not kill the server
                        self._send(500, json.dumps(
                            {'error': 'internal error'}) + '\n')
                    except Exception:  # noqa: BLE001
                        pass

            def do_GET(self):
                path = self.path.split('?', 1)[0].rstrip('/') or '/'

                def run():
                    if path == '/models':
                        self._send(200, json.dumps(
                            {'models': [outer.engine.describe()]},
                            indent=2) + '\n')
                    elif path == '/metrics':
                        from .. import telemetry as _tele
                        from ..telemetry import serve as _tserve
                        from ..telemetry import cluster as _cluster
                        body = _tserve.render_prometheus(
                            _tele.snapshot(),
                            host=_cluster.host_index())
                        self._send(200, body, _tserve._CONTENT_PROM)
                    elif path == '/healthz':
                        payload = outer.healthz_payload()
                        self._send(200 if payload['status'] == 'ok'
                                   else 503,
                                   json.dumps(payload, indent=2) + '\n')
                    elif path == '/':
                        self._send(200, 'mxnet_tpu serving endpoints: '
                                   'POST /predict, GET /models /metrics '
                                   '/healthz\n', 'text/plain')
                    else:
                        self._send(404, json.dumps(
                            {'error': 'not found'}) + '\n')
                self._guarded(run)

            def do_POST(self):
                path = self.path.split('?', 1)[0].rstrip('/')

                def run():
                    if path != '/predict':
                        self._send(404, json.dumps(
                            {'error': 'not found'}) + '\n')
                        return
                    from ..telemetry import trace as _trace
                    n = int(self.headers.get('Content-Length') or 0)
                    body = self.rfile.read(n)
                    # the client's X-Request-Id / traceparent names the
                    # request end to end; with telemetry on and no
                    # client id, mint one so the echoed header still
                    # links to the trace JSONL record
                    trace_id = _trace.from_headers(self.headers) \
                        or (_trace.new_trace_id() if _trace.enabled()
                            else None)
                    hdrs = {'X-Request-Id': trace_id} if trace_id \
                        else None
                    accept = (self.headers.get('Accept') or '') \
                        .split(';', 1)[0].strip().lower()
                    if accept in _NPY_TYPES:
                        code, res = outer.predict_arrays(
                            body, self.headers.get('Content-Type'),
                            trace_id=trace_id)
                        if code != 200:
                            self._send(code, json.dumps(res) + '\n',
                                       headers=hdrs)
                            return
                        buf = io.BytesIO()
                        np.save(buf, res[0], allow_pickle=False)
                        hdrs = dict(hdrs or {})
                        hdrs['X-Rows'] = str(int(res[0].shape[0]))
                        hdrs['X-Outputs'] = str(len(res))
                        self._send(200, buf.getvalue(),
                                   'application/x-npy', headers=hdrs)
                        return
                    code, payload = outer.predict_payload(
                        body, self.headers.get('Content-Type'),
                        trace_id=trace_id)
                    self._send(code, json.dumps(payload) + '\n',
                               headers=hdrs)
                self._guarded(run)

        return Handler

    # -- lifecycle ---------------------------------------------------------
    def start(self, port=0):
        """Bind (``port=0`` = OS-assigned ephemeral) and serve on a
        daemon thread; also starts the batcher. Returns the bound
        port."""
        from http.server import ThreadingHTTPServer
        assert self._server is None, 'already started'
        self.batcher.start()
        self._server = ThreadingHTTPServer((_bind_address(), int(port)),
                                           self._make_handler())
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name='mxtpu-serving-http',
                                        daemon=True)
        self._thread.start()
        bound = self._server.server_address[1]
        self.logger.info('serving %s on :%d (POST /predict, GET /models '
                         '/metrics /healthz)', self.engine.name, bound)
        return bound

    @property
    def port(self):
        return self._server.server_address[1] if self._server else None

    def stop(self):
        srv, th = self._server, self._thread
        self._server = self._thread = None
        if srv is not None:
            try:
                srv.shutdown()
                srv.server_close()
            except Exception:  # noqa: BLE001
                pass
        if th is not None:
            th.join(timeout=5)
        self.batcher.close()


def start_server(engine, batcher=None, port=0, logger=logging):
    """Engine (+ optional pre-built batcher) -> running ServingServer.
    Returns the server; read the bound port off ``server.port``."""
    from .batcher import DynamicBatcher
    server = ServingServer(engine, batcher or DynamicBatcher(engine),
                           logger=logger)
    server.start(port)
    return server
