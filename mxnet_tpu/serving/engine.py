"""Serving engine: checkpoint -> pre-compiled bucketed forward programs.

A production endpoint cannot pay a 20-40s XLA compile mid-request, and
it cannot compile one program per observed batch size either — request
sizes are arbitrary. The standard resolution (and this engine's core)
is a BUCKET LADDER: forward programs are compiled once per power-of-two
batch size up to ``MXTPU_SERVE_MAX_BATCH``, every request pads up to
the smallest covering bucket, and pad rows are stripped from the
outputs before they leave the engine. After :meth:`ServingEngine.warmup`
the steady state performs zero compiles — each program registers
through ``telemetry/programs.register``, so the existing
``xla.compiles`` counter is the proof (asserted in
tests/unittest/test_serving.py), and ``MXTPU_COMPILE_CACHE`` makes even
the warmup itself warm across restarts.

The forward program is the read-only single-step twin of
``module/fused_eval.py``'s window body: the bound executor's
``_run_eager`` traced over (params, aux, data, key) with
``is_train=False``, exactly the math ``Module.predict`` runs — a
full-bucket request answers bit-identically to ``Module.predict`` at
the same batch size. Pad rows never influence real rows (the graph is
per-example at inference: BatchNorm uses moving stats), and they are
sliced off on axis 0 exactly where the reference predict slices pad.
"""
import logging
import threading

import numpy as np

import jax

from .. import random as _random
from .. import telemetry as _tele

__all__ = ['ServingEngine', 'bucket_ladder']


def bucket_ladder(max_batch):
    """Powers of two up to ``max_batch`` (inclusive when it is one,
    appended when it is not), ascending — the warm shapes the engine
    compiles and the batcher coalesces toward."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError('max_batch must be >= 1, got %d' % max_batch)
    ladder = []
    b = 1
    while b <= max_batch:
        ladder.append(b)
        b *= 2
    if ladder[-1] != max_batch:
        ladder.append(max_batch)
    return ladder


def _serve_max_batch():
    from ..config import flags
    flags.reload('MXTPU_SERVE_MAX_BATCH')
    return flags.get('MXTPU_SERVE_MAX_BATCH')


class _SingleExecutorEngine:
    """Shared plumbing of the serving engines (:class:`ServingEngine`
    and step_cache's :class:`~.step_cache.DecodeEngine`): module
    eligibility validation, the per-bucket program cache, the cached
    param/aux snapshot (mesh-replicated on SPMD), and host->device
    placement. The eligibility set mirrors fused-eval's, but serving
    RAISES instead of falling back — an engine that silently
    recompiled per shape would violate the latency contract it exists
    for."""

    _default_name = 'model'

    def __init__(self, module, logger=logging, name=None):
        from ..module.module import Module
        from ..module.executor_group import SPMDExecutorGroup
        cls = type(self).__name__
        if type(module) is not Module:
            raise ValueError('%s needs a plain Module, got %s'
                             % (cls, type(module).__name__))
        assert module.binded and module.params_initialized, \
            'bind the module (for_training=False) and load params first'
        eg = module._exec_group
        execs = getattr(eg, 'execs', ())
        if len(execs) != 1:
            raise ValueError('%s needs a single-executor module (one '
                             'context, or an SPMD group)' % cls)
        e = execs[0]
        if e._use_staged() or e._monitor is not None:
            raise ValueError('%s cannot serve a staged/monitored module'
                             % cls)
        self.module = module
        self._exec = e
        self._run = e._run_eager
        self._arg_names = list(e._prog.arg_names)
        self._aux_names = list(e._prog.aux_names)
        self._mesh = eg.mesh if isinstance(eg, SPMDExecutorGroup) else None
        self._descs = {d.name: d for d in module.data_shapes}
        from ..telemetry.programs import scope_name
        self.name = name or scope_name(
            getattr(module._symbol, 'name', None) or self._default_name)
        self._programs = {}        # bucket -> (program, fixed_names)
        self._snap = None          # cached (fixed, aux) param snapshot
        self._snap_lock = threading.Lock()
        self.logger = logger

    def _program(self, bucket):
        entry = self._programs.get(bucket)
        if entry is None:
            with _tele.span('serve.build', 'serve'):
                entry = self._build_program(bucket)
            self._programs[bucket] = entry
        return entry

    def _snapshot(self, fixed_names):
        """Param/aux arrays in program order, cached — serving params
        are immutable between :meth:`refresh_params` calls, so the
        snapshot (and any SPMD re-placement) is paid once, not per
        request."""
        with self._snap_lock:
            if self._snap is None:
                e = self._exec
                fixed = tuple(e.arg_dict[n]._data for n in fixed_names)
                aux = tuple(e.aux_dict[n]._data for n in self._aux_names)
                if self._mesh is not None:
                    from ..module.window_pipeline import place_replicated
                    fixed, aux = place_replicated(self._mesh, fixed, aux)
                self._snap = (fixed, aux)
            return self._snap

    def refresh_params(self):
        """Drop the cached param snapshot (after set_params / a hot
        reload); the next dispatch re-reads the executor's arrays.
        Programs stay warm — the signature (shape/dtype/sharding) is
        unchanged, so no recompile happens."""
        with self._snap_lock:
            self._snap = None

    def _place(self, stack):
        if self._mesh is None:
            return jax.device_put(stack, self._exec._ctx.jax_device())
        # replicated on the mesh: buckets smaller than dp need not
        # divide, and the per-example forward is correct either way
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(stack, NamedSharding(self._mesh, P()))

    def _desc_dtype(self, n):
        return getattr(self._descs[n], 'dtype', None) or np.float32


class ServingEngine(_SingleExecutorEngine):
    """Bucketed, pre-compilable inference over one bound Module.

    The module must be plain (single executor, not staged, no monitor)
    and bound ``for_training=False`` at the largest bucket's batch
    size with parameters loaded.
    """

    def __init__(self, module, max_batch=None, logger=logging, name=None):
        super().__init__(module, logger=logger, name=name)
        self._data_names = list(module._data_names)
        self.max_batch = int(max_batch) if max_batch else _serve_max_batch()
        self.buckets = bucket_ladder(self.max_batch)
        self.output_names = list(module._output_names)
        self.warmed = False

    # -- checkpoint -> engine ----------------------------------------------
    @classmethod
    def from_checkpoint(cls, prefix, epoch, data_shapes, context=None,
                        max_batch=None, logger=logging, **module_kwargs):
        """``Module.load`` + inference bind + engine in one step.

        ``data_shapes``: [(name, per_example_shape)] WITHOUT the batch
        dimension — the engine owns batching. Label variables a
        training graph carries (e.g. ``softmax_label``) are bound as
        plain zero arrays, exactly like a predict-bound module
        (``label_names=[]``); the ``is_train=False`` forward never
        reads them."""
        from .. import context as ctx_mod
        from ..module.module import Module
        data_shapes = [(n, tuple(s)) for n, s in data_shapes]
        max_b = int(max_batch) if max_batch else _serve_max_batch()
        mod = Module.load(prefix, epoch,
                          data_names=[n for n, _ in data_shapes],
                          label_names=[], context=context or ctx_mod.cpu(),
                          logger=logger, **module_kwargs)
        mod.bind(data_shapes=[(n, (max_b,) + s) for n, s in data_shapes],
                 for_training=False)
        return cls(mod, max_batch=max_b, logger=logger)

    # -- programs ----------------------------------------------------------
    def _build_program(self, bucket):
        run = self._run
        arg_pos = {n: i for i, n in enumerate(self._arg_names)}
        data_names = self._data_names
        io_pos = set(arg_pos[n] for n in data_names)
        fixed_names = [n for i, n in enumerate(self._arg_names)
                       if i not in io_pos]

        def fwd(fixed, aux, datas, key):
            full = [None] * len(arg_pos)
            for n, v in zip(fixed_names, fixed):
                full[arg_pos[n]] = v
            for n, v in zip(data_names, datas):
                full[arg_pos[n]] = v
            outs, _ = run(tuple(full), aux, key, False)
            return outs

        from ..module.window_pipeline import registered_jit
        prog = registered_jit('serve.predict[%s][b%d]' % (self.name, bucket),
                              fwd)
        return prog, fixed_names

    def bucket_for(self, rows):
        """Smallest warm bucket covering ``rows`` (chunk first when
        rows exceed the largest bucket)."""
        for b in self.buckets:
            if b >= rows:
                return b
        raise ValueError('rows=%d exceeds the largest bucket %d — '
                         'chunk via dispatch_rows()' % (rows,
                                                        self.buckets[-1]))

    # -- dispatch ----------------------------------------------------------
    def _check_and_cast(self, arrays):
        if not isinstance(arrays, (list, tuple)):
            arrays = [arrays]
        if len(arrays) != len(self._data_names):
            raise ValueError('expected %d input arrays (%s), got %d'
                             % (len(self._data_names),
                                ', '.join(self._data_names), len(arrays)))
        out = []
        for n, a in zip(self._data_names, arrays):
            desc = self._descs[n]
            a = np.asarray(a, dtype=self._desc_dtype(n))
            want = tuple(desc.shape[1:])
            if tuple(a.shape[1:]) != want:
                raise ValueError('input %r: per-example shape %s does not '
                                 'match the bound %s'
                                 % (n, tuple(a.shape[1:]), want))
            out.append(a)
        rows = out[0].shape[0]
        if rows == 0:
            raise ValueError('empty request (0 rows)')
        if any(a.shape[0] != rows for a in out):
            raise ValueError('input arrays disagree on the row count')
        return out, rows

    def _dispatch_chunk(self, arrays, rows, timings=None):
        import time as _time
        bucket = self.bucket_for(rows)
        prog, fixed_names = self._program(bucket)
        fixed, aux = self._snapshot(fixed_names)
        t0 = _time.perf_counter()
        padded = []
        for a in arrays:
            if rows < bucket:
                a = np.concatenate(
                    [a, np.zeros((bucket - rows,) + a.shape[1:], a.dtype)])
            # device_put takes the host array directly — one transfer,
            # not a default-device stage + re-place
            padded.append(self._place(a))
        t1 = _time.perf_counter()
        _tele.histogram('serve.pad').observe((t1 - t0) * 1e3)
        with _tele.span('serve.dispatch', 'serve'):
            pieces = prog(fixed, aux, tuple(padded), _random.next_key())
        if timings is not None:
            timings['pad_ms'] = timings.get('pad_ms', 0.0) \
                + (t1 - t0) * 1e3
            timings['dispatch_ms'] = timings.get('dispatch_ms', 0.0) \
                + (_time.perf_counter() - t1) * 1e3
        return pieces, rows, bucket

    def dispatch_rows(self, arrays, timings=None):
        """Asynchronously dispatch ``arrays`` (row counts beyond the
        largest bucket are chunked across several device calls).
        Returns a list of (device_outputs, rows, bucket) chunks —
        device compute proceeds while the caller does host work; hand
        the chunks to :meth:`fetch_chunks` for the one blocking
        device->host fetch. ``timings`` (a dict, optional) accumulates
        the host-measured ``pad_ms`` / ``dispatch_ms`` for the caller's
        request-trace breakdown."""
        arrays, rows = self._check_and_cast(arrays)
        chunks = []
        off = 0
        while off < rows:
            take = min(rows - off, self.buckets[-1])
            chunks.append(self._dispatch_chunk(
                [a[off:off + take] for a in arrays], take,
                timings=timings))
            off += take
        return chunks

    def fetch_chunks(self, chunks, timings=None):
        """Fetch + pad-strip the chunks of one :meth:`dispatch_rows`
        call back into host arrays: one np list per output, rows in
        request order, pad rows sliced off axis 0 exactly where
        ``Module.predict`` slices the iterator pad. ``timings``
        accumulates the blocking ``fetch_ms``."""
        import time as _time
        per_out = None
        t0 = _time.perf_counter()
        with _tele.span('serve.fetch', 'serve'):
            for pieces, rows, _bucket in chunks:
                host = [np.asarray(o)[:rows] for o in pieces]
                if per_out is None:
                    per_out = [[h] for h in host]
                else:
                    for acc, h in zip(per_out, host):
                        acc.append(h)
        if timings is not None:
            timings['fetch_ms'] = timings.get('fetch_ms', 0.0) \
                + (_time.perf_counter() - t0) * 1e3
        return [np.concatenate(parts) if len(parts) > 1 else parts[0]
                for parts in per_out]

    def infer(self, arrays):
        """Synchronous predict: pad-to-bucket, dispatch, strip. Returns
        the list of output arrays (len == number of graph outputs),
        each with exactly the request's row count."""
        return self.fetch_chunks(self.dispatch_rows(arrays))

    # -- warmup ------------------------------------------------------------
    def warmup(self, buckets=None):
        """Compile (or load from ``MXTPU_COMPILE_CACHE``) every bucket's
        program and run each once, so the serving steady state performs
        zero compiles — the `xla.compiles` counter is flat afterwards.
        Returns the number of programs warmed."""
        warmed = 0
        for b in (buckets or self.buckets):
            zeros = []
            for n in self._data_names:
                desc = self._descs[n]
                zeros.append(np.zeros((b,) + tuple(desc.shape[1:]),
                                      dtype=self._desc_dtype(n)))
            chunk = self._dispatch_chunk(zeros, b)
            self.fetch_chunks([chunk])     # block: the compile is done
            warmed += 1
        self.warmed = True
        _tele.gauge('serve.buckets_warm').set(warmed)
        self.logger.info('serving engine %s: %d bucket programs warm '
                         '(ladder %s)', self.name, warmed, self.buckets)
        return warmed

    def describe(self):
        """The /models payload for this engine."""
        return {
            'name': self.name,
            'buckets': list(self.buckets),
            'max_batch': self.max_batch,
            'inputs': [{'name': n,
                        'shape': list(self._descs[n].shape[1:]),
                        'dtype': str(np.dtype(self._desc_dtype(n)))}
                       for n in self._data_names],
            'outputs': list(self.output_names),
            'warmed': bool(self.warmed),
        }
