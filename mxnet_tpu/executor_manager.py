"""Legacy executor manager (reference python/mxnet/executor_manager.py).

Thin shim over module.executor_group — kept for API completeness; new code
should use Module.
"""
import logging

from .module.executor_group import DataParallelExecutorGroup
from .io import DataDesc

__all__ = ['DataParallelExecutorManager', '_split_input_slice']

import numpy as np


def _split_input_slice(batch_size, work_load_list):
    """Reference executor_manager.py:31."""
    total_work_load = sum(work_load_list)
    batch_num_list = [round(work_load * batch_size / total_work_load)
                      for work_load in work_load_list]
    batch_num_sum = sum(batch_num_list)
    if batch_num_sum < batch_size:
        batch_num_list[-1] += batch_size - batch_num_sum
    slices = []
    end = 0
    for batch_num in batch_num_list:
        begin = int(min(end, batch_size))
        end = int(min(begin + batch_num, batch_size))
        if begin >= end:
            raise ValueError('Too many slices. Some splits are empty.')
        slices.append(slice(begin, end))
    return slices


class DataParallelExecutorManager:
    """Reference executor_manager.py:200 — legacy Module predecessor."""

    def __init__(self, symbol, ctx, train_data, arg_names, param_names,
                 aux_names, work_load_list=None, logger=None, sym_gen=None):
        if logger is None:
            logger = logging
        self.arg_names = arg_names
        self.param_names = param_names
        self.aux_names = aux_names
        self.ctx = ctx
        data_shapes = [DataDesc(name, shape) for name, shape in
                       train_data.provide_data]
        label_shapes = [DataDesc(name, shape) for name, shape in
                        train_data.provide_label]
        self.execgrp = DataParallelExecutorGroup(
            symbol, ctx, work_load_list, data_shapes, label_shapes,
            param_names, for_training=True, inputs_need_grad=False)
        self.symbol = symbol
        self.sym_gen = sym_gen

    def install_monitor(self, monitor):
        self.execgrp.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        self.execgrp.get_params(arg_params, aux_params)

    @property
    def param_arrays(self):
        return self.execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.execgrp.aux_arrays

    def load_data_batch(self, data_batch):
        self._cur_batch = data_batch

    def forward(self, is_train=False):
        self.execgrp.forward(self._cur_batch, is_train=is_train)

    def backward(self):
        self.execgrp.backward()

    def update_metric(self, metric, labels):
        self.execgrp.update_metric(metric, labels)
