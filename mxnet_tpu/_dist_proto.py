"""Wire protocol for the distributed KVStore (worker/server/scheduler).

Reference: ps-lite's ZMQ transport as used by src/kvstore/kvstore_dist.h:52
and kvstore_dist_server.h:109. The reference ships messages over ZeroMQ with
zero-copy SArrays; here the transport is length-prefixed pickled tuples over
TCP sockets — tensors travel as (shape, dtype, raw bytes) triples so the
payload is a single contiguous buffer either way.

Env protocol (reference include/mxnet/kvstore.h:244-301, tools/launch.py):
DMLC_ROLE in {worker, server, scheduler}; DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT
locate the scheduler; DMLC_NUM_WORKER / DMLC_NUM_SERVER size the cluster.
"""
import pickle
import socket
import struct

import numpy as np

_LEN = struct.Struct('>Q')


def send_msg(sock, obj):
    """Length-prefixed pickle. One writer per socket at a time."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock):
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    return pickle.loads(payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def pack_array(arr):
    arr = np.ascontiguousarray(arr)
    return (arr.shape, arr.dtype.str, arr.tobytes())


def unpack_array(triple):
    shape, dtype, raw = triple
    return np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape).copy()


def connect(host, port, timeout=60.0):
    deadline = __import__('time').monotonic() + timeout
    last = None
    while True:
        try:
            sock = socket.create_connection((host, int(port)), timeout=5.0)
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except OSError as e:
            last = e
            if __import__('time').monotonic() > deadline:
                raise ConnectionError(
                    'cannot reach %s:%s after %.0fs: %s'
                    % (host, port, timeout, last))
            __import__('time').sleep(0.2)


def listener(host='0.0.0.0', port=0):
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(128)
    return srv, srv.getsockname()[1]
