"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capabilities.

From-scratch rebuild of Apache MXNet 0.11.1's API surface and semantics
(reference at /root/reference) on a JAX/XLA/Pallas execution model: eager
NDArray ops dispatch through cached jit closures, Symbol.bind compiles whole
graphs into single XLA computations, KVStore lowers to mesh collectives.
See SURVEY.md for the layer map this follows.
"""
__version__ = '0.1.0'

from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus
from . import ndarray
from . import ndarray as nd
from . import random
from .random import seed  # noqa: F401
from . import autograd
from . import engine
